"""The online-learning supervisor: serve -> retrain -> delta-export -> swap.

Monolith (§3.3) keeps CTR models fresh by feeding served traffic back into
training and streaming parameter deltas to the serving fleet; torchrec's
streaming-retrain loop is the same shape.  This module closes that loop for
this repo: it tails the frontend's request log through the crash-safe
``ReplayConsumer`` (``data/replay.py``), trains ``steps_per_cycle``
incremental steps, persists the replay cursor as a checkpoint sidecar,
exports a delta bundle (``serve/export.py:export_delta``), publishes it to
the ``BundleStore`` and hot-swaps the in-process ``MicroBatcher`` — forever,
or until the log drains / ``max_cycles``.

Crash-safety is a single-durability-point design.  Each cycle runs stages

    replay -> train -> checkpoint -> export -> publish -> swap

and the CHECKPOINT is the only commit: state and replay cursor land
atomically in one ``CheckpointManager.save`` (plus a ``target_version``
claim for the store).  A kill before the checkpoint discards the cycle —
the restart re-reads the same records from the last durable cursor and
retrains them onto the matching restored state, so each record contributes
to the state lineage exactly once.  A kill after the checkpoint but before
the store caught up is repaired by ``_catch_up`` at startup: the store head
still names a version below ``target_version``, so the supervisor re-exports
the (deterministic) delta from the head to the checkpointed state and
publishes it before entering the loop.  Either way "restart the same
command" converges to the uninterrupted run's bundle, bit for bit — the
property ``tests/test_online.py`` asserts with real ``os._exit`` kills at
every stage boundary (``[faults] kill_between_stages`` /
``kill_during_replay`` / ``kill_during_swap``).

Stage boundaries consult ``FaultInjector.maybe_kill_stage`` so the kill
matrix is deterministic, and every cycle logs an ``online_cycle`` record —
consumed ``(seq, row_start, row_end)`` spans plus the ``replay/*`` counters
— through the trainer's ``metrics.jsonl`` (PR-7 telemetry path), which is
the record-id accounting the no-dup/no-loss test audits.

The GATED mode (``[online] canary_cycles > 0``, requires a multi-replica
``[serving] replicas`` fleet) puts a canary gatekeeper between training
and serving, the deployment discipline Monolith §3.3 describes for its
online models.  Cycle stages become

    replay -> train -> export -> publish -> canary -> verdict -> commit -> swap

with the VERDICT CHECKPOINT as the single durability point: (1) a shadow
slice of held-out replayed traffic (``ReplayConsumer.peek_batches`` —
rows PAST the committed cursor, which train only in a LATER cycle, i.e.
progressive validation) scores every candidate against the incumbent
before any pointer moves, refusing on AUC regression beyond ``[online]
max_auc_regression``; (2) survivors publish under the ``CANARY`` pointer,
picked up by only the first ``canary_fraction`` of the
``serve/fleet.ServingFleet`` replicas; (3) ``canary_cycles`` watch rounds
compare per-replica held-out-AUC heartbeats (latency recorded alongside)
canary-vs-stable — training/serving skew that byte-perfect bundles can't
reveal shows up here; (4) promote moves ``CURRENT`` and rollback deletes
the candidate, records it in ``rejections.json`` and digest-verifies that
every replica converges bitwise back onto the last good version.  A
rejected cycle still advances the replay cursor and the durable
``cycles_done`` counter (consumed-but-discarded, recorded in metrics), so
a persistently bad stream cannot wedge the loop, and the trained state is
restored from the previous verdict checkpoint — version numbers are
REUSED by the next candidate, keeping the delta chain strictly parent+1.
A kill anywhere before the verdict checkpoint redoes the whole cycle
deterministically (same records, bit-identical retrain, identical delta
digest, idempotent ``publish_canary``); a kill after it is repaired by
``_catch_up_gated`` replaying the recorded verdict onto the store.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.obs.aggregate import percentile as _percentile
from tdfo_tpu.utils import faults as _faults

__all__ = ["OnlineLoop", "online_from_config"]


def _stage(name: str) -> None:
    """A supervisor stage boundary: the deterministic kill-matrix hook.
    The named stage has NOT run yet when the injected kill fires."""
    inj = _faults.active()
    if inj is not None:
        inj.maybe_kill_stage(name)


class _StageTrace:
    """Per-cycle stage timer: ``mark(name)`` closes the previous stage's
    trace span and opens the next, so the assembled timeline gets a
    wall-clock breakdown of every stage the cycle actually crossed.  A
    killed stage simply never closes — its partial time is lost with the
    cycle (which redoes entirely anyway)."""

    def __init__(self, cycle: int):
        self.cycle = int(cycle)
        self._name: str | None = None
        self._t0 = 0.0

    def mark(self, name: str) -> None:
        self.close()
        self._name, self._t0 = name, _trace.clock()

    def close(self) -> None:
        if self._name is not None:
            _trace.emit("online", "stage", cycle=self.cycle,
                        stage=self._name,
                        dur_ms=round(_trace.elapsed_ms(self._t0), 3))
            self._name = None


class OnlineLoop:
    """One supervisor process: trainer + replay consumer + bundle store +
    serving batcher, advancing in checkpointed cycles.

    Restricted to the DMP/sparse regime (DLRM, TwoTower with
    model_parallel, or Bert4Rec): delta export diffs embedding tables, and
    online freshness is an embedding-dominated problem (Monolith §3.3).
    The seq family (``model_kind == "seq"``) replays eval-window records
    (``seqs``/``cands``, no label column), maps each to a last-position
    masked-LM step, and judges shadow/canary scores by ``ranking_auc``
    over the candidate panels instead of the labelled ``binary_auc``.
    """

    def __init__(self, config, *, log_dir: str | Path | None = None):
        import jax

        from tdfo_tpu.data.replay import ReplayConsumer, make_replay_consumer
        from tdfo_tpu.serve.swap import BundleStore
        from tdfo_tpu.train.trainer import Trainer

        if not config.online.request_log:
            raise ValueError(
                "the online loop needs [online] request_log — the directory "
                "a serving frontend (serve --serving.log_features) wrote")
        from tdfo_tpu.core.config import serving_model_kind

        # rejects unknown models with the actionable family map; bert4rec
        # joins as the "seq" family (replayed candidate panels, ranking_auc
        # gates, label-free heartbeats)
        self.model_kind = serving_model_kind(config)
        if jax.process_count() > 1:
            raise ValueError(
                "the online supervisor is single-process (one serving "
                "replica owns its request log and bundle store)")
        if config.steps_per_execution > 1:
            raise ValueError(
                "online requires steps_per_execution = 1: cycles are short "
                "and the cursor commits per cycle, not per scan chunk")

        self.config = config
        self.trainer = Trainer(config, log_dir=log_dir)
        if not hasattr(self.trainer.state, "tables"):
            raise ValueError(
                "online requires the DMP/sparse regime (dlrm, or twotower "
                "with model_parallel) — delta export diffs embedding tables")
        if self.trainer._pipelined:
            raise ValueError(
                "online does not support train.pipeline_overlap: the "
                "checkpoint stage needs the cycle's updates flushed")
        if self.trainer._ckpt is None:
            raise ValueError("online requires checkpoint_dir")

        self.workdir = Path(config.checkpoint_dir)
        self.store = BundleStore(self.workdir / "bundle_store",
                                 keep_versions=config.serving.keep_versions)
        self.store.recover()  # half-published strays from a killed publish
        self.chain = self.workdir / "delta_chain"
        self.chain.mkdir(parents=True, exist_ok=True)
        self.gated = config.online.canary_cycles > 0

        # restore: state + replay cursor land together, so a resumed process
        # continues at the exact record the durable state has seen
        self.gstep = 0
        cursor: dict[str, Any] | None = None
        if self.trainer._ckpt.latest_step() is not None:
            self.gstep, self.trainer.state, cursor = self.trainer._ckpt.restore(
                self.trainer.state, stamps=self.trainer._ckpt_stamps)
        replay_cursor = (cursor or {}).get("replay")
        self._claimed_version = int((cursor or {}).get("target_version") or 0)
        self.cycles_done = int((cursor or {}).get("cycles_done") or 0)
        self._pending_canary = (cursor or {}).get("canary")

        mesh = self.trainer.mesh
        # a multi-replica fleet writes one request log per replica
        # (<root>/replica-<k>); the factory folds them into one
        # exactly-once stream keyed (replica_id, seq)
        consumer_cls = (make_replay_consumer if config.serving.replicas > 1
                        else ReplayConsumer)
        self.consumer = consumer_cls(
            config.online.request_log,
            schema=self.trainer._eval_schema,
            batch_size=config.per_device_train_batch_size
            * mesh.shape["data"],
            max_bad_records=config.online.max_bad_records,
            max_lag_records=config.online.max_lag_records,
            lag_policy=config.online.lag_policy,
            cursor=replay_cursor,
        )
        self._bootstrap_store()
        if self.gated and self.trainer._ckpt.latest_step() is None:
            # rollback anchor: gated cycle 1 needs a last-good state to
            # restore on rejection, so the pristine state is durable BEFORE
            # any gated training
            self.trainer._ckpt.save(
                0, self.trainer.state, force=True,
                cursor={"online": True, "global_step": 0, "cycles_done": 0,
                        "replay": self.consumer.cursor(),
                        "target_version":
                        int(self.store.current_version() or 0)},
                stamps=self.trainer._ckpt_stamps)
        if self.gated:
            self._catch_up_gated()
        else:
            self._catch_up()
        self.fleet = None
        if config.serving.fleet_mode == "process":
            # out-of-process fleet: each replica is a real OS process behind
            # the socket ingress; same duck-typed surface as ServingFleet,
            # but mark_canary_watch can deliver a REAL SIGKILL and sync()
            # respawns/reconnects the victims (serve/supervisor.py)
            from tdfo_tpu.serve.supervisor import ProcessFleet

            self.fleet = ProcessFleet(self.store, config,
                                      workdir=self.workdir,
                                      logger=self.trainer.logger)
            self.fleet.sync()
            self.batcher = None
        elif config.serving.replicas > 1:
            from tdfo_tpu.serve.fleet import ServingFleet

            self.fleet = ServingFleet(self.store, config, mesh=mesh,
                                      logger=self.trainer.logger)
            self.fleet.sync()
            self.batcher = None
        else:
            self.batcher = self._make_batcher()
        self.cycles = 0

    # ----------------------------------------------------------- store side

    def _export_kwargs(self) -> dict[str, Any]:
        cfg = self.config
        state = self.trainer.state
        if self.model_kind == "seq":
            # seq bundles carry no CTR columns; the manifest's seq block is
            # the backbone geometry the scorer rebuilds (and the drift key
            # export_delta refuses on)
            cat_cols: tuple[str, ...] = ()
            cont_cols: tuple[str, ...] = ()
            seq = {"max_len": cfg.max_len, "n_heads": cfg.n_heads,
                   "n_layers": cfg.n_layers}
        else:
            from tdfo_tpu.train.trainer import _ctr_columns

            cat_cols, cont_cols = _ctr_columns(cfg)
            seq = None
        return dict(
            model=cfg.model, embed_dim=cfg.embed_dim, cat_columns=cat_cols,
            cont_columns=cont_cols, size_map=cfg.size_map, step=self.gstep,
            coll=self.trainer.coll, tables=state.tables,
            dense_params=state.dense_params,
            mixed_precision=cfg.mixed_precision, seq=seq,
        )

    def _bootstrap_store(self) -> None:
        """First launch: publish the current state as full bundle v0 so every
        later cycle is a delta on a verified base.  Idempotent — a restart
        that finds a store head skips this entirely."""
        from tdfo_tpu.serve.export import export_bundle
        from tdfo_tpu.serve.swap import _version_name

        if self.store.current_version() is not None:
            return
        v0 = self.chain / _version_name(0)
        if v0.exists():
            shutil.rmtree(v0)  # crashed between export and ingest: redo
        export_bundle(v0, version=0, **self._export_kwargs())
        self.store.ingest_full(v0)

    def _publish_state(self, target: int) -> None:
        """Export the delta from the store head to the CURRENT trainer state
        and publish it as ``target``.  Deterministic and redoable: a stale
        half-exported directory is discarded and rebuilt from the same
        state, and the store refuses to regress versions."""
        from tdfo_tpu.serve.export import export_delta
        from tdfo_tpu.serve.swap import _version_name

        _stage("export")
        delta_dir = self.chain / _version_name(target)
        if delta_dir.exists():
            shutil.rmtree(delta_dir)
        export_delta(delta_dir, self.store.current_dir(),
                     **self._export_kwargs())
        _stage("publish")
        self.store.apply_delta(delta_dir)  # kill_during_swap fires in here

    def _catch_up(self) -> None:
        """Repair a kill between checkpoint and publish: the checkpoint
        claimed ``target_version`` but the store head is still behind it, so
        the durable state has never reached serving.  Re-export + publish
        before the loop — without this, a drained log would strand the last
        trained cycle in the checkpoint forever."""
        if self._claimed_version <= int(self.store.current_version() or 0):
            return
        self._publish_state(self._claimed_version)

    def _catch_up_gated(self) -> None:
        """Repair a kill between the gated VERDICT checkpoint and the store
        commit: the checkpoint records the verdict durably; the store-side
        promote/rollback replays idempotently here.  Identity is the
        verdict's ``(version, digest)`` pair — version numbers are reused
        after a rollback, so a LATER cycle's pending canary carrying the
        same number (different bytes) must not be judged by an old
        verdict.  The gated mode never runs the non-gated ``_catch_up``:
        a claimed-but-unpromoted version already exists as the canary
        directory, so the repair is a pointer move, not a re-export."""
        pc = self._pending_canary
        if not pc:
            return
        verdict = pc.get("verdict")
        if verdict == "promote":
            if int(self.store.current_version() or 0) < int(pc["version"]):
                self.store.promote_canary()
        elif verdict == "rollback":
            ptr = self.store._read_pointer("CANARY")
            if ptr is not None and (ptr["version"], ptr["digest"]) == (
                    int(pc["version"]), pc["digest"]):
                self.store.rollback_canary(
                    str(pc.get("reason") or "auto-rollback (replayed)"))
        # "rejected" never published — nothing on the store side to redo

    def _make_batcher(self):
        from tdfo_tpu.serve.frontend import MicroBatcher

        spec = self.config.serving
        scorer = self._build_scorer(self.store.current_dir())
        buckets = ((spec.history_buckets or spec.buckets)
                   if self.model_kind == "seq" else spec.buckets)
        return MicroBatcher(
            scorer.score, buckets=buckets, max_batch=spec.max_batch,
            batch_deadline_ms=spec.batch_deadline_ms,
            logger=self.trainer.logger,
            program_cache_size=scorer.score_cache_size,
            max_queue=spec.max_queue, shed_policy=spec.shed_policy,
        )

    def _build_scorer(self, bundle_dir):
        from tdfo_tpu.serve.export import load_bundle
        from tdfo_tpu.serve.scoring import make_scorer

        return make_scorer(load_bundle(bundle_dir), mesh=self.trainer.mesh)

    # ------------------------------------------------------------ the cycle

    def _seq_train_batch(self, batch: dict[str, np.ndarray]
                         ) -> dict[str, np.ndarray]:
        """Replayed eval windows -> one masked-LM training batch.  The
        request's ``seqs`` already carry the appended MASK at the last
        position (``serve/seq_scoring.py:history_window``); the label sheet
        supervises ONLY that position with the panel's positive (column 0,
        the torchrec eval convention) — online next-item fine-tuning through
        the SAME ``bert4rec_sparse_forward`` step as offline fit
        (``masked_ce_loss`` ignores the ``PAD_ID`` sheet)."""
        from tdfo_tpu.models.bert4rec import PAD_ID

        item = np.asarray(batch["seqs"], np.int32)
        label = np.full_like(item, PAD_ID)
        label[:, -1] = np.asarray(batch["cands"], np.int32)[:, 0]
        return {"item": item, "label": label}

    def _train_cycle(self, batches: list[dict[str, np.ndarray]]) -> float:
        """Run one incremental step per replay batch.  Same step program as
        offline fit — [online] adds no graph edits (jaxpr-pinned by
        tests/test_online.py), so serving-loop configs never recompile."""
        from jax.sharding import PartitionSpec as P

        from tdfo_tpu.data.loader import prefetch_to_mesh
        from tdfo_tpu.train.metrics import AUC

        if self.model_kind == "seq":
            batches = [self._seq_train_batch(b) for b in batches]
        trainer, loss = self.trainer, 0.0
        auc = AUC.empty() if trainer._train_auc_enabled else None
        for batch in prefetch_to_mesh(iter(batches), trainer.mesh, P("data")):
            if self.model_kind == "seq":
                # the bert4rec step signature (trainer.py fit loop): a fixed
                # dropout key folded with state.step — deterministic per
                # step, so rollback-restored state replays bit for bit
                out = trainer.train_step(trainer.state, batch,
                                         trainer._dropout_rng)
                trainer.state, step_loss = out[:2]
            else:
                out = trainer.train_step(trainer.state, batch, auc)
                trainer.state, step_loss, auc = out[:3]
            self.gstep += 1
            loss = float(step_loss)
        trainer._flush_cache_sync()  # update cache -> tables before export
        return loss

    def run_cycle(self) -> dict[str, Any] | None:
        """One full serve->retrain->swap cycle; ``None`` when the durable
        log has fewer than one batch of unread rows (drained)."""
        cfg = self.config
        st = _StageTrace(self.cycles)  # metrics rec numbers ungated cycles 0-based
        cycle_t0 = _trace.clock()
        step_begin = self.gstep
        _stage("replay")
        st.mark("replay")
        self.consumer.check_backpressure()
        batches, consumed = [], []
        while len(batches) < cfg.online.steps_per_cycle:
            out = self.consumer.next_batch()
            if out is None:
                break
            batches.append(out[0])
            consumed.extend(out[1])
        if not batches:
            return None

        _stage("train")
        st.mark("train")
        loss = self._train_cycle(batches)

        _stage("checkpoint")
        st.mark("checkpoint")
        target = int(self.store.current_version() or 0) + 1
        self.trainer._ckpt.save(
            self.gstep, self.trainer.state, force=True,
            cursor={"online": True, "global_step": self.gstep,
                    "replay": self.consumer.cursor(),
                    "target_version": target},
            stamps=self.trainer._ckpt_stamps)
        self._claimed_version = target
        # ungated cycles have no verdict; "published" marks the direct-to-
        # CURRENT path in the assembled timeline
        _trace.emit(
            "online", "online_cycle", cycle=self.cycles,
            verdict="published", version=target,
            step_begin=step_begin, step_end=self.gstep,
            dur_ms=round(_trace.elapsed_ms(cycle_t0), 3),
            consumed=[list(span) for span in consumed])
        rec = {
            "event": "online_cycle", "cycle": self.cycles,
            "global_step": self.gstep, "steps": len(batches),
            "loss": loss, "version": target,
            "consumed": [list(span) for span in consumed],
            **self.consumer.counters(),
        }
        self.trainer.logger.log(**rec)

        st.mark("publish")
        self._publish_state(target)  # stages: export -> publish

        _stage("swap")
        st.mark("swap")
        if self.fleet is not None:
            # ungated fleet: every replica follows the freshly-moved CURRENT
            self.fleet.sync()
        else:
            scorer = self._build_scorer(self.store.current_dir())
            self.batcher.swap(scorer.score, version=target,
                              program_cache_size=scorer.score_cache_size)
        st.close()
        self.cycles += 1
        return rec

    # ------------------------------------------------------- the gated cycle

    def _score_batches(self, scorer, batches: list[dict[str, np.ndarray]]
                       ) -> np.ndarray:
        """Score replay batches on a scorer, label-stripped.  The jitted
        score donates its inputs, so every call gets fresh arrays."""
        outs = []
        for b in batches:
            feats = {k: np.array(v) for k, v in b.items() if k != "label"}
            outs.append(np.asarray(scorer.score(feats)))
        return np.concatenate(outs)

    def _shadow_auc(self, labels, scores) -> float:
        """The gate metric for either family: labelled rows -> binary_auc
        (CTR); ``labels is None`` -> ranking_auc over [N, C] candidate
        panels with the positive in column 0 (seq)."""
        from tdfo_tpu.train.metrics import binary_auc, ranking_auc

        return (ranking_auc(scores) if labels is None
                else binary_auc(labels, scores))

    def _restore_last_good(self) -> None:
        """Discard the cycle's trained state: reload the last durable state
        (the previous verdict checkpoint, or the gated anchor).  ``gstep``
        is NOT rewound — checkpoint ids stay monotonic, and a restarted
        redo recomputes the identical ids from the identical records."""
        _, self.trainer.state, _ = self.trainer._ckpt.restore(
            self.trainer.state, stamps=self.trainer._ckpt_stamps)

    def _corrupt_candidate(self, delta_dir: Path) -> None:
        """The ``corrupt_candidate`` fault body: flip one payload byte of
        the ON-DISK delta (manifest digest left stale), so the gate's
        ``compose_delta`` digest check runs against real corruption."""
        from tdfo_tpu.serve.export import read_raw_bundle, write_raw_bundle

        manifest, arrays = read_raw_bundle(delta_dir)
        name = sorted(arrays)[0]
        arr = arrays[name]
        raw = bytearray(arr.tobytes())
        raw[len(raw) // 2] ^= 0xFF
        arrays[name] = np.frombuffer(bytes(raw),
                                     dtype=arr.dtype).reshape(arr.shape)
        shutil.rmtree(delta_dir)
        write_raw_bundle(delta_dir, manifest, arrays)

    def _run_cycle_gated(self) -> dict[str, Any] | None:
        """One gatekept cycle (see the module docstring for the contract):
        shadow-gate the candidate, canary it on the fleet's canary cohort,
        then promote or roll back — with the verdict checkpoint as the
        cycle's single durability point.  Returns ``None`` (nothing
        committed, nothing trained into the durable lineage) when the log
        lacks a full cycle of train rows plus the held-out shadow slice."""
        from tdfo_tpu.serve.export import bundle_from_raw, export_delta
        from tdfo_tpu.serve.scoring import make_scorer
        from tdfo_tpu.serve.swap import CorruptDeltaError, _version_name

        cfg = self.config
        inj = _faults.active()
        cycle_no = self.cycles_done + 1
        st = _StageTrace(cycle_no)
        cycle_t0 = _trace.clock()
        step_begin = self.gstep

        _stage("replay")
        st.mark("replay")
        self.consumer.check_backpressure()
        batches, consumed = [], []
        while len(batches) < cfg.online.steps_per_cycle:
            out = self.consumer.next_batch()
            if out is None:
                break
            batches.append(out[0])
            consumed.extend(out[1])
        if not batches:
            return None
        # the shadow-eval slice: held-out traffic PAST the cursor (it
        # trains in a later cycle, never this one — progressive validation)
        shadow = self.consumer.peek_batches(cfg.online.shadow_eval_batches)
        if len(shadow) < cfg.online.shadow_eval_batches:
            return None  # no commit: wait until the held-out slice fills
        if self.model_kind == "seq":
            # seq records carry no label column: candidate panels judge
            # themselves (column 0 is the positive), so the shadow labels
            # are None and every gate below routes through ranking_auc
            shadow_labels = None
            shadow_feats = {k: np.concatenate([b[k] for b in shadow])
                            for k in shadow[0]}
        else:
            shadow_labels = np.concatenate([b["label"] for b in shadow])
            shadow_feats = {k: np.concatenate([b[k] for b in shadow])
                            for k in shadow[0] if k != "label"}

        _stage("train")
        st.mark("train")
        loss = self._train_cycle(batches)

        _stage("export")
        st.mark("export")
        target = int(self.store.current_version() or 0) + 1
        delta_dir = self.chain / _version_name(target)
        if delta_dir.exists():
            shutil.rmtree(delta_dir)
        export_delta(delta_dir, self.store.current_dir(),
                     **self._export_kwargs())
        if inj is not None and inj.corrupt_candidate_due():
            self._corrupt_candidate(delta_dir)
        try:
            manifest, arrays = self.store.compose_delta(delta_dir)
        except CorruptDeltaError as err:
            # a corrupt candidate never reaches a pointer: re-export from
            # the in-memory state (deterministic) and re-verify — a second
            # failure means the corruption is upstream of the disk, so die
            self.trainer.logger.log(event="candidate_corrupt",
                                    cycle=cycle_no, version=target,
                                    error=str(err))
            shutil.rmtree(delta_dir)
            export_delta(delta_dir, self.store.current_dir(),
                         **self._export_kwargs())
            manifest, arrays = self.store.compose_delta(delta_dir)
        digest = manifest["digest"]

        # shadow gate: candidate vs incumbent on the same held-out rows
        candidate = make_scorer(
            bundle_from_raw(manifest, arrays, source=str(delta_dir)),
            mesh=self.trainer.mesh)
        incumbent = self._build_scorer(self.store.current_dir())
        auc_cand = self._shadow_auc(shadow_labels,
                                    self._score_batches(candidate, shadow))
        auc_base = self._shadow_auc(shadow_labels,
                                    self._score_batches(incumbent, shadow))

        verdict, reason = "promote", ""
        canary_auc = stable_auc = None
        canary_p99 = stable_p99 = None
        canary_ms: list[float] = []
        stable_ms: list[float] = []
        if auc_cand < auc_base - cfg.online.max_auc_regression:
            verdict = "rejected"
            reason = (f"shadow gate: candidate AUC {auc_cand:.4f} < "
                      f"incumbent {auc_base:.4f} - "
                      f"{cfg.online.max_auc_regression}")
        else:
            if inj is not None and inj.auc_regress_due(cycle_no):
                # training/serving skew: the BYTES are healthy (the shadow
                # gate scored them directly and passed) — only live serving
                # misbehaves, which is what the canary watch exists for
                self.fleet.set_score_skew(digest)
            if inj is not None and inj.slow_canary_due(cycle_no):
                # latency regression the AUC gate cannot see: only the
                # replicas serving this digest score slowly, so the p99
                # verdict term below has a differential signal
                self.fleet.set_score_slow(digest)
            _stage("publish")
            st.mark("publish")
            self.store.publish_canary(delta_dir, composed=(manifest, arrays))
            _stage("canary")
            st.mark("canary")
            self.fleet.sync()  # the canary cohort picks the candidate up
            for rnd in range(1, cfg.online.canary_cycles + 1):
                if inj is not None:
                    inj.maybe_kill_canary(rnd)
                self.fleet.mark_canary_watch()
                self.fleet.sync()
                hbs = self.fleet.heartbeat(shadow_feats, shadow_labels)
                for hb in hbs:
                    self.trainer.logger.log(event="canary_heartbeat",
                                            cycle=cycle_no, round=rnd, **hb)
                canaries = [h for h in hbs
                            if h["canary"] and h["version"] == target]
                stables = [h for h in hbs if not h["canary"]]
                if not canaries:
                    verdict, reason = "rollback", "no alive canary replica"
                    break
                canary_ms.extend(h["ms"] for h in canaries)
                stable_ms.extend(h["ms"] for h in stables)
                canary_auc = float(np.mean([h["auc"] for h in canaries]))
                stable_auc = (float(np.mean([h["auc"] for h in stables]))
                              if stables else auc_base)
                if canary_auc < stable_auc - cfg.online.max_auc_regression:
                    verdict = "rollback"
                    reason = (f"canary AUC {canary_auc:.4f} < stable "
                              f"{stable_auc:.4f} - "
                              f"{cfg.online.max_auc_regression} at watch "
                              f"round {rnd}")
                    break
            # latency verdict term ([online] max_p99_regression_ms): the
            # heartbeat-scoring p99s, canary cohort vs stable cohort, on
            # the SAME nearest-rank percentile launch.py obs reports — a
            # candidate that serves correct logits slowly rolls back
            # exactly like an AUC regression
            canary_p99 = _percentile(canary_ms, 99)
            stable_p99 = _percentile(stable_ms, 99)
            if (verdict == "promote" and cfg.online.max_p99_regression_ms > 0
                    and canary_p99 is not None and stable_p99 is not None
                    and canary_p99 > stable_p99
                    + cfg.online.max_p99_regression_ms):
                verdict = "rollback"
                reason = (f"canary p99 {canary_p99:.1f}ms > stable p99 "
                          f"{stable_p99:.1f}ms + "
                          f"{cfg.online.max_p99_regression_ms}ms budget")

        _stage("verdict")
        st.mark("verdict")
        if verdict != "promote":
            self._restore_last_good()
        canary_rec = {"verdict": verdict, "version": target,
                      "digest": digest, "reason": reason}
        self.trainer._ckpt.save(
            self.gstep, self.trainer.state, force=True,
            cursor={"online": True, "global_step": self.gstep,
                    "cycles_done": cycle_no,
                    "replay": self.consumer.cursor(),
                    "target_version": target if verdict == "promote"
                    else int(self.store.current_version() or 0),
                    "canary": canary_rec},
            stamps=self.trainer._ckpt_stamps)
        self._pending_canary = canary_rec
        # the cycle's trace span lands right AFTER its single durability
        # point: a kill before the verdict checkpoint redoes the cycle (and
        # emits then, once); a kill after it leaves the span already on
        # disk while _catch_up_gated replays the store side — either way
        # the assembled timeline carries exactly one record per durable
        # cycle (obs/aggregate.py dedups by cycle number, last wins)
        _trace.emit(
            "online", "online_cycle", cycle=cycle_no, verdict=verdict,
            reason=reason, version=target, digest=digest,
            step_begin=step_begin, step_end=self.gstep,
            canary_p99_ms=canary_p99, stable_p99_ms=stable_p99,
            dur_ms=round(_trace.elapsed_ms(cycle_t0), 3),
            consumed=[list(span) for span in consumed])

        _stage("commit")
        st.mark("commit")
        if verdict == "promote":
            self.store.promote_canary()
        elif verdict == "rollback":
            self.store.rollback_canary(reason)

        _stage("swap")
        st.mark("swap")
        self.fleet.sync()  # every replica converges on the verdict's head
        if cfg.online.keep_consumed_segments > 0:
            self.consumer.gc_consumed_segments(
                cfg.online.keep_consumed_segments)
        st.close()
        self.cycles_done = cycle_no
        self.cycles += 1
        rec = {
            "event": "online_cycle", "cycle": cycle_no, "gated": True,
            "global_step": self.gstep, "steps": len(batches), "loss": loss,
            "verdict": verdict, "reason": reason, "version": target,
            "shadow_auc": auc_cand, "shadow_auc_base": auc_base,
            "canary_auc": canary_auc, "stable_auc": stable_auc,
            "canary_p99_ms": canary_p99, "stable_p99_ms": stable_p99,
            "consumed": [list(span) for span in consumed],
            **self.consumer.counters(),
        }
        self.trainer.logger.log(**rec)
        return rec

    def run(self) -> dict[str, Any]:
        """Cycle until the log drains or ``max_cycles``; returns run stats.
        The gated loop counts DURABLE cycles (``cycles_done`` rides in the
        verdict checkpoint) so a restarted run finishes the budget instead
        of re-running it."""
        max_cycles = self.config.online.max_cycles
        if self.gated:
            while not max_cycles or self.cycles_done < max_cycles:
                if self._run_cycle_gated() is None:
                    break
        else:
            while not max_cycles or self.cycles < max_cycles:
                if self.run_cycle() is None:
                    break
        ctrs = self.consumer.counters()
        out = {
            "cycles": self.cycles,
            "global_step": self.gstep,
            "version": int(self.store.current_version() or 0),
            "bundle": str(self.store.current_dir()),
            **ctrs,
        }
        if self.gated:
            out["cycles_done"] = self.cycles_done
        return out

    def probe(self, requests) -> dict[Any, np.ndarray]:
        """Score a request trace through the live (post-swap) serving side —
        the served-logits fingerprint the bitwise-equality acceptance
        compares.  In fleet mode the trace round-robins over alive
        replicas (``fleet.probe_each`` gives the per-replica variant)."""
        if self.fleet is not None:
            return self.fleet.run(requests)
        return self.batcher.run(requests)

    def close(self) -> None:
        """Release the serving side.  Required for process fleets (child
        processes + sockets); a no-op-ish courtesy for the in-process
        kinds."""
        if self.fleet is not None:
            self.fleet.close()


def online_from_config(config, *, log_dir: str | Path | None = None
                       ) -> dict[str, Any]:
    """The ``python -m tdfo_tpu.launch online`` body."""
    loop = OnlineLoop(config, log_dir=log_dir)
    try:
        return loop.run()
    finally:
        loop.close()
