"""Hybrid sparse/dense train step — the DMP + CombinedOptimizer equivalent.

torchrec splits parameters in two (``torchrec/train.py:235-254``): embedding
tables get a fused in-backward sparse optimizer (fbgemm), dense params get a
regular optimizer wrapped in ``CombinedOptimizer``.  The TPU-native
re-expression:

  * the step computes gradients w.r.t. the *gathered vectors* (an activation,
    shape [B, D]) instead of the dense [V, D] table — the jnp.take VJP that
    would materialise a dense table gradient is never taken;
  * each table then gets a row-sparse update (``tdfo_tpu/ops/sparse``) that
    touches O(unique ids) rows of table + optimizer slots;
  * dense params flow through optax exactly as in the dense step.

Under GSPMD with row-sharded tables the gather/scatter pair lowers to ICI
collectives; tables, slots and updates all stay sharded end to end.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax

from tdfo_tpu.obs import counters as obs_counters
from tdfo_tpu.ops.quant import bytes_to_f32, dequantize_rows
from tdfo_tpu.ops.quant import sr_key as _make_sr_key
from tdfo_tpu.ops.sparse import SparseOptimizer, cache_lookup_rows, dedupe_ids
from tdfo_tpu.ops.sparse import cache_overlay_rows
from tdfo_tpu.parallel.embedding import (
    CACHE_PREFIX, ShardedEmbeddingCollection, qscale_name)


def _array_is_narrow(state: "SparseTrainState", aname: str) -> bool:
    """True when ``aname``'s table or any optimizer slot is stored narrow
    (bf16 or int8): the signal that its update needs a stochastic-rounding
    key.  Static under jit (dtypes are trace-time constants), so f32 arrays
    keep a key-free — hence byte-identical — update graph."""
    if state.tables[aname].dtype in (jnp.bfloat16, jnp.int8):
        return True
    return any(leaf.dtype == jnp.bfloat16
               for leaf in jax.tree_util.tree_leaves(state.slots[aname]))


def _pin_replicated(mesh, tree):
    """Constrain every leaf of ``tree`` to a fully-replicated layout.

    The update cache is replicated state by contract (``init_caches``
    commits it at ``P()``), but inside a jitted program GSPMD's sharding
    PROPAGATION — not the committed input shardings — decides the layout
    of intermediates, and it is free to partition the [C] sorted-id
    directory over the batch axis (observed under the trainer's fused
    step+AUC program: a data-sharded directory breaks the searchsorted
    routing and silently drops every cache write).  Explicit constraints
    at the cache read and write boundaries make replication part of the
    program instead of a propagation accident.  No-op when ``mesh`` is
    None (single-device / eager tests)."""
    if mesh is None:
        return tree
    s = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, s), tree)


__all__ = [
    "SparseTrainState",
    "make_sparse_train_step",
    "make_cache_flush_fn",
    "PipelinedSparseStep",
    "make_pipelined_sparse_train_step",
]


@jax.tree_util.register_dataclass
@dataclass
class SparseTrainState:
    """Dense params under optax + embedding tables under sparse optimizers."""

    step: jax.Array
    dense_params: Any
    opt_state: Any
    tables: dict[str, jax.Array]
    slots: dict[str, Any]
    tx: optax.GradientTransformation = field(metadata=dict(static=True))
    sparse_opt: SparseOptimizer = field(metadata=dict(static=True))

    @classmethod
    def create(cls, *, dense_params, tx, tables, sparse_opt) -> "SparseTrainState":
        from tdfo_tpu.parallel.embedding import QSCALE_PREFIX

        return cls(
            step=jnp.zeros((), jnp.int32),
            dense_params=dense_params,
            opt_state=tx.init(dense_params),
            tables=dict(tables),
            # int8 (scale, offset) sidecars are storage, not optimized
            # parameters: they get no slot state (empty tuple keeps the
            # pytree structure table-keyed and checkpoint-stable)
            slots={n: (() if n.startswith(QSCALE_PREFIX)
                       else sparse_opt.init(t))
                   for n, t in tables.items()},
            tx=tx,
            sparse_opt=sparse_opt,
        )


def make_sparse_train_step(
    coll: ShardedEmbeddingCollection,
    forward: Callable,
    *,
    mode: str = "gspmd",
    donate: bool = True,
    jit: bool = True,
    batch_transform: Callable | None = None,
    with_aux: bool = False,
    dedup_lookup: bool = False,
):
    """Build the jitted hybrid step.

    ``forward(dense_params, embeddings, batch) -> scalar loss`` receives the
    gathered vectors ``{feature: [**ids_shape, D]}`` — the model under this
    step consumes embeddings as inputs (HistoryArch-style,
    ``torchrec/models.py:163-178``) rather than owning the tables.  A forward
    that also accepts a ``dropout_rng`` keyword gets a per-step key derived
    from the rng passed to the step (``step(state, batch, rng)``), enabling
    stochastic regularisation in this regime.

    ``batch`` must contain an id array for every feature the collection
    serves (same key names) — or, with ``batch_transform``, whatever the
    transform turns into one: the transform runs INSIDE the jitted step
    (e.g. ``jagged_to_dense`` materialising [B, T] ids from a
    (values, lengths) jagged batch, fbgemm ``jagged_2d_to_dense`` parity).

    ``with_aux=True``: ``forward`` must return ``(loss, aux)`` and the step
    returns ``(state, (loss, aux))`` — the hook for per-epoch TRAIN metrics
    (reference parity: train-side ROC-AUC, ``jax-flax/train_dp.py:219-220``).

    ``dedup_lookup=True`` (requires ``mode="gspmd"``, non-negative ids): the
    TBE unique-then-expand recipe.  Per table array, ONE sort deduplicates
    the step's ids; the forward gathers only the unique rows (a compact,
    cache-resident block — scattered gathers from a multi-GB table cost
    ~40 ns/row on v5e, expands from the compact block ~2 ns/row) and the
    backward segment-sums grads by the SAME mapping, feeding the optimizer
    directly — no second dedupe.  Embeddings and updates are bit-identical
    to the default path (same gather values, same segment construction);
    measured ~25%% off the DLRM-Criteo step.  Arrays whose update needs the
    explicit shard_map program (fused fat + real row sharding) keep the
    default update path.

    Grouped exchange (collection built with ``grouped_a2a=True``, requires
    ``mode="alltoall"``): every row/table-sharded feature's forward rides
    the collection's combined-stream lookup and the update half runs ONE
    :meth:`~ShardedEmbeddingCollection.grouped_update` over all of them —
    O(1) collectives per direction instead of O(tables).  Losses and
    tables are bit-identical to the sequential per-table reference (see
    ``grouped_update``'s docstring for the exact guarantee).

    Hot/cold collections (``ShardedEmbeddingCollection`` built with
    ``hot_ids``, requires ``mode="gspmd"``): each split table's ids route
    once per step into hot-head positions and residual cold ids.  The hot
    half updates via ONE one-hot MXU contraction + dense [K, D]
    read-modify-write per table (``SparseOptimizer.dense_update`` — no
    sort/dedupe/scatter for the power-law head, where most ids land); the
    cold half rides the unchanged machinery above with hot hits as -1
    (dropped by dedupe like padding).  Fully-hot tables skip the cold side
    statically, shrinking the cold distinct-row bound and scatter cost.

    Update cache (collection built with ``cache_rows > 0`` AND a state
    whose ``slots`` carry the ``coll.init_caches`` entries, requires
    ``mode="gspmd"``): every cached array's row update runs IN its cache
    (``SparseOptimizer.cache_update[_unique]`` — admit misses gather-only,
    update hits scatter-free, touch no big array), and forward gathers
    overlay the cached rows so nothing ever reads a stale big-table value.
    The step's jaxpr then contains NO scatter into any big table; the
    trainer pays the coalesced write-back via :func:`make_cache_flush_fn`
    once per ``flush_every`` interval.  Bit-identical to the eager path
    (see ``ops/sparse.py``'s cache section for why).  A state without
    cache entries — the default — traces the exact pre-cache graph.
    """
    import inspect

    if dedup_lookup and mode != "gspmd":
        raise ValueError("dedup_lookup composes with lookup mode 'gspmd' only")
    if coll.cache_rows > 0 and mode != "gspmd":
        raise ValueError(
            "the update cache (cache_rows > 0) composes with lookup mode "
            "'gspmd' only")
    features = list(coll.features())
    takes_rng = "dropout_rng" in inspect.signature(forward).parameters
    # hot/cold (frequency-partitioned) tables: per-feature id routing splits
    # lookups into hot-head positions (updated scatter-free via one-hot MXU
    # contractions, no dedupe) and residual cold ids (riding the unchanged
    # machinery below — hot hits become -1 and the existing negative-id
    # padding semantics drop them everywhere).  All statics resolved here.
    hot_tables = coll.hot_tables()
    if hot_tables and mode != "gspmd":
        raise ValueError(
            "hot/cold tables compose with lookup mode 'gspmd' only")
    feat_table = {f: coll.resolve(f)[1].name for f in features}
    hot_by_table = {
        t: [f for f in features if feat_table[f] == t] for t in hot_tables
    }
    hot_feats = {f for t in hot_tables for f in hot_by_table[t]}
    # features of FULLY hot tables have no cold side at all: they skip the
    # cold concat/dedupe/gather/update statically (at the Criteo profile 18
    # of 26 tables fit under a 16k hot cap, shrinking the cold distinct-row
    # bound ~102k -> ~65k and the scatter cost with it)
    full_hot_feats = {f for f in hot_feats if coll.hot_full(feat_table[f])}
    # grouped cross-table exchange (torchrec KJTAllToAll parity): every
    # row/table-sharded feature rides ONE combined id all_to_all + ONE
    # vector all_to_all per direction instead of one pair per TABLE.
    # ``coll.lookup`` routes the forward internally; the update below
    # replaces these features' per-array loop with one grouped_update.
    use_grouped = (
        mode == "alltoall" and coll.grouped_a2a
        and coll.mesh is not None and coll.n_shards > 1)
    grouped_feats = tuple(
        f for f in features
        if coll.resolve(f)[1].sharding in ("row", "table")
    ) if use_grouped else ()
    grouped_arrays = tuple(sorted({coll.resolve(f)[0] for f in grouped_feats}))
    by_table_static: dict[str, list[str]] = {}
    for f in features:
        if f in full_hot_feats or f in grouped_feats:
            continue
        by_table_static.setdefault(coll.resolve(f)[0], []).append(f)

    def _concat_ids(feats, ids, rows_per_line: int = 1):
        id_list, sizes, bound = [], [], 0
        for f in feats:
            _, spec, offset = coll.resolve(f)
            # negative (padding or routed-to-hot) ids must stay negative:
            # adding the stack offset would alias them into the previous
            # member's rows and corrupt its update
            flat = jnp.where(ids[f] >= 0, ids[f] + offset, -1).reshape(-1)
            id_list.append(flat)
            sizes.append(flat.shape[0])
            # static per-feature distinct bound: a feature can touch at most
            # min(its id count, its member vocab) rows — minus the hot-head
            # rows for hot/cold tables (hot ids never reach the cold side) —
            # or, for fat-line arrays, that many LINES (+1: a member's row
            # range may straddle one extra line at each unaligned stack
            # offset)
            if rows_per_line == 1:
                cold_rows = spec.num_embeddings - coll.hot_count(spec.name)
                bound += min(flat.shape[0], cold_rows)
            else:
                bound += min(flat.shape[0],
                             -(-spec.num_embeddings // rows_per_line) + 1)
        return jnp.concatenate(id_list), sizes, bound

    def step(state: SparseTrainState, batch, rng=None) -> tuple[SparseTrainState, jax.Array]:
        if batch_transform is not None:
            batch = batch_transform(batch)
        ids = {f: batch[f] for f in features}
        # update-cache coverage, static under jit: the presence of the
        # coll.init_caches entries in state.slots IS the enable signal, so
        # a cache-off state traces the exact pre-cache (byte-identical)
        # graph even on a cache_rows > 0 collection
        cached = {k[len(CACHE_PREFIX):] for k in state.slots
                  if k.startswith(CACHE_PREFIX)}
        step_rng = None
        if takes_rng and rng is not None:
            step_rng = jax.random.fold_in(rng, state.step)

        # hot/cold routing: one remap per hot feature, shared by the
        # forward gather and both update halves.  cold_ids carries -1 at
        # hot hits (dropped by dedupe / clamped by gathers), hot_pos
        # carries -1 at cold hits (zeroed by the one-hot contraction).
        hot_pos: dict[str, jax.Array] = {}
        cold_ids = ids
        if hot_tables:
            cold_ids = dict(ids)
            for f in hot_feats:
                hp, ci = coll.route_ids(f, ids[f])
                hot_pos[f] = hp
                cold_ids[f] = ci

        def _merge_hot(f, cold_vec):
            """Select hot-head vectors at hot hits (identity off hot/cold)."""
            hp = hot_pos.get(f)
            if hp is None:
                return cold_vec
            hot = state.tables[coll.hot_array_name(feat_table[f])]
            hot_vec = jnp.take(
                hot, jnp.maximum(hp, 0), axis=0).astype(jnp.float32)
            if cold_vec is None:  # fully hot: there is no cold side
                return hot_vec
            return jnp.where((hp >= 0)[..., None], hot_vec, cold_vec)

        def _overlay_lookup(embs, feats):
            """Serve cached rows into ``coll.lookup`` outputs: between
            flushes the big tables are stale for dirty cached rows, so any
            position whose gather landed on a cached row must show the
            cache value — replicating each lookup path's own padding-clamp
            semantics so the overlaid vector equals the eager-path gather
            bit-for-bit."""
            for f in feats:
                aname, _, off = coll.resolve(f)
                # fully hot features never read their (dead) cold rows
                if aname not in cached or f in full_hot_feats:
                    continue
                cache = _pin_replicated(
                    coll.mesh, state.slots[CACHE_PREFIX + aname])
                hp = hot_pos.get(f)
                if hp is None:
                    # plain gspmd lookup: jnp.take clamps out-of-range ids
                    v = state.tables[aname].shape[0]
                    gid = jnp.clip(ids[f] + off, 0, v - 1)
                else:
                    # hot/cold lookup gathers cold at where(cold >= 0,
                    # cold + off, 0) and selects the hot head at hot hits —
                    # those positions must keep the (authoritative) hot vec
                    cold = cold_ids[f]
                    gid = jnp.where(cold >= 0, cold + off, 0)
                cur, hit = cache_lookup_rows(cache, gid, mesh=coll.mesh)
                if hp is not None:
                    hit = hit & (hp < 0)
                embs[f] = jnp.where(
                    hit[..., None], cur.astype(embs[f].dtype), embs[f])
            return embs

        # Gradients w.r.t. the gathered vectors, never the [V, D] table.
        def loss_from_embs(dense_params, embs):
            if takes_rng:
                return forward(dense_params, embs, batch, dropout_rng=step_rng)
            return forward(dense_params, embs, batch)

        dedup_ctx: dict[str, tuple] = {}
        if dedup_lookup:
            embs = {}
            for tname, feats in by_table_static.items():
                # column-sharded tables shard the EMBEDDING dim: the compact
                # gather would drop the activation sharding the default
                # lookup constrains — keep them on the default path (their
                # update falls back too, since no ctx entry exists)
                if (tname in coll.specs
                        and coll.specs[tname].sharding == "column"):
                    embs.update(_overlay_lookup(coll.lookup(
                        state.tables, {f: ids[f] for f in feats}, mode=mode),
                        feats))
                    continue
                table = state.tables[tname]
                d = coll.array_embedding_dim(tname)
                fat = table.ndim == 3
                all_ids, sizes, bound = _concat_ids(feats, cold_ids)
                obs_counters.emit(f"emb/{tname}/touched_ids",
                                  lambda a=all_ids: (a >= 0).sum())
                total = all_ids.shape[0]
                # +1 slack: negative (padding) ids dedupe to ONE sentinel
                # slot beyond the real-id bound; without it the expand would
                # clamp the sentinel seg onto a real row's slot
                cap = (-(-(bound + 1) // 8) * 8) if bound + 1 < total else None
                if fat:
                    # routed fat-line flow: ONE sort yields the row-level
                    # expand key AND the line grouping.  Forward: gather
                    # whole packed LINES straight off the 3D array (the
                    # fast TPU gather — reshaping the table to a row view
                    # materialises a multi-GB copy), expand per distinct
                    # row from the SMALL gathered block, slot-select, then
                    # expand per batch position.  Sentinel rows resolve to
                    # line 0 slot 0 = row 0, the default lookup's clip.
                    from tdfo_tpu.ops.sparse import dedupe_rows_and_lines

                    lay = coll.fat_layout_for(tname)
                    _, _, bound_l = _concat_ids(feats, cold_ids,
                                                rows_per_line=lay.r)
                    cap_r = cap if cap is not None else total
                    cap_l = min(cap_r, -(-(bound_l + 1) // 8) * 8)
                    seg, ulines, row_lidx, row_slot = dedupe_rows_and_lines(
                        all_ids.astype(jnp.int32), capacity_rows=cap_r,
                        capacity_lines=cap_l, rows_per_line=lay.r,
                    )
                    oob = jnp.iinfo(jnp.int32).max
                    lines = jnp.take(
                        table, jnp.where(ulines < oob, ulines, 0), axis=0)
                    flat = lines.reshape(cap_l, lay.tiles * 128)
                    rowlines = jnp.take(
                        flat, jnp.minimum(row_lidx, cap_l - 1), axis=0)
                    # int8 byte lines slot-select codes AND the adjacent 8
                    # sidecar bytes, then decode the small selected block
                    span = d + 8 if lay.dtype == "int8" else d
                    rows = rowlines[:, :span]
                    for s in range(1, lay.r):
                        rows = jnp.where(
                            (row_slot == s)[:, None],
                            rowlines[:, s * lay.w: s * lay.w + span], rows)
                    if lay.dtype == "int8":
                        rows = dequantize_rows(
                            rows[:, :d], bytes_to_f32(rows[:, d:span]))
                    dedup_ctx[tname] = ("routed", ulines, seg, row_lidx,
                                        row_slot, lines)
                    obs_counters.emit(f"emb/{tname}/unique_lines",
                                      lambda u=ulines: (u < oob).sum())
                else:
                    uids, seg, valid = dedupe_ids(
                        all_ids.astype(jnp.int32), capacity=cap,
                        max_distinct=cap,
                    )
                    rows = jnp.take(table, jnp.where(valid, uids, 0), axis=0)
                    if coll.array_is_int8(tname):
                        # sidecar rides the same compact gather; dequantize
                        # the small block so downstream expand stays f32
                        rows = dequantize_rows(rows, jnp.take(
                            state.tables[qscale_name(tname)],
                            jnp.where(valid, uids, 0), axis=0))
                    if tname in cached:
                        # serve cached (authoritative) rows into the compact
                        # gather — sentinel slots clamp to row 0 exactly like
                        # the eager gather, so they overlay to row 0's
                        # authoritative value too
                        rows = cache_overlay_rows(
                            _pin_replicated(
                                coll.mesh,
                                state.slots[CACHE_PREFIX + tname]),
                            jnp.where(valid, uids, 0),
                            rows, mesh=coll.mesh)
                    dedup_ctx[tname] = ("rows", uids, seg, valid)
                    obs_counters.emit(f"emb/{tname}/unique_rows",
                                      lambda v=valid: v.sum())
                off = 0
                # dequantize after the compact gather (identity for f32):
                # the model interface is f32 whatever the storage dtype
                rows = rows.astype(jnp.float32)
                for f, n_f in zip(feats, sizes):
                    e = jnp.take(rows, seg[off:off + n_f], axis=0)
                    e = e.reshape(*ids[f].shape, e.shape[-1])
                    embs[f] = _merge_hot(f, e)
                    off += n_f
            for f in full_hot_feats:  # no cold side: hot gather only
                embs[f] = _merge_hot(f, None)
        else:
            # coll.lookup routes hot/cold internally (eval shares that path)
            embs = _overlay_lookup(
                coll.lookup(state.tables, ids, mode=mode), features)
        loss, (g_dense, g_embs) = jax.value_and_grad(
            loss_from_embs, argnums=(0, 1), has_aux=with_aux
        )(state.dense_params, embs)
        aux = None
        if with_aux:
            loss, aux = loss
        if obs_counters.enabled():
            # global norms over the dense half and the gathered-vector
            # grads (the table-side signal without a [V, D] reduction);
            # param_norm walks the full tables — one HBM pass, priced into
            # telemetry.counters = true only
            obs_counters.emit("grad_norm",
                              optax.global_norm((g_dense, g_embs)))
            obs_counters.emit("param_norm", optax.global_norm(
                (state.dense_params, state.tables)))

        # dense half: optax
        updates, new_opt_state = state.tx.update(g_dense, state.opt_state, state.dense_params)
        new_dense = optax.apply_updates(state.dense_params, updates)

        # sparse half: group features by table, one row-sparse update each.
        # _sr_key: stochastic-rounding key per narrow-storage array, derived
        # from (state.step, array name) — bit-deterministic, resume-exact —
        # and None for f32 arrays (their update graph stays key-free)
        def _sr_key(aname):
            return (_make_sr_key(state.step, aname)
                    if _array_is_narrow(state, aname) else None)

        new_tables = dict(state.tables)
        new_slots = dict(state.slots)
        if grouped_feats:
            # one grouped backward exchange for every row/table-sharded
            # feature: 2 collectives total (ids + grads) vs 2 per array.
            # One base key serves the whole exchange (grouped_update folds
            # per-array table ids itself)
            g_narrow = any(_array_is_narrow(state, a) for a in grouped_arrays)
            gt, gs = coll.grouped_update(
                state.sparse_opt, state.tables, state.slots,
                {f: ids[f] for f in grouped_feats},
                {f: g_embs[f] for f in grouped_feats},
                sr_key=(_make_sr_key(state.step, "__grouped_update__")
                        if g_narrow else None))
            new_tables.update(gt)
            new_slots.update(gs)
        for tname, feats in by_table_static.items():
            grad_list = [
                g_embs[f].reshape(-1, g_embs[f].shape[-1]) for f in feats
            ]
            all_grads = jnp.concatenate(grad_list)
            # small-vocab adam tables keep the one-hot MXU tier (raw ids,
            # no scatter — ~10x the per-row scatter formulation update_unique
            # would fall back to)
            small_adam = (
                state.sparse_opt.kind == "adam"
                and state.tables[tname].ndim == 2
                and state.tables[tname].shape[0]
                <= state.sparse_opt.small_vocab_threshold
            )
            if (tname in dedup_ctx and not small_adam
                    and not coll.needs_shard_map_update(tname)):
                # shared-dedupe fast path: segment-sum by the forward's seg
                # and feed the optimizer tiers directly (no second sort)
                ctx = dedup_ctx[tname]
                d_t = coll.array_embedding_dim(tname)
                if ctx[0] == "routed":
                    # row-level segment-sum (the cheap space) + in-kernel
                    # routing: the whole table update has no XLA scatter,
                    # and the kernel reuses the forward's line gather
                    _, ulines, seg, row_lidx, row_slot, lines = ctx
                    g_u = jax.ops.segment_sum(
                        all_grads.astype(jnp.float32), seg,
                        num_segments=row_lidx.shape[0],
                    )
                    new_tables[tname], new_slots[tname] = (
                        state.sparse_opt.update_routed(
                            state.tables[tname], state.slots[tname], ulines,
                            g_u, row_lidx, row_slot, lines,
                            embedding_dim=d_t, sr_key=_sr_key(tname),
                        ))
                    continue
                _, uids, seg, valid = ctx
                g_u = jax.ops.segment_sum(
                    all_grads, seg, num_segments=uids.shape[0]
                )
                g_u = jnp.where(valid[:, None], g_u, 0.0)
                if tname in cached:
                    # cached tier: admit misses (gather-only), update in
                    # the cache — the big table and slot rows stay
                    # untouched until the coalesced flush.  All cache-math
                    # operands pin replicated (see _pin_replicated).
                    ck = CACHE_PREFIX + tname
                    u_r, g_r, v_r = _pin_replicated(
                        coll.mesh, (uids, g_u, valid))
                    qsc = (state.tables[qscale_name(tname)]
                           if coll.array_is_int8(tname) else None)
                    with obs_counters.scope(f"emb/{tname}/"):
                        new_cache, new_slots[tname] = (
                            state.sparse_opt.cache_update_unique(
                                _pin_replicated(coll.mesh, state.slots[ck]),
                                state.tables[tname],
                                state.slots[tname], u_r, g_r, v_r,
                                step=state.step, sr_key=_sr_key(tname),
                                mesh=coll.mesh, qscale=qsc,
                            ))
                    new_slots[ck] = _pin_replicated(coll.mesh, new_cache)
                    continue
                if (coll.array_is_int8(tname)
                        and state.tables[tname].ndim == 2):
                    # plain 2D int8: the (scale, offset) sidecar is a
                    # separate array; fat int8 carries it in-line and
                    # never threads qscale
                    qn = qscale_name(tname)
                    (new_tables[tname], new_slots[tname],
                     new_tables[qn]) = state.sparse_opt.update_unique(
                        state.tables[tname], state.slots[tname], uids, g_u,
                        valid, embedding_dim=d_t, sr_key=_sr_key(tname),
                        qscale=state.tables[qn],
                    )
                else:
                    new_tables[tname], new_slots[tname] = (
                        state.sparse_opt.update_unique(
                            state.tables[tname], state.slots[tname], uids,
                            g_u, valid, embedding_dim=d_t,
                            sr_key=_sr_key(tname),
                        ))
                continue
            all_ids, _, bound = _concat_ids(feats, cold_ids)
            obs_counters.emit(f"emb/{tname}/touched_ids",
                              lambda a=all_ids: (a >= 0).sum())
            # dedupe capacity = the proven bound when it is tighter than the
            # id count: scatter cost scales with SLOTS, so stacked many-table
            # arrays (e.g. DLRM-Criteo, where small tables are fully covered
            # every step) save ~half the update cost
            total = all_ids.shape[0]
            md = -(-bound // 8) * 8 if bound < total else None
            if tname in cached and not small_adam:
                # cached tier: the SAME dedupe (bit-identical summed grads)
                # feeds the cache update; no big array is written.  All
                # cache-math operands pin replicated (see _pin_replicated).
                ck = CACHE_PREFIX + tname
                i_r, g_r = _pin_replicated(
                    coll.mesh, (all_ids, all_grads))
                qsc = (state.tables[qscale_name(tname)]
                       if coll.array_is_int8(tname) else None)
                with obs_counters.scope(f"emb/{tname}/"):
                    new_cache, new_slots[tname] = (
                        state.sparse_opt.cache_update(
                            _pin_replicated(coll.mesh, state.slots[ck]),
                            state.tables[tname],
                            state.slots[tname], i_r, g_r,
                            step=state.step, capacity=md, max_distinct=md,
                            sr_key=_sr_key(tname), mesh=coll.mesh,
                            qscale=qsc,
                        ))
                new_slots[ck] = _pin_replicated(coll.mesh, new_cache)
                continue
            # sharding-aware routing: fused row-sharded tables update inside
            # an explicit shard_map (Pallas has no GSPMD partition rule)
            if (coll.array_is_int8(tname)
                    and state.tables[tname].ndim == 2):
                # plain 2D int8 threads the separate qscale sidecar; fat
                # int8 byte containers carry it in-line
                qn = qscale_name(tname)
                (new_tables[tname], new_slots[tname],
                 new_tables[qn]) = coll.sparse_update(
                    state.sparse_opt, tname,
                    state.tables[tname], state.slots[tname], all_ids,
                    all_grads, max_distinct=md, sr_key=_sr_key(tname),
                    qscale=state.tables[qn],
                )
            else:
                new_tables[tname], new_slots[tname] = coll.sparse_update(
                    state.sparse_opt, tname,
                    state.tables[tname], state.slots[tname], all_ids,
                    all_grads, max_distinct=md, sr_key=_sr_key(tname),
                )

        # hot-head updates: per logical table, ONE one-hot MXU contraction
        # merges duplicates and a full dense [K, D] read-modify-write
        # applies the optimizer — no sort, no dedupe, no scatter (the
        # power-law head is where scatters hurt: most of the batch's ids
        # land here).  Cold hits carry hot_pos -1 and one-hot to zero rows.
        for tname in hot_tables:
            hname = coll.hot_array_name(tname)
            feats = hot_by_table[tname]
            hp_all = jnp.concatenate(
                [hot_pos[f].reshape(-1) for f in feats])
            obs_counters.emit(f"emb/{tname}/hot_ids",
                              lambda h=hp_all: (h >= 0).sum())
            g_all = jnp.concatenate([
                g_embs[f].reshape(-1, g_embs[f].shape[-1]) for f in feats
            ])
            new_tables[hname], new_slots[hname] = state.sparse_opt.dense_update(
                state.tables[hname], state.slots[hname], hp_all, g_all,
                sr_key=_sr_key(hname),
            )

        return (
            SparseTrainState(
                step=state.step + 1,
                dense_params=new_dense,
                opt_state=new_opt_state,
                tables=new_tables,
                slots=new_slots,
                tx=state.tx,
                sparse_opt=state.sparse_opt,
            ),
            (loss, aux) if with_aux else loss,
        )

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_cache_flush_fn(*, donate: bool = True, jit: bool = True,
                        mesh=None, counters: bool = False):
    """Build the coalesced write-back program of the update cache:
    ``flush(state) -> (state, overflow)``.

    A SEPARATE jitted program from the train step — the trainer calls it
    every ``flush_every`` steps and unconditionally before checkpoint,
    eval, and serving export — so the big-table scatter cost is paid once
    per interval and non-flush step jaxprs carry no big-table scatter at
    all.  Per cached array it writes every dirty row + slot mirror back
    verbatim (``SparseOptimizer.cache_flush``), evicts down to the hottest
    half, and surfaces the interval's admission-overflow counters:
    ``overflow`` maps array name -> int32 count of distinct ids whose
    updates were LOST to a full cache.  Callers MUST fail on any non-zero
    entry — the bit-exactness contract is broken past that point.  A state
    without cache entries flushes to itself (empty overflow dict).  Pass
    the collection's ``mesh`` so the cache stays pinned replicated inside
    the jitted program (see ``_pin_replicated``).

    ``counters=True`` (``telemetry.counters``) collects the flush's
    in-graph diagnostics (``emb/<array>/cache_flushed_rows`` and resident
    counts, ``tdfo_tpu/obs/counters.py``) and returns ``(state, overflow,
    counters_dict)``; the default signature and graph are untouched."""

    def _body(state: SparseTrainState):
        new_tables = dict(state.tables)
        new_slots = dict(state.slots)
        overflow = {}
        for key in sorted(state.slots):
            if not key.startswith(CACHE_PREFIX):
                continue
            aname = key[len(CACHE_PREFIX):]
            qn = qscale_name(aname)
            with obs_counters.scope(f"emb/{aname}/"):
                if qn in state.tables:
                    # int8 array: flush bit-copies codes AND the per-row
                    # (scale, offset) grid back into the table + sidecar
                    cache, table, slots, qsc, over = (
                        state.sparse_opt.cache_flush(
                            _pin_replicated(mesh, state.slots[key]),
                            state.tables[aname], state.slots[aname],
                            qscale=state.tables[qn]))
                    new_tables[qn] = qsc
                else:
                    cache, table, slots, over = state.sparse_opt.cache_flush(
                        _pin_replicated(mesh, state.slots[key]),
                        state.tables[aname], state.slots[aname])
            new_tables[aname] = table
            new_slots[aname] = slots
            new_slots[key] = _pin_replicated(mesh, cache)
            overflow[aname] = over
        return SparseTrainState(
            step=state.step,
            dense_params=state.dense_params,
            opt_state=state.opt_state,
            tables=new_tables,
            slots=new_slots,
            tx=state.tx,
            sparse_opt=state.sparse_opt,
        ), overflow

    if counters:
        def flush(state: SparseTrainState):
            with obs_counters.collect() as ctrs:
                new_state, overflow = _body(state)
            return new_state, overflow, dict(ctrs)
    else:
        flush = _body

    if not jit:
        return flush
    return jax.jit(flush, donate_argnums=(0,) if donate else ())


@dataclass(frozen=True)
class PipelinedSparseStep:
    """The three entry points of the cross-batch pipelined sparse step.

    ``prime(batch) -> carry`` starts the pipeline on the epoch's first
    batch (input-dist only, no training).  ``step(state, batch, carry,
    rng=None) -> (state, out, carry)`` issues the NEW batch's input-dist
    and trains the CARRIED one.  ``flush(state, carry, rng=None) ->
    (state, out)`` trains the last carried batch at epoch end.  ``carry``
    is a plain ``(transformed_batch, ctx)`` pytree — checkpoint cursors
    need not persist it: on resume the stream re-yields the carried batch
    and ``prime`` rebuilds the ctx (pure function of the ids).
    """

    prime: Callable
    step: Callable
    flush: Callable


def make_pipelined_sparse_train_step(
    coll: ShardedEmbeddingCollection,
    forward: Callable,
    *,
    donate: bool = True,
    jit: bool = True,
    batch_transform: Callable | None = None,
    with_aux: bool = False,
):
    """Cross-batch input-dist pipelining over the grouped exchange —
    torchrec ``TrainPipelineSparseDist`` parity (``torchrec/train.py``'s
    pipeline overlaps batch N+1's ``KJTAllToAll`` with batch N's
    fwd/bwd/update on a side CUDA stream).

    The TPU-native re-expression: :meth:`grouped_input_dist` reads NO
    tables (owner/virtual-id arithmetic is pure spec-derived statics), so
    batch N+1's bucketing + id ``all_to_all`` is issued at the TOP of the
    jitted step, before batch N's dense fwd/bwd and table update — with no
    data dependency between them, the XLA scheduler is free to overlap the
    collective with the compute instead of serialising 2 exchange phases
    behind the step.

    Semantics: losses, rng folds (by ``state.step``, which counts TRAINED
    batches) and state evolution are bit-identical to the eager grouped
    step — outputs just surface one ``step`` call later, with ``flush``
    draining the final batch.  Requires a ``grouped_a2a`` collection on a
    multi-shard mesh; hot/cold tables and ``dedup_lookup`` (both
    gspmd-only) do not compose.  Features on replicated tables keep their
    plain lookup/update path inside the same jitted program.
    """
    import inspect

    if not (coll.grouped_a2a and coll.mesh is not None and coll.n_shards > 1):
        raise ValueError(
            "the pipelined sparse step requires a grouped_a2a collection on "
            "a multi-shard mesh ([embeddings] grouped_a2a = true with "
            "model_parallel)")
    if coll.hot_tables():
        raise ValueError(
            "hot/cold tables do not compose with the pipelined sparse step "
            "(they require lookup mode 'gspmd')")
    if coll.cache_rows > 0:
        raise ValueError(
            "the update cache (cache_rows > 0) does not compose with the "
            "pipelined sparse step (it requires lookup mode 'gspmd')")
    features = list(coll.features())
    takes_rng = "dropout_rng" in inspect.signature(forward).parameters
    grouped_feats = tuple(
        f for f in features if coll.resolve(f)[1].sharding in ("row", "table"))
    grouped_arrays = tuple(sorted({coll.resolve(f)[0] for f in grouped_feats}))
    rest_feats = tuple(f for f in features if f not in grouped_feats)
    by_table_rest: dict[str, list[str]] = {}
    for f in rest_feats:
        by_table_rest.setdefault(coll.resolve(f)[0], []).append(f)

    def input_dist(batch):
        if batch_transform is not None:
            batch = batch_transform(batch)
        ctx = coll.grouped_input_dist({f: batch[f] for f in grouped_feats})
        return batch, ctx

    def train_on(state, batch, ctx, rng):
        ids = {f: batch[f] for f in features}
        step_rng = None
        if takes_rng and rng is not None:
            # same fold as the eager step: state.step counts trained batches
            step_rng = jax.random.fold_in(rng, state.step)

        def loss_from_embs(dense_params, embs):
            if takes_rng:
                return forward(dense_params, embs, batch, dropout_rng=step_rng)
            return forward(dense_params, embs, batch)

        embs = coll.grouped_lookup(
            state.tables, {f: ids[f] for f in grouped_feats}, ctx)
        if rest_feats:
            embs.update(coll.lookup(
                state.tables, {f: ids[f] for f in rest_feats},
                mode="alltoall"))
        loss, (g_dense, g_embs) = jax.value_and_grad(
            loss_from_embs, argnums=(0, 1), has_aux=with_aux
        )(state.dense_params, embs)
        aux = None
        if with_aux:
            loss, aux = loss
        if obs_counters.enabled():
            # global norms over the dense half and the gathered-vector
            # grads (the table-side signal without a [V, D] reduction);
            # param_norm walks the full tables — one HBM pass, priced into
            # telemetry.counters = true only
            obs_counters.emit("grad_norm",
                              optax.global_norm((g_dense, g_embs)))
            obs_counters.emit("param_norm", optax.global_norm(
                (state.dense_params, state.tables)))

        updates, new_opt_state = state.tx.update(
            g_dense, state.opt_state, state.dense_params)
        new_dense = optax.apply_updates(state.dense_params, updates)

        # same SR keying as the eager step: state.step counts trained
        # batches, so pipelining does not shift the key stream
        def _sr_key(aname):
            return (_make_sr_key(state.step, aname)
                    if _array_is_narrow(state, aname) else None)

        new_tables = dict(state.tables)
        new_slots = dict(state.slots)
        g_narrow = any(_array_is_narrow(state, a) for a in grouped_arrays)
        gt, gs = coll.grouped_update(
            state.sparse_opt, state.tables, state.slots,
            {f: ids[f] for f in grouped_feats},
            {f: g_embs[f] for f in grouped_feats},
            sr_key=(_make_sr_key(state.step, "__grouped_update__")
                    if g_narrow else None))
        new_tables.update(gt)
        new_slots.update(gs)
        for tname, feats in by_table_rest.items():
            id_list, bound = [], 0
            for f in feats:
                _, spec, off = coll.resolve(f)
                flat = jnp.where(ids[f] >= 0, ids[f] + off, -1).reshape(-1)
                id_list.append(flat)
                bound += min(flat.shape[0], spec.num_embeddings)
            all_ids = jnp.concatenate(id_list)
            all_grads = jnp.concatenate([
                g_embs[f].reshape(-1, g_embs[f].shape[-1]) for f in feats])
            md = -(-bound // 8) * 8 if bound < all_ids.shape[0] else None
            if (coll.array_is_int8(tname)
                    and state.tables[tname].ndim == 2):
                # plain 2D int8 threads the separate qscale sidecar; fat
                # int8 byte containers carry it in-line
                qn = qscale_name(tname)
                (new_tables[tname], new_slots[tname],
                 new_tables[qn]) = coll.sparse_update(
                    state.sparse_opt, tname,
                    state.tables[tname], state.slots[tname], all_ids,
                    all_grads, max_distinct=md, sr_key=_sr_key(tname),
                    qscale=state.tables[qn],
                )
            else:
                new_tables[tname], new_slots[tname] = coll.sparse_update(
                    state.sparse_opt, tname,
                    state.tables[tname], state.slots[tname], all_ids,
                    all_grads, max_distinct=md, sr_key=_sr_key(tname),
                )

        new_state = SparseTrainState(
            step=state.step + 1,
            dense_params=new_dense,
            opt_state=new_opt_state,
            tables=new_tables,
            slots=new_slots,
            tx=state.tx,
            sparse_opt=state.sparse_opt,
        )
        return new_state, (loss, aux) if with_aux else loss

    def prime(batch):
        return input_dist(batch)

    def step(state, batch, carry, rng=None):
        # the NEW batch's dist first: no table dependency, so the scheduler
        # may overlap its id all_to_all with everything below
        new_carry = input_dist(batch)
        cur_batch, ctx = carry
        state, out = train_on(state, cur_batch, ctx, rng)
        return state, out, new_carry

    def flush(state, carry, rng=None):
        cur_batch, ctx = carry
        return train_on(state, cur_batch, ctx, rng)

    if jit:
        d = (0,) if donate else ()
        return PipelinedSparseStep(
            prime=jax.jit(prime),
            step=jax.jit(step, donate_argnums=d),
            flush=jax.jit(flush, donate_argnums=d),
        )
    return PipelinedSparseStep(prime=prime, step=step, flush=flush)
