"""Train state: params + optimizer state + (optional) loss-scale, as a pytree.

Supersedes the three reference variants: flax ``TrainState`` + optax adamw
(``jax-flax/train.py:17-27``), the DynamicScale-carrying subclass
(``jax-flax/train_dp.py:28-45``), and torchrec's ``CombinedOptimizer`` of a
fused in-backward sparse optimizer + dense Adam (``torchrec/train.py:248-254``).
The sparse/dense split is mirrored here: params under ``SPARSE_COLLECTION``
table names can be excluded from the dense optax transform and updated by the
row-sparse path in ``tdfo_tpu/parallel/embedding`` instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import optax

from tdfo_tpu.core.precision import DynamicLossScale

__all__ = ["TrainState", "make_adamw"]


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    loss_scale: DynamicLossScale | None
    apply_fn: Callable = field(metadata=dict(static=True))
    tx: optax.GradientTransformation = field(metadata=dict(static=True))

    @classmethod
    def create(cls, *, apply_fn, params, tx, loss_scale=None) -> "TrainState":
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            loss_scale=loss_scale,
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return TrainState(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            loss_scale=self.loss_scale,
            apply_fn=self.apply_fn,
            tx=self.tx,
        )


def make_adamw(learning_rate: float, weight_decay: float) -> optax.GradientTransformation:
    """The reference's optimizer everywhere (jax-flax/train.py:24-26,
    tensorflow2/train.py:13, torchrec fused ADAM train.py:236-240)."""
    return optax.adamw(learning_rate=learning_rate, weight_decay=weight_decay)
