"""jit-compiled train/eval steps, sharding-annotated (pjit-on-mesh, not pmap).

The reference's per-backend step functions (``jax-flax/train.py:30-49``,
``train_dp.py:48-91``, ``tensorflow2/train_dp.py:54-104``) collapse into one
factory: the SAME step function serves single-chip and any mesh — data
parallelism is a sharding spec on the batch, gradient sync is inserted by
GSPMD (replacing explicit ``jax.lax.pmean`` at ``train_dp.py:63`` and
``strategy.reduce`` at ``tensorflow2/train_dp.py:79``).

Mixed precision: loss-scale branch + non-finite rollback re-expresses
``jax-flax/train_dp.py:55-81`` SPMD-safely (the finite check is a global
all-reduce under GSPMD, so every device takes the same branch).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdfo_tpu.core.mesh import DATA_AXIS
from tdfo_tpu.core.precision import scale_loss, unscale_grads
from tdfo_tpu.obs import counters as obs_counters
from tdfo_tpu.train.state import TrainState

__all__ = ["bce_with_logits_loss", "make_train_step", "make_eval_step", "make_multi_step"]


def bce_with_logits_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Sigmoid BCE (jax-flax/train.py:36-38; tensorflow2 BinaryCrossentropy
    from_logits=True, tensorflow2/train.py:12)."""
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def make_train_step(
    loss_fn: Callable | None = None,
    *,
    mesh: Mesh | None = None,
    donate_state: bool = True,
    jit: bool = True,
    with_aux: bool = False,
):
    """Build the jitted train step.

    ``loss_fn(params, apply_fn, batch) -> scalar`` defaults to sigmoid BCE on
    ``batch["label"]`` (TwoTower workload).  With ``mesh``, inputs are
    constrained batch-sharded over ``data`` and the state replicated (the
    replicate/shard/prefetch plumbing of ``jax-flax/train_dp.py:186,210-211``
    reduced to sharding annotations); parameter shardings are taken from the
    arrays themselves so model-parallel params keep their specs.

    ``with_aux=True``: ``loss_fn`` must return ``(scalar, aux)`` (the default
    returns the logits as aux) and the step returns ``(state, (loss, aux))``
    — how the trainer streams per-epoch TRAIN metrics (the reference computes
    train-side ROC-AUC every epoch, ``jax-flax/train_dp.py:190,219-220``)
    without a second forward pass.
    """
    loss_fn = loss_fn or (_default_loss_aux if with_aux else _default_loss)

    def step(state: TrainState, batch) -> tuple[TrainState, jax.Array]:
        if mesh is not None:
            batch = jax.lax.with_sharding_constraint(
                batch, NamedSharding(mesh, P(DATA_AXIS))
            )

        def scaled_loss(params):
            out = loss_fn(params, state.apply_fn, batch)
            loss, aux = out if with_aux else (out, None)
            return scale_loss(loss, state.loss_scale), aux

        (loss, aux), grads = jax.value_and_grad(scaled_loss, has_aux=True)(
            state.params
        )
        grads, finite = unscale_grads(grads, state.loss_scale)
        if obs_counters.enabled():
            obs_counters.emit("grad_norm", optax.global_norm(grads))
            obs_counters.emit("param_norm", optax.global_norm(state.params))

        new_state = state.apply_gradients(grads)
        if state.loss_scale is not None:
            loss = loss / state.loss_scale.scale
            # non-finite rollback (jax-flax/train_dp.py:67-81): keep old
            # params/opt_state when any grad overflowed, always advance step
            # and the scale schedule.
            new_state = TrainState(
                step=new_state.step,
                params=jax.tree.map(
                    partial(jnp.where, finite), new_state.params, state.params
                ),
                opt_state=jax.tree.map(
                    partial(jnp.where, finite), new_state.opt_state, state.opt_state
                ),
                loss_scale=state.loss_scale.update(finite),
                apply_fn=state.apply_fn,
                tx=state.tx,
            )
        return new_state, ((loss, aux) if with_aux else loss)

    if not jit:
        return step
    donate = (0,) if donate_state else ()
    return jax.jit(step, donate_argnums=donate)


def make_multi_step(step_fn: Callable, *, donate_state: bool = True):
    """Compile a ``steps_per_execution`` loop into ONE device dispatch.

    TF parity (``tensorflow2/utils.py:10-38`` ``steps_per_execution`` ->
    ``model.compile``): ``multi(state, stack, *rest)`` scans ``step_fn`` (an
    UNJITTED step from a factory called with ``jit=False``) over a stacked
    batch pytree (leading axis = steps), returning the final state and the
    mean loss over the chunk.  Host round trips per step vanish; XLA overlaps
    the scan body's transfers and compute.

    ``*rest`` (e.g. the dropout rng of the sparse step) is closed over
    per-chunk; steps stay distinct because the step folds the rng with the
    step counter.  ``with_aux`` steps are NOT accepted here — their chunked
    composition (metric folding in the scan carry) lives in the trainer's
    ``_wrap_auc_multi_step``.
    """

    def multi(state, stack, *rest):
        def body(st, batch):
            st, loss = step_fn(st, batch, *rest)
            return st, loss

        state, losses = jax.lax.scan(body, state, stack)
        return state, losses.mean()

    return jax.jit(multi, donate_argnums=(0,) if donate_state else ())


def _default_loss(params, apply_fn, batch):
    logits = apply_fn({"params": params}, batch)
    return bce_with_logits_loss(logits, batch["label"])


def _default_loss_aux(params, apply_fn, batch):
    logits = apply_fn({"params": params}, batch)
    return bce_with_logits_loss(logits, batch["label"]), logits


def make_eval_step(forward: Callable | None = None, *, mesh: Mesh | None = None):
    """Eval step returning (loss, logits) — jax-flax/train.py:44-49 parity."""

    def step(state: TrainState, batch):
        if mesh is not None:
            batch = jax.lax.with_sharding_constraint(
                batch, NamedSharding(mesh, P(DATA_AXIS))
            )
        fwd = forward or (lambda p, f, b: f({"params": p}, b))
        logits = fwd(state.params, state.apply_fn, batch)
        loss = bce_with_logits_loss(logits, batch["label"])
        return loss, logits

    return jax.jit(step)
