"""Online serving subsystem: checkpoint export, corpus build, sharded exact
MIPS retrieval, train-parity CTR scoring, and the micro-batching frontend.

The inference half of the ROADMAP north star ("serves heavy traffic from
millions of users").  Layering, offline to online:

  * :mod:`~tdfo_tpu.serve.export`    — train state -> serving bundle on disk
    (optimizer slots dropped, hot heads merged back, stamped + refused on
    mismatch like training restores).
  * :mod:`~tdfo_tpu.serve.scoring`   — bundle -> jitted CTR scoring step whose
    logits are bitwise the training eval step's (train/serve skew = 0).
  * :mod:`~tdfo_tpu.serve.corpus`    — batched item-tower sweep materialising
    the [N_items, D] candidate corpus, sharded over the mesh data axis.
  * :mod:`~tdfo_tpu.serve.retrieval` — sharded exact top-k MIPS, bitwise-equal
    to a single-device argsort reference.
  * :mod:`~tdfo_tpu.serve.frontend`  — deadline/bucket micro-batching request
    loop with per-request latency JSONL, bounded-queue load shedding, and
    drain-and-flip hot swap; ``launch.py serve`` entry point.
  * :mod:`~tdfo_tpu.serve.swap`      — delta-chain bundle store: digest-
    verified ingest/apply, atomic publication + CURRENT/CANARY pointers,
    crash recovery, corrupt-delta quarantine, rejection ledger, retention
    GC and degraded mode.
  * :mod:`~tdfo_tpu.serve.fleet`     — multi-replica frontends following the
    shared store pointers (canary cohort + per-replica request logs +
    held-out heartbeats), the serving tier the gated online loop watches.
"""

from tdfo_tpu.serve.corpus import Corpus, build_corpus, synthetic_item_features
from tdfo_tpu.serve.export import (
    BUNDLE_VERSION,
    QSCALE_LAYOUT,
    ServingBundle,
    apply_delta_arrays,
    bundle_digest,
    export_bundle,
    export_corpus,
    export_delta,
    load_bundle,
    load_corpus,
    merged_tables,
)
from tdfo_tpu.serve.fleet import ReplicaFrontend, ServingFleet
from tdfo_tpu.serve.frontend import MicroBatcher, serve_from_config
from tdfo_tpu.serve.retrieval import make_retrieval, mips_scores, retrieval_reference
from tdfo_tpu.serve.scoring import make_scorer
from tdfo_tpu.serve.swap import (
    BundleStore,
    CorruptDeltaError,
    DeltaChainError,
    DeltaPoller,
    SwapController,
)

__all__ = [
    "BUNDLE_VERSION",
    "BundleStore",
    "Corpus",
    "CorruptDeltaError",
    "DeltaChainError",
    "DeltaPoller",
    "MicroBatcher",
    "QSCALE_LAYOUT",
    "ReplicaFrontend",
    "ServingBundle",
    "ServingFleet",
    "SwapController",
    "apply_delta_arrays",
    "build_corpus",
    "bundle_digest",
    "export_bundle",
    "export_corpus",
    "export_delta",
    "load_bundle",
    "load_corpus",
    "make_retrieval",
    "make_scorer",
    "merged_tables",
    "mips_scores",
    "retrieval_reference",
    "serve_from_config",
    "synthetic_item_features",
]
