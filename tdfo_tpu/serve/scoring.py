"""Train-parity CTR scoring from a serving bundle.

The serving forward IS the training eval forward (``train/ctr.py
make_ctr_sparse_eval_step`` for the DMP regime, ``TwoTower.__call__`` for the
dense regime) re-pointed at the bundle's merged tables: same backbone module,
same lookup program (replicated tables, ``mode="gspmd"`` — plain row
gathers), same dtype policy.  That is what makes train/serve skew exactly
zero for f32 bundles (``tests/test_serve.py``), the property Monolith calls
out as the serving contract and the reference's eval forward
(``jax-flax/train_dp.py:233-240``) implies but never packages.

Scoring steps are jitted with the request batch DONATED (the batch is
per-request garbage the moment logits exist) and take tables/params as
ARGUMENTS, never closures — big closed-over constants serialize into the
compile payload (CLAUDE.md tunnel rules).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp

from tdfo_tpu.core.mesh import replicated_sharding
from tdfo_tpu.models.twotower import (
    TWOTOWER_CATEGORICAL,
    TWOTOWER_CONTINUOUS,
    TWOTOWER_ITEM_CATEGORICAL,
    _FEATURE_TO_INPUT,
    Tower,
    TwoTower,
    TwoTowerBackbone,
)
from tdfo_tpu.serve.export import ServingBundle

__all__ = ["Scorer", "make_scorer"]


@dataclass
class Scorer:
    """Jitted serving programs bound to one bundle's parameters.

    ``score(batch) -> [B] f32 logits`` is the CTR request path (batch
    donated).  ``user_embed`` / ``item_embed`` map a batch to its tower
    vectors — the retrieval query/corpus halves (TwoTower only; ``None``
    for DLRM, whose interaction head does not factorize into towers).
    """

    model: str
    embed_dim: int
    cont_columns: tuple[str, ...]
    features: tuple[str, ...]  # categorical input columns score() consumes
    _score: Callable = field(repr=False)
    _params: tuple = field(repr=False)  # trailing args for the jitted fns
    _user: Callable | None = field(repr=False, default=None)
    _item: Callable | None = field(repr=False, default=None)

    def score(self, batch: Mapping[str, jax.Array]) -> jax.Array:
        return self._score(dict(batch), *self._params)

    def user_embed(self, batch: Mapping[str, jax.Array]) -> jax.Array:
        if self._user is None:
            raise ValueError(f"{self.model!r} has no user tower")
        return self._user(dict(batch), *self._params)

    def item_embed(self, batch: Mapping[str, jax.Array]) -> jax.Array:
        if self._item is None:
            raise ValueError(f"{self.model!r} has no item tower")
        return self._item(dict(batch), *self._params)

    def score_cache_size(self) -> int:
        """Compiled-program count of the scoring step (one per padded batch
        shape) — the frontend's compile-count regression hook."""
        return self._score._cache_size()


def _device_tree(tree: Any, mesh) -> Any:
    put = (partial(jax.device_put, device=replicated_sharding(mesh))
           if mesh is not None else jnp.asarray)
    return jax.tree.map(put, tree)


def make_scorer(bundle: ServingBundle, *, mesh=None):
    """Bundle -> :class:`Scorer`.  ``mesh`` replicates the parameters over
    it (serving tables are replicated; retrieval shards the CORPUS, not the
    tables — ``serve/retrieval.py``).  Bert4rec bundles dispatch to the
    sequence scorer (``serve/seq_scoring.py``) so pointer followers — fleet
    replicas, swap controllers — serve either family through one builder."""
    if bundle.model == "bert4rec":
        from tdfo_tpu.serve.seq_scoring import make_seq_scorer

        return make_seq_scorer(bundle, mesh=mesh)
    if bundle.kind == "dense":
        return _dense_scorer(bundle, mesh)
    return _sparse_scorer(bundle, mesh)


def _dense_scorer(bundle: ServingBundle, mesh) -> Scorer:
    model = TwoTower(size_map=dict(bundle.size_map),
                     embed_dim=bundle.embed_dim, dtype=bundle.jax_dtype)
    params = _device_tree(bundle.params, mesh)

    @partial(jax.jit, donate_argnums=(0,))
    def score(batch, params):
        return model.apply({"params": params}, batch)

    @jax.jit
    def user(batch, params):
        return model.apply({"params": params}, batch,
                           method="user_embeddings")

    @jax.jit
    def item(batch, params):
        return model.apply({"params": params}, batch,
                           method="item_embeddings")

    return Scorer(
        model=bundle.model, embed_dim=bundle.embed_dim,
        cont_columns=tuple(TWOTOWER_CONTINUOUS),
        features=tuple(_FEATURE_TO_INPUT[f] for f in TWOTOWER_CATEGORICAL),
        _score=score, _params=(params,), _user=user, _item=item,
    )


def _sparse_scorer(bundle: ServingBundle, mesh) -> Scorer:
    from tdfo_tpu.models.dlrm import DLRMBackbone, generic_embedding_specs
    from tdfo_tpu.models.twotower import ctr_embedding_specs
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection

    dtype = bundle.jax_dtype
    twotower_names = {f"{f}_embed" for f in TWOTOWER_CATEGORICAL}
    if set(bundle.tables) == twotower_names:
        specs = ctr_embedding_specs(bundle.size_map, bundle.embed_dim,
                                    sharding="replicated",
                                    fused_threshold=None)
    else:
        specs = generic_embedding_specs(bundle.size_map, bundle.cat_columns,
                                        bundle.embed_dim,
                                        sharding="replicated",
                                        fused_threshold=None)
    # replicated + non-fused + unstacked: every logical table keeps its own
    # [V, d] array under its own name, exactly the merged-bundle layout
    coll = ShardedEmbeddingCollection(specs, mesh=mesh)
    if set(bundle.tables) != set(coll.specs):
        raise ValueError(
            f"bundle tables {sorted(bundle.tables)} do not match the "
            f"{bundle.model!r} schema {sorted(coll.specs)} — wrong bundle "
            "for this model/config")
    tables = _device_tree(dict(bundle.tables), mesh)
    dense_params = _device_tree(bundle.dense_params, mesh)
    features = tuple(coll.features())

    if bundle.model == "dlrm":
        backbone = DLRMBackbone(embed_dim=bundle.embed_dim, dtype=dtype,
                                cat_columns=tuple(bundle.cat_columns),
                                cont_columns=tuple(bundle.cont_columns))
    else:
        backbone = TwoTowerBackbone(embed_dim=bundle.embed_dim, dtype=dtype)

    @partial(jax.jit, donate_argnums=(0,))
    def score(batch, tables, dense_params):
        embs = coll.lookup(tables, {f: batch[f] for f in features},
                           mode="gspmd")
        return backbone.apply({"params": dense_params}, embs, batch)

    user = item = None
    if bundle.model == "twotower":
        item_cols = tuple(
            _FEATURE_TO_INPUT[f] for f in TWOTOWER_ITEM_CATEGORICAL)
        tower = Tower(bundle.embed_dim, dtype=dtype)

        @jax.jit
        def user(batch, tables, dense_params):
            embs = coll.lookup(tables, {"user_id": batch["user_id"]},
                               mode="gspmd")
            return tower.apply({"params": dense_params["user_tower"]},
                               embs["user_id"].astype(dtype))

        @jax.jit
        def item(batch, tables, dense_params):
            embs = coll.lookup(tables, {c: batch[c] for c in item_cols},
                               mode="gspmd")
            parts = [embs[c].astype(dtype) for c in item_cols]
            parts += [batch[c].astype(dtype)[:, None]
                      for c in TWOTOWER_CONTINUOUS]
            return tower.apply({"params": dense_params["item_tower"]},
                               jnp.concatenate(parts, axis=-1))

    return Scorer(
        model=bundle.model, embed_dim=bundle.embed_dim,
        cont_columns=tuple(bundle.cont_columns), features=features,
        _score=score, _params=(tables, dense_params), _user=user, _item=item,
    )
