"""Socket ingress: the fleet's load balancer over per-replica health.

One process owns ingress (the online supervisor, the loadgen harness, or
``launch.py serve-fleet``); N replica processes own listeners
(``serve/replica_main.py``).  This module keeps one persistent framed
connection per replica (``serve/wire.py`` — the socket monopoly; ingress
never opens a socket itself, it asks ``wire.connect``) and routes each
request by **power-of-two-choices** (Mitzenmacher 2001: sample two distinct
replicas, send to the less loaded — within a constant of optimal balance at
a fraction of full-scan cost) over the ``queue_depth``/``batch_fill`` pair
that already rides every heartbeat record (``serve/fleet.py heartbeat``).

Staleness eviction is the PR-16 heartbeat fix: a dead or stalled replica
used to keep its last ``queue_depth`` forever and kept winning the balance.
Every observation is stamped at RECEIPT with the trace clock — monotonic
clocks are not comparable across processes, so the sender's stamp is
useless here — and :meth:`Ingress.pick` refuses replicas whose freshness
(``_trace.elapsed_ms(hb_at)``, never a raw clock difference) exceeds
``[serving] heartbeat_stale_ms``.  A silent replica therefore stops
receiving traffic within one eviction window, no supervisor round trip
needed.  Score REPLIES double as observations: a replica actively
answering is fresh by construction, so streaming traffic needs no side
heartbeat channel.

Shed accounting is never silent: a ``null`` score reply (the replica's
admission control shed the request) and a request failed by a mid-flight
disconnect both land in counters the caller reports.
"""

from __future__ import annotations

import random
import select
import time
from pathlib import Path
from typing import Any, Callable, Mapping

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.serve import wire

__all__ = ["Ingress"]


class Ingress:
    """Persistent connections + P2C balancing + staleness eviction.

    ``elapsed_ms``/``rng``/``sleep`` are injectable so tests pin the
    eviction window and the balance draw without wall-clock sleeps.
    """

    def __init__(self, paths: Mapping[int, str | Path], *,
                 stale_ms: float = 5000.0,
                 max_frame: int = wire.MAX_FRAME_BYTES,
                 connect_retries: int = 10,
                 connect_base_ms: float = 10.0,
                 rng: random.Random | None = None,
                 elapsed_ms: Callable[[float], float] | None = None,
                 sleep: Callable[[float], None] = time.sleep,
                 logger=None):
        self._paths = {int(k): Path(p) for k, p in paths.items()}
        self._stale_ms = float(stale_ms)
        self._max_frame = int(max_frame)
        self._connect_retries = int(connect_retries)
        self._connect_base_ms = float(connect_base_ms)
        self._rng = rng or random.Random()
        self._elapsed_ms = elapsed_ms or _trace.elapsed_ms
        self._sleep = sleep
        self._logger = logger
        self._conns: dict[int, Any] = {}
        # replica -> {"queue_depth", "batch_fill", "hb_at"}; hb_at is OUR
        # receipt stamp, not the sender's (cross-process monotonic clocks)
        self._stats: dict[int, dict[str, Any]] = {}
        self._inflight: dict[Any, tuple[int, float]] = {}  # rid -> (k, t0)
        self.completed: dict[Any, np.ndarray | None] = {}
        self.latencies_ms: list[float] = []
        self.sheds = 0
        self.failures = 0  # requests lost to a mid-flight disconnect

    # -------------------------------------------------------- connections

    def connect(self, k: int) -> None:
        """(Re)connect replica ``k``, dropping any stale connection.  A
        fresh connection counts as an observation: a replica that just
        accepted us is alive, and routable until its first eviction
        window closes."""
        self.disconnect(k)
        self._conns[k] = wire.connect(
            self._paths[k], attempts=self._connect_retries,
            base_ms=self._connect_base_ms, rng=self._rng, sleep=self._sleep)
        self.observe(k, {})

    def connect_all(self) -> None:
        for k in sorted(self._paths):
            self.connect(k)

    def disconnect(self, k: int) -> None:
        conn = self._conns.pop(k, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        self._fail_inflight(k)

    def drop(self, k: int) -> None:
        """Forget replica ``k`` entirely (quarantined by the supervisor):
        no connection, no stats, never picked again."""
        self.disconnect(k)
        self._paths.pop(k, None)
        self._stats.pop(k, None)

    def close(self) -> None:
        for k in list(self._conns):
            self.disconnect(k)

    def _fail_inflight(self, k: int) -> None:
        lost = [rid for rid, (rk, _) in self._inflight.items() if rk == k]
        for rid in lost:
            self._inflight.pop(rid)
            self.completed[rid] = None
            self.failures += 1
        if lost and self._logger is not None:
            self._logger.log(event="ingress_inflight_lost", replica=k,
                             requests=len(lost))

    # ----------------------------------------------------------- balance

    def observe(self, k: int, rec: Mapping[str, Any]) -> None:
        """Fold a health observation (heartbeat record or score reply) into
        the balance state, stamped at receipt."""
        self._stats[k] = {
            "queue_depth": int(rec.get("queue_depth", 0)),
            "batch_fill": float(rec.get("batch_fill", 0.0)),
            "hb_at": _trace.clock(),
        }

    def fresh(self) -> list[int]:
        """Connected replicas whose last observation is within the
        eviction window."""
        out = []
        for k in sorted(self._conns):
            st = self._stats.get(k)
            if st is None:
                continue
            if self._elapsed_ms(st["hb_at"]) <= self._stale_ms:
                out.append(k)
        return out

    def pick(self) -> int:
        """Power-of-two-choices over the fresh replicas: two distinct
        samples, lower ``queue_depth`` wins, ties broken by lower
        ``batch_fill`` then lower id (deterministic under an injected
        rng).  An empty fresh set is a loud error — routing a request to
        a known-stale replica would hide a dead fleet."""
        fresh = self.fresh()
        if not fresh:
            evicted = sorted(set(self._conns) - set(fresh))
            raise RuntimeError(
                "ingress has no fresh replica to route to "
                f"(stale/evicted: {evicted}, window {self._stale_ms} ms) — "
                "the fleet is dead or the supervisor has not respawned "
                "anyone yet")
        if len(fresh) == 1:
            return fresh[0]
        a, b = self._rng.sample(fresh, 2)
        ka = (self._stats[a]["queue_depth"], self._stats[a]["batch_fill"], a)
        kb = (self._stats[b]["queue_depth"], self._stats[b]["batch_fill"], b)
        return a if ka <= kb else b

    # ------------------------------------------------------------ traffic

    def submit(self, rid, feats: Mapping[str, np.ndarray]) -> int:
        """Route one score request; returns the replica it went to."""
        k = self.pick()
        try:
            wire.send_msg(self._conns[k],
                          {"type": "score", "rid": rid,
                           "feats": wire.encode_feats(feats)},
                          max_frame=self._max_frame)
        except OSError:
            self.disconnect(k)
            raise
        self._inflight[rid] = (k, _trace.clock())
        return k

    def poll(self, timeout_s: float = 0.0) -> int:
        """Drain readable replies; returns how many completed.  A
        disconnect mid-poll fails that replica's in-flight requests
        (counted, never silent) and drops the connection — the caller's
        next ``check()``/``connect()`` decides recovery."""
        done = 0
        while self._conns:
            socks = {conn: k for k, conn in self._conns.items()}
            readable, _, _ = select.select(list(socks), [], [], timeout_s)
            if not readable:
                return done
            for conn in readable:
                k = socks[conn]
                try:
                    msg = wire.recv_msg(conn, max_frame=self._max_frame)
                except wire.WireError:
                    self.disconnect(k)
                    continue
                self._complete(k, msg)
                done += 1
            timeout_s = 0.0  # only the first select waits
        return done

    def _complete(self, k: int, msg: Mapping[str, Any]) -> None:
        """Fold one score reply: latency from OUR submit stamp, balance
        observation from the replica's queue state, trace span for the
        offline assembler."""
        rid = msg.get("rid")
        self.observe(k, msg)
        if rid is None or rid not in self._inflight:
            return
        _, t0 = self._inflight.pop(rid)
        ms = self._elapsed_ms(t0)
        scores = msg.get("scores")
        if scores is None:
            self.completed[rid] = None
            self.sheds += 1
        else:
            self.completed[rid] = np.asarray(scores, np.float32)
            self.latencies_ms.append(ms)
        _trace.emit("ingress", "ingress_request", replica=k, rid=str(rid),
                    latency_ms=ms, shed=scores is None,
                    queue_depth=int(msg.get("queue_depth", 0)))

    def inflight(self) -> int:
        return len(self._inflight)

    # --------------------------------------------------------------- rpc

    def rpc(self, k: int, msg: Mapping[str, Any]) -> dict[str, Any]:
        """Synchronous round trip to replica ``k`` (sync / heartbeat /
        probe / drain).  Score replies that arrive first are folded into
        ``completed`` — the replica flushes its pending scores before
        answering a drain, and this loop preserves that ordering."""
        conn = self._conns[k]
        wire.send_msg(conn, msg, max_frame=self._max_frame)
        while True:
            reply = wire.recv_msg(conn, max_frame=self._max_frame)
            if "rid" in reply:
                self._complete(k, reply)
                continue
            return reply
