"""Process supervisor + out-of-process fleet facade.

:class:`ProcessSupervisor` owns the ``subprocess`` monopoly for
``tdfo_tpu/`` (enforced by a ``tests/test_quality.py`` AST rule;
``serve/wire.py`` holds the matching socket monopoly): it spawns each
replica as ``python -m tdfo_tpu.serve.replica_main <spec.json>`` with the
listener pre-bound in the supervisor and handed down by fd (socket
activation — connects succeed from the instant of spawn; the child's
jax cold-start drains the backlog when it is ready), detects
deaths by ``poll()``, respawns with capped exponential backoff through the
single ``utils/retry.backoff_delay`` law, and refuses flap-looping — a
replica that dies ``[serving] flap_max_deaths`` times within
``flap_window_s`` seconds is quarantined permanently and the fleet degrades
to the survivors, loudly (a quarantine is logged, never silent).

:class:`ProcessFleet` is the duck-typed drop-in for
``serve/fleet.ServingFleet`` that ``train/online.py`` selects when
``[serving] fleet_mode = "process"``: same ``sync`` / ``heartbeat`` /
``mark_canary_watch`` / ``probe_each`` / ``run`` / ``versions`` surface,
but every replica lives across a real OS boundary — ``sync`` is an RPC
fan-out, ``run`` routes through the power-of-two-choices ingress, and the
death drill is a real ``SIGKILL`` (``[faults] kill_replica_signal``)
whose respawned lineage re-follows ``CURRENT``/``CANARY`` by
(version, digest) because the child re-reads the same spec file and the
fleet re-sends its full skew/slow digest sets on EVERY sync (idempotent
re-arm — a respawn missing a previously armed fault would diverge from
the unkilled reference).

Clock discipline: death timestamps come from an injectable ``clock``
attribute (default ``time.monotonic``) and windows compare those floats
locally; respawn delays go through ``backoff_delay`` and an injectable
``sleep`` — tests pin all three and never wait wall-clock.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Callable, Mapping

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.serve import wire
from tdfo_tpu.serve.ingress import Ingress
from tdfo_tpu.utils import faults as _faults
from tdfo_tpu.utils.retry import backoff_delay

__all__ = ["ProcessSupervisor", "ProcessFleet"]


class ProcessSupervisor:
    """Spawn / monitor / respawn replica processes with flap quarantine.

    ``spec_paths`` maps replica id -> the spec JSON its child re-reads on
    every (re)spawn — the spec file IS the lineage identity, which is what
    makes a respawn re-follow the store instead of starting a new replica.
    """

    def __init__(self, spec_paths: Mapping[int, str | Path], *,
                 respawn_base_ms: float = 50.0,
                 respawn_max_ms: float = 2000.0,
                 flap_window_s: float = 30.0,
                 flap_max_deaths: int = 3,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: random.Random | None = None,
                 popen: Callable[..., Any] | None = None,
                 logger=None):
        self._spec_paths = {int(k): Path(p) for k, p in spec_paths.items()}
        self._respawn_base_s = float(respawn_base_ms) / 1000.0
        self._respawn_max_s = float(respawn_max_ms) / 1000.0
        self._flap_window_s = float(flap_window_s)
        self._flap_max_deaths = int(flap_max_deaths)
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        self._popen = popen or self._spawn_child
        self._logger = logger
        self._procs: dict[int, Any] = {}
        self._death_times: dict[int, list[float]] = {k: []
                                                     for k in self._spec_paths}
        self._consecutive: dict[int, int] = {k: 0 for k in self._spec_paths}
        self.quarantined: set[int] = set()
        self.respawns: dict[int, int] = {k: 0 for k in self._spec_paths}

    @staticmethod
    def _spawn_child(spec_path: Path):
        """Spawn one replica child, socket-activation style.

        The SUPERVISOR binds the listener and passes the fd
        (``--listen-fd`` + ``pass_fds``), so the socket accepts
        connections from the instant ``Popen`` returns — the child's
        cold-start (interpreter + jax import, minutes on a loaded
        single-core box) queues connects in the kernel backlog instead
        of racing the ingress's retry budget.  Child stdio goes to
        ``replica-<k>.log`` beside the spec, never an inherited pipe: an
        orphaned child holding a test harness's pipe write-end would
        wedge the harness's ``communicate()`` long after the parent
        died.
        """
        spec = json.loads(Path(spec_path).read_text())
        sock_path = spec.get("socket")
        argv = [sys.executable, "-m", "tdfo_tpu.serve.replica_main",
                str(spec_path)]
        log_path = Path(spec_path).with_suffix(".log")
        with open(log_path, "ab") as logf:
            if sock_path is None:  # bare spec: child binds for itself
                return subprocess.Popen(
                    argv, stdin=subprocess.DEVNULL, stdout=logf,
                    stderr=logf)
            listener = wire.listen(sock_path)
            try:
                fd = listener.fileno()
                return subprocess.Popen(
                    argv + ["--listen-fd", str(fd)],
                    stdin=subprocess.DEVNULL, stdout=logf, stderr=logf,
                    pass_fds=(fd,))
            finally:
                # the child's inherited fd keeps the socket bound and
                # its backlog live; this only drops the parent's copy
                listener.close()

    # ----------------------------------------------------------- lifecycle

    def spawn(self, k: int) -> None:
        if k in self.quarantined:
            raise RuntimeError(f"replica {k} is quarantined (flap-looping); "
                               "refusing to respawn it")
        self._procs[k] = self._popen(self._spec_paths[k])

    def spawn_all(self) -> None:
        for k in sorted(self._spec_paths):
            self.spawn(k)

    def pid(self, k: int) -> int | None:
        proc = self._procs.get(k)
        return None if proc is None else proc.pid

    def alive_ids(self) -> list[int]:
        return [k for k, p in sorted(self._procs.items())
                if p is not None and p.poll() is None]

    def kill(self, k: int, sig: int = signal.SIGKILL) -> None:
        """Deliver a real signal to replica ``k``'s pid — the
        ``kill_replica_signal`` drill's hammer."""
        proc = self._procs.get(k)
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, sig)
            proc.wait()  # reap; poll() in check() then sees the death

    def quarantine(self, k: int) -> None:
        """Force-quarantine (the in-process ``kill_replica_nth`` twin for
        process fleets: the replica is terminated and never respawned, so
        membership stays degraded exactly like the soft-kill path)."""
        if k in self.quarantined:
            return
        self.kill(k)
        self._procs.pop(k, None)
        self.quarantined.add(k)
        self._note_quarantine(k, reason="forced")

    def _note_quarantine(self, k: int, *, reason: str) -> None:
        print(f"[supervisor] replica {k} QUARANTINED ({reason}); fleet "
              f"degrades to the survivors", flush=True)
        if self._logger is not None:
            self._logger.log(event="replica_quarantined", replica=k,
                             reason=reason)
        _trace.emit("supervisor", "replica_quarantined", replica=k,
                    reason=reason)

    def check(self) -> list[int]:
        """Detect deaths, respawn with backoff, quarantine flappers.
        Returns the ids respawned THIS call (the ingress must reconnect
        them)."""
        respawned: list[int] = []
        for k in sorted(self._procs):
            proc = self._procs[k]
            if proc is None or proc.poll() is None:
                continue
            code = proc.returncode
            self._procs[k] = None
            now = self._clock()
            window = [t for t in self._death_times[k]
                      if now - t <= self._flap_window_s]
            window.append(now)
            self._death_times[k] = window
            self._consecutive[k] += 1
            if self._logger is not None:
                self._logger.log(event="replica_died", replica=k,
                                 returncode=code,
                                 deaths_in_window=len(window))
            _trace.emit("supervisor", "replica_died", replica=k,
                        returncode=code, deaths_in_window=len(window))
            if len(window) >= self._flap_max_deaths:
                self._procs.pop(k, None)
                self.quarantined.add(k)
                self._note_quarantine(
                    k, reason=f"{len(window)} deaths in "
                    f"{self._flap_window_s:.0f}s window")
                continue
            delay = backoff_delay(self._consecutive[k] - 1,
                                  base_delay=self._respawn_base_s,
                                  max_delay=self._respawn_max_s,
                                  rng=self._rng)
            self._sleep(delay)
            self.spawn(k)
            self.respawns[k] += 1
            respawned.append(k)
        return respawned

    def mark_healthy(self, k: int) -> None:
        """Reset the consecutive-death backoff counter once a respawned
        replica answers an RPC (the flap WINDOW keeps counting — backoff
        resets on recovery, quarantine does not)."""
        self._consecutive[k] = 0

    def shutdown(self) -> None:
        for k, proc in list(self._procs.items()):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        self._procs.clear()


class ProcessFleet:
    """N replica PROCESSES following one store — the ``ServingFleet``
    surface across real OS boundaries.

    The canary cohort is the same deterministic law as the in-process
    fleet (first ``max(1, int(n * canary_fraction))`` ids), persisted into
    each child's spec file so a respawned lineage keeps its cohort.
    ``heartbeat`` RPCs carry durations, not timestamps (durations compare
    across processes; timestamps do not), and every record is re-stamped
    ``hb_at`` at ingress receipt for staleness eviction.
    """

    def __init__(self, store, config, *, workdir: str | Path,
                 logger=None, request_log_root=None):
        n = int(config.serving.replicas)
        if n < 2:
            raise ValueError(
                f"fleet_mode='process' needs serving.replicas >= 2, got {n}")
        spec = config.serving
        self.store = store
        self.spec = spec
        self._logger = logger
        frac = float(config.online.canary_fraction)
        self.n_canary = max(1, int(n * frac))
        self.workdir = Path(workdir) / "fleet"
        self.workdir.mkdir(parents=True, exist_ok=True)

        paths: dict[int, Path] = {}
        spec_paths: dict[int, Path] = {}
        serving_dict = dataclasses.asdict(spec)
        serving_dict["buckets"] = list(serving_dict["buckets"])
        slow_ms = float(config.faults.slow_score_ms or 0.0)
        for k in range(n):
            sock = self.workdir / f"replica-{k}.sock"
            cspec = {
                "replica_id": k,
                "socket": str(sock),
                "store_dir": str(store.root),
                "serving": serving_dict,
                "canary_member": k < self.n_canary,
                "request_log_root": (None if request_log_root is None
                                     else str(request_log_root)),
                "trace_dir": (str(_trace.trace_dir())
                              if _trace.active() else None),
                "slow_score_ms": slow_ms,
                # children NEVER inherit the parent's platform: a TPU
                # parent spawning N TPU children would contend on the one
                # tunnelled chip (CLAUDE.md: one TPU job at a time)
                "jax_platforms": "cpu",
            }
            spath = self.workdir / f"replica-{k}.json"
            spath.write_text(json.dumps(cspec, indent=1))
            paths[k] = sock
            spec_paths[k] = spath

        self.supervisor = ProcessSupervisor(
            spec_paths,
            respawn_base_ms=spec.respawn_base_ms,
            respawn_max_ms=spec.respawn_max_ms,
            flap_window_s=spec.flap_window_s,
            flap_max_deaths=spec.flap_max_deaths,
            logger=logger)
        self.ingress = Ingress(
            paths, stale_ms=spec.heartbeat_stale_ms,
            max_frame=spec.max_frame_bytes,
            connect_retries=spec.connect_retries,
            connect_base_ms=spec.connect_base_ms,
            logger=logger)
        self._skew_digests: set[str] = set()
        self._slow_digests: set[str] = set()
        self._versions: dict[int, int | None] = {}
        try:
            self.supervisor.spawn_all()
            self.ingress.connect_all()
        except BaseException:
            # a half-built fleet must not leak children: an orphaned
            # replica runs forever (and on a test harness, holds pipes)
            self.supervisor.shutdown()
            raise

    # ------------------------------------------------------------ members

    @property
    def _dead(self) -> set[int]:
        """Quarantined ids — the degraded-membership set the fleet worker
        reports (name-compatible with ``ServingFleet._dead``)."""
        return set(self.supervisor.quarantined)

    def alive_ids(self) -> list[int]:
        return [k for k in self.supervisor.alive_ids()
                if k not in self.supervisor.quarantined]

    def set_score_skew(self, digest: str) -> None:
        self._skew_digests.add(str(digest))

    def set_score_slow(self, digest: str) -> None:
        self._slow_digests.add(str(digest))

    def mark_canary_watch(self) -> None:
        """Consult the replica-death faults at a canary watch round:
        ``kill_replica_signal`` delivers a real SIGKILL to the victim's
        pid (the supervisor's next ``check`` respawns it);
        ``kill_replica_nth`` quarantines the victim (the in-process
        soft-kill twin — membership stays degraded)."""
        inj = _faults.active()
        if inj is None:
            return
        if inj.replica_sigkill_due():
            victim = int(inj.spec.kill_replica_signal) - 1
            if victim in self.supervisor._spec_paths:
                self.supervisor.kill(victim, signal.SIGKILL)
                self.ingress.disconnect(victim)
                if self._logger is not None:
                    self._logger.log(event="replica_sigkilled",
                                     replica=victim,
                                     reason="kill_replica_signal")
        if inj.replica_kill_due():
            victim = int(inj.spec.kill_replica_nth) - 1
            if victim in self.supervisor._spec_paths:
                self.supervisor.quarantine(victim)
                self.ingress.drop(victim)
                if self._logger is not None:
                    self._logger.log(event="replica_dead", replica=victim,
                                     reason="kill_replica_nth")

    # -------------------------------------------------------------- sync

    def check(self) -> list[int]:
        """Respawn any dead, unquarantined replicas and reconnect their
        ingress links; quarantined ids are dropped from routing."""
        respawned = self.supervisor.check()
        for k in self.supervisor.quarantined:
            self.ingress.drop(k)
        for k in respawned:
            self.ingress.connect(k)
        return respawned

    def sync(self) -> dict[int, int | None]:
        """Fan the pointer-follow RPC to every alive replica, always with
        the FULL skew/slow digest sets (idempotent re-arm: a respawned
        child starts blank and must relearn every armed fault or its
        lineage diverges from the unkilled reference)."""
        self.check()
        msg = {"type": "sync", "skew": sorted(self._skew_digests),
               "slow": sorted(self._slow_digests)}
        self._versions = {}
        for k in self.alive_ids():
            reply = self.ingress.rpc(k, msg)
            self._versions[k] = reply.get("version")
            self.supervisor.mark_healthy(k)
            _trace.emit("fleet", "replica_sync_rpc", replica=k,
                        version=reply.get("version"),
                        digest=reply.get("digest"))
        return dict(self._versions)

    def versions(self) -> dict[int, int | None]:
        return dict(self._versions)

    # ---------------------------------------------------------- heartbeat

    def heartbeat(self, feats: Mapping[str, np.ndarray],
                  labels: np.ndarray) -> list[dict[str, Any]]:
        """One RPC health sample per alive replica — the same record shape
        as ``ServingFleet.heartbeat`` (the canary verdict consumes either),
        re-stamped at receipt and fed to the balancer."""
        enc = wire.encode_feats(feats)
        lab = np.asarray(labels).ravel().tolist()
        out: list[dict[str, Any]] = []
        for k in self.alive_ids():
            reply = self.ingress.rpc(
                k, {"type": "heartbeat", "feats": enc, "labels": lab})
            rec = {key: reply[key] for key in
                   ("replica", "version", "auc", "ms", "canary")}
            for key in ("queue_depth", "batch_fill"):
                if key in reply:
                    rec[key] = reply[key]
            rec["hb_at"] = _trace.clock()  # receipt stamp, OUR clock
            self.ingress.observe(k, rec)
            _trace.emit("fleet", "heartbeat", **rec)
            out.append(rec)
        return out

    # -------------------------------------------------------------- serve

    def run(self, requests) -> dict[Any, np.ndarray | None]:
        """Route a request trace through the P2C ingress, then drain every
        replica and collect.  Sheds come back as ``None`` (counted at the
        ingress), exactly like ``MicroBatcher.run``."""
        if not self.alive_ids():
            raise RuntimeError("no alive replica process to serve on")
        for rid, batch in requests:
            self.ingress.submit(rid, batch)
            self.ingress.poll(0.0)
        for k in self.alive_ids():
            self.ingress.rpc(k, {"type": "drain"})
        while self.ingress.inflight():
            if self.ingress.poll(1.0) == 0:
                break  # remaining in-flight died with a connection
        return dict(self.ingress.completed)

    def probe_each(self, requests) -> dict[int, dict[Any, np.ndarray]]:
        """The bitwise fleet-convergence probe, per replica process."""
        payload = [[rid, wire.encode_feats(batch)] for rid, batch in requests]
        # JSON object keys are strings; map replies back to the callers' rids
        rid_by_str = {str(rid): rid for rid, _ in requests}
        out: dict[int, dict[Any, np.ndarray]] = {}
        for k in self.alive_ids():
            reply = self.ingress.rpc(k, {"type": "probe",
                                         "requests": payload})
            out[k] = {rid_by_str.get(s, s): None if v is None
                      else np.asarray(v, np.float32)
                      for s, v in reply["results"].items()}
        return out

    def close(self) -> None:
        for k in self.alive_ids():
            try:
                self.ingress.rpc(k, {"type": "shutdown"})
            except (wire.WireError, OSError, KeyError):
                pass
        self.ingress.close()
        self.supervisor.shutdown()
