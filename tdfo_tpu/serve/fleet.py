"""Multi-replica serving frontends over one shared :class:`BundleStore`.

A production serving tier is N identical frontends behind a load balancer
(Monolith §3.3 runs its parameter-synchronised serving replicas this way;
torchrec's inference path reloads a ``DistributedModelParallel`` module
per-host from one published snapshot).  This module is that tier scaled
down to one process: each :class:`ReplicaFrontend` owns its own
:class:`~tdfo_tpu.serve.frontend.MicroBatcher` and its own request-log
directory (``<root>/replica-<k>`` — the layout
``data/replay.MergedReplayConsumer`` folds back into one stream), while
ALL replicas follow the store's shared ``CURRENT``/``CANARY`` pointers.

Replicas are pointer FOLLOWERS, not per-replica store-mutating
``SwapController``s: the delta chain admits each version exactly once (a
second ``apply_delta`` of the same delta raises ``DeltaChainError``), so
exactly one writer — the online supervisor — mutates the store and every
replica merely re-reads the pointers on :meth:`ServingFleet.sync`.  A
canary MEMBER follows ``CANARY`` when one is pending; everyone else stays
on ``CURRENT``.  Because rollback deletes the canary dir and pointer and
promotion moves ``CURRENT`` itself, the same sync walk converges every
replica bitwise onto whatever the store says is good — there is no
per-replica state to reconcile.

Deterministic faults (``utils/faults.py``): ``regress_auc_at_cycle``
models training/serving skew by replacing a named version's logits with a
feature heuristic (no model call — the bundle itself is healthy, which is
exactly why only the canary watch, not the shadow gate, can catch it);
``kill_replica_nth`` drops one replica dead at its first canary watch
round, in-process, so restart lineages see identical membership.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Callable

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.serve.export import load_bundle
from tdfo_tpu.serve.frontend import MicroBatcher
from tdfo_tpu.serve.scoring import make_scorer
from tdfo_tpu.serve.swap import BundleStore, _version_name
from tdfo_tpu.train.metrics import binary_auc, ranking_auc
from tdfo_tpu.utils import faults as _faults

__all__ = ["ReplicaFrontend", "ServingFleet"]


class ReplicaFrontend:
    """One serving replica: a micro-batcher plus the pointer-follow logic.

    ``sync`` is the whole replica lifecycle: read the pointer this replica
    follows (``CANARY`` for canary members while one is pending, else
    ``CURRENT``), and when the ``(version, digest)`` pair changed, load
    the bundle (digest-verified), build a fresh scorer, and hot-swap the
    batcher onto it.  ``skew_digests`` injects the training/serving-skew
    fault: for bundles with those digests the scorer is replaced by a
    feature heuristic (negated first continuous column), so the replica
    serves confidently wrong logits from a bundle whose bytes are perfect.
    """

    def __init__(self, replica_id: int, store: BundleStore, serving_spec,
                 *, mesh=None, logger=None, request_log_root=None,
                 canary_member: bool = False):
        self.replica_id = int(replica_id)
        self.store = store
        self.spec = serving_spec
        self.mesh = mesh
        self.canary_member = bool(canary_member)
        self._logger = logger
        self.batcher: MicroBatcher | None = None
        # (version, digest, skewed, slow): fault membership is part of the
        # served identity — a restart lineage may sync onto a pending
        # canary BEFORE the supervisor re-arms the skew/slow fault, and
        # the later sync must then reload the same bytes with the faulted
        # scorer or the two lineages diverge.
        self._served: tuple[int, str, bool, bool] | None = None
        self._score_fn: Callable | None = None
        self._request_log = None
        if request_log_root is not None:
            from tdfo_tpu.data.replay import RequestLog, replica_log_dir

            self._request_log = RequestLog(
                replica_log_dir(request_log_root, self.replica_id),
                segment_bytes=serving_spec.log_segment_bytes)

    # ------------------------------------------------------------- follow

    def _target_pointer(self) -> dict | None:
        if self.canary_member:
            can = self.store._read_pointer("CANARY")
            cur = self.store.current_version()
            if can is not None and (cur is None or can["version"] > cur):
                return can
        return self.store._read_pointer("CURRENT")

    def sync(self, skew_digests: frozenset[str] = frozenset(),
             slow_digests: frozenset[str] = frozenset()) -> int | None:
        """Follow this replica's pointer; reload iff (version, digest,
        skewed, slow) changed.  Returns the version now being served
        (None = empty store, nothing to serve yet)."""
        ptr = self._target_pointer()
        if ptr is None:
            return None
        skewed = str(ptr["digest"]) in skew_digests
        slow = str(ptr["digest"]) in slow_digests
        key = (int(ptr["version"]), str(ptr["digest"]), skewed, slow)
        if key == self._served:
            return key[0]
        version = key[0]
        bdir = self.store.versions / _version_name(version)
        bundle = load_bundle(bdir, verify=True)
        scorer = make_scorer(bundle, mesh=self.mesh)
        cache_probe: Callable[[], int] | None = scorer.score_cache_size
        if skewed:
            # training/serving skew stand-in: healthy bytes, wrong logits.
            # No model call — deterministic, and independent of how well
            # the real model fits.  The seq family has no continuous
            # columns; its heuristic negates the candidate-id panel (same
            # [n, C] output shape as the honest scorer).
            skew_col = (scorer.cont_columns[0] if scorer.cont_columns
                        else "cands")

            def score_fn(batch, _col=skew_col):
                return -np.asarray(batch[_col], np.float32)

            cache_probe = None  # nothing jitted behind the heuristic
        else:
            score_fn = scorer.score
        if slow:
            # latency-regression stand-in (slow_canary_at_cycle): correct
            # logits, slow scorer — only replicas serving THIS digest pay
            # the sleep, so heartbeat p99s diverge by cohort and the
            # [online] max_p99_regression_ms verdict term has a signal
            inner = score_fn

            def score_fn(batch, _inner=inner):
                inj = _faults.active()
                if inj is not None:
                    inj.slow_score_sleep()
                return _inner(batch)

        self._score_fn = score_fn
        # seq requests carry [n, max_len] history panels, so the right fill
        # thresholds are the (smaller) [serving] history_buckets when set
        buckets = ((self.spec.history_buckets or self.spec.buckets)
                   if scorer.model == "bert4rec" else self.spec.buckets)
        if self.batcher is None:
            self.batcher = MicroBatcher(
                score_fn, buckets=buckets,
                max_batch=self.spec.max_batch,
                batch_deadline_ms=self.spec.batch_deadline_ms,
                logger=self._logger, program_cache_size=cache_probe,
                max_queue=self.spec.max_queue,
                shed_policy=self.spec.shed_policy,
                request_log=self._request_log)
            self.batcher.replica = self.replica_id
            self.batcher._version = version
            self.batcher._digest = key[1]
        else:
            self.batcher.swap(score_fn, version=version, digest=key[1],
                              program_cache_size=cache_probe)
        self._served = key
        # the freshness-lag anchor: when a version first goes live on a
        # replica outside a promote flip (obs/aggregate.py uses the
        # earliest of either)
        _trace.emit("fleet", "replica_sync", replica=self.replica_id,
                    version=version, digest=key[1],
                    canary=self.canary_member, skewed=skewed, slow=slow)
        return version

    # -------------------------------------------------------------- serve

    def score_direct(self, feats: dict[str, np.ndarray]) -> np.ndarray:
        """Score one batch on the replica's CURRENT scorer, bypassing the
        micro-batcher — the heartbeat path, which must not append to the
        request log (scoring our own replayed traffic back into the log
        would feed the gate its own output).  The jitted scorer donates
        its input, so callers pass a fresh dict of fresh arrays."""
        if self._score_fn is None:
            raise RuntimeError(
                f"replica {self.replica_id} has never synced — no scorer")
        return np.asarray(
            self._score_fn({k: np.asarray(v) for k, v in feats.items()}))

    def version(self) -> int | None:
        return None if self._served is None else self._served[0]

    def close(self) -> None:
        if self._request_log is not None:
            self._request_log.close()
            self._request_log = None


class ServingFleet:
    """N replicas following one store, plus the canary-watch instrumentation.

    The first ``max(1, int(n * canary_fraction))`` replica ids are the
    canary cohort — a fixed, deterministic membership, same on every
    restart lineage.  ``heartbeat`` is the per-replica health sample the
    gatekeeper consumes: held-out AUC plus a wall-clock latency figure per
    alive replica, tagged with cohort membership.  Dead replicas
    (``kill_replica_nth``) stop syncing, serving and heartbeating but are
    NOT forgotten: their request logs remain merged-replay inputs, so
    exactly-once accounting survives replica death.
    """

    def __init__(self, store: BundleStore, config, *, mesh=None,
                 logger=None, request_log_root=None):
        n = int(config.serving.replicas)
        if n < 1:
            raise ValueError(f"serving.replicas must be >= 1, got {n}")
        frac = float(config.online.canary_fraction)
        self.n_canary = max(1, int(n * frac)) if n > 1 else 0
        self.replicas = [
            ReplicaFrontend(
                k, store, config.serving, mesh=mesh, logger=logger,
                request_log_root=request_log_root,
                canary_member=k < self.n_canary)
            for k in range(n)
        ]
        self.store = store
        self._dead: set[int] = set()
        self._skew_digests: set[str] = set()
        self._slow_digests: set[str] = set()
        self._warmed: set[tuple] = set()
        self._logger = logger

    # ------------------------------------------------------------ members

    def alive(self) -> list[ReplicaFrontend]:
        return [r for r in self.replicas if r.replica_id not in self._dead]

    def alive_canaries(self) -> list[ReplicaFrontend]:
        return [r for r in self.alive() if r.canary_member]

    def mark_canary_watch(self) -> None:
        """Consult the ``kill_replica_nth`` fault at a canary watch round:
        replica ``nth - 1`` drops dead (in-process — its scorer and
        batcher stop participating; its request log stays on disk for the
        merged replay)."""
        inj = _faults.active()
        if inj is not None and inj.replica_kill_due():
            victim = int(inj.spec.kill_replica_nth) - 1
            if 0 <= victim < len(self.replicas):
                self._dead.add(victim)
                if self._logger is not None:
                    self._logger.log(event="replica_dead", replica=victim,
                                     reason="kill_replica_nth")

    def set_score_skew(self, digest: str) -> None:
        """Arm the training/serving-skew fault for the bundle with this
        digest: any replica that syncs onto it serves heuristic logits.
        Keyed by DIGEST, not version — rollback deletes the bad candidate
        and the next cycle REUSES its version number for different
        bytes, which must serve honestly."""
        self._skew_digests.add(str(digest))

    def set_score_slow(self, digest: str) -> None:
        """Arm the latency-regression fault (``slow_canary_at_cycle``) for
        the bundle with this digest: any replica that syncs onto it scores
        through a ``slow_score_ms`` host sleep.  Digest-keyed for the same
        reason as :meth:`set_score_skew` — rollback reuses version numbers
        for different bytes, which must serve at full speed."""
        self._slow_digests.add(str(digest))

    # -------------------------------------------------------------- sync

    def sync(self) -> dict[int, int | None]:
        """Point every alive replica at its pointer; returns the served
        version per replica id."""
        skew = frozenset(self._skew_digests)
        slow = frozenset(self._slow_digests)
        return {r.replica_id: r.sync(skew, slow) for r in self.alive()}

    def versions(self) -> dict[int, int | None]:
        return {r.replica_id: r.version() for r in self.alive()}

    # ---------------------------------------------------------- heartbeat

    def heartbeat(self, feats: dict[str, np.ndarray],
                  labels: np.ndarray | None) -> list[dict[str, Any]]:
        """One health sample per alive replica on a held-out slice:
        ``{replica, version, auc, ms, canary, queue_depth, batch_fill}``.
        ``labels = None`` is the seq family: scores are [n, C] candidate
        panels with the positive in column 0, judged by ``ranking_auc``
        instead of the labelled ``binary_auc``
        (the saturation pair mirrored from the replica's micro-batcher).
        Fresh arrays per call — the scorer donates its inputs.  Each
        sample is also emitted as a ``heartbeat`` trace span: the ``ms``
        samples are what the offline p50/p99 histograms and the online
        ``max_p99_regression_ms`` verdict term are computed from.

        A replica's FIRST sample on a freshly-synced scorer is preceded by
        one unmeasured warm-up score: jit compilation is a one-time cost
        the canary cohort would otherwise pay on EVERY cycle (its bundle
        is always new) while the stable cohort never does — a constant
        false p99 regression that would mask or mimic real slowdowns."""
        out = []
        for r in self.alive():
            if (r.replica_id, r._served) not in self._warmed:
                self._warmed.add((r.replica_id, r._served))
                r.score_direct({k: np.array(v) for k, v in feats.items()})
            t0 = _trace.clock()
            scores = r.score_direct(
                {k: np.array(v) for k, v in feats.items()})
            ms = _trace.elapsed_ms(t0)
            rec: dict[str, Any] = {
                "replica": r.replica_id, "version": r.version(),
                "auc": (ranking_auc(scores) if labels is None
                        else binary_auc(labels, scores)), "ms": ms,
                "canary": r.canary_member,
                # trace-clock stamp for staleness eviction: a dead replica
                # keeps its last queue_depth/batch_fill forever, so the
                # balancer must age records out — serve/ingress.py treats
                # anything older than [serving] heartbeat_stale_ms as dead
                # (freshness = _trace.elapsed_ms(hb_at), never a raw clock
                # difference).  In-process fleets stamp at the sample;
                # PROCESS fleets re-stamp at ingress receipt, because
                # monotonic clocks are not comparable across processes.
                "hb_at": _trace.clock(),
            }
            if r.batcher is not None:
                rec["queue_depth"] = r.batcher.last_queue_depth
                rec["batch_fill"] = r.batcher.last_batch_fill
            _trace.emit("fleet", "heartbeat", **rec)
            out.append(rec)
        return out

    # -------------------------------------------------------------- serve

    def probe_each(self, requests) -> dict[int, dict[Any, np.ndarray]]:
        """Run the same request trace through EVERY alive replica's
        micro-batcher — the bitwise fleet-convergence probe.  Each replica
        gets its own copy of the trace (scorers donate; batchers log)."""
        out = {}
        for r in self.alive():
            if r.batcher is None:
                continue
            trace = [(rid, {k: np.array(v) for k, v in batch.items()})
                     for rid, batch in requests]
            out[r.replica_id] = dict(r.batcher.run(trace))
        return out

    def run(self, requests) -> dict[Any, np.ndarray]:
        """Round-robin a request trace across alive replicas — the load-
        balancer path the fleet quickstart demonstrates."""
        alive = [r for r in self.alive() if r.batcher is not None]
        if not alive:
            raise RuntimeError("no alive, synced replica to serve on")
        results: dict[Any, np.ndarray] = {}
        for i, (rid, batch) in enumerate(requests):
            r = alive[i % len(alive)]
            r.batcher.submit(rid, batch)
            r.batcher.poll()
        for r in alive:
            r.batcher.drain()
            results.update(r.batcher.results)
        return results

    def close(self) -> None:
        for r in self.replicas:
            r.close()
