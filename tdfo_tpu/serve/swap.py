"""Crash-safe bundle store + hot-swap orchestration for the serving frontend.

The training side survives preemption via checkpoint layout stamps and
atomic cursor sidecars (``train/checkpoint.py``); this module is the
serving twin.  The reference analogue is torchrec's inference model-update
idiom (``DistributedModelParallel`` state-dict reload into a live predictor;
fbgemm's inplace-update path for TBE weights) and Monolith's minute-level
sparse sync (Liu et al. 2022 §3.3) — a frontend must pick up a newer model
without dropping traffic, and must survive crashing at ANY byte of the
update.

Layout under the store root::

    versions/v000000/   fully-materialized serving bundles (bundle.json +
    versions/v000001/   arrays.npz), published by directory rename
    CURRENT             {"version": N, "digest": ...} pointer, atomic JSON
    CANARY              same shape: the staged-rollout pointer the canary
                        slice of the fleet serves while the gatekeeper
                        watches (absent outside a watch window)
    quarantine.json     record of refused-corrupt deltas
    rejections.json     (version, digest) pairs the gatekeeper rolled back
                        — recover() prunes their directories and never
                        re-adopts them, so a rejected candidate's version
                        NUMBER is reusable but its bytes are not

Durability discipline — the ONLY sanctioned rename sites in the repo
(``test_quality.py`` rejects bare ``os.rename``/``os.replace`` elsewhere):

  * :func:`atomic_write_json` — write-temp + fsync + ``os.replace`` +
    parent-dir fsync, for the ``CURRENT`` pointer and quarantine record;
  * :func:`publish_dir` — stage a complete bundle directory under a
    ``.tmp`` name, fsync every file and the directory, then one rename.

A crash between stage and publish leaves only a ``*.tmp`` directory;
:meth:`BundleStore.recover` deletes strays and re-points ``CURRENT`` at the
newest version whose content digest verifies — so "restart the same
command" converges, exactly like the trainer's kill-marker semantics.

Canary ordering invariant: :meth:`BundleStore.publish_canary` writes the
``CANARY`` pointer BEFORE publishing the version directory.  A crash in
between leaves a pointer naming a missing directory (``recover()`` clears
it; the supervisor's deterministic redo republishes identical bytes) —
never an unnamed published directory that ``recover()``'s newest-first walk
would wrongly adopt as ``CURRENT`` before the gatekeeper passed it.
Promotion reverses that: ``CURRENT`` advances first, then ``CANARY``
clears, so a canary pointer at or below ``CURRENT`` is a completed
promotion, not a pending one.

Failure degradation: a delta whose payload does not hash to its manifest
digest is QUARANTINED (recorded, never applied, never crashes the
frontend); the store keeps serving the last good version.  After
``max_bad_deltas`` consecutive quarantines the controller flips a degraded
flag into the serving heartbeat (``obs/watchdog.py set_status``) — the
operator signal that the export pipeline, not the frontend, is sick.  All
of it is driven deterministically by the ``[faults]`` harness
(``corrupt_delta_nth``, ``kill_during_swap``).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.serve.export import (
    apply_delta_arrays,
    bundle_digest,
    read_raw_bundle,
    write_raw_bundle,
)
from tdfo_tpu.utils import faults
from tdfo_tpu.utils.retry import retry_call

__all__ = [
    "BundleStore",
    "CorruptDeltaError",
    "DeltaChainError",
    "DeltaPoller",
    "SwapController",
    "atomic_write_json",
    "publish_dir",
]

_CURRENT = "CURRENT"
_CANARY = "CANARY"
_QUARANTINE = "quarantine.json"
_REJECTIONS = "rejections.json"


class DeltaChainError(ValueError):
    """The delta does not extend the current chain head (gap, re-order, or
    parent digest mismatch) — a loud refusal, never applied."""


class CorruptDeltaError(ValueError):
    """The delta payload fails digest verification — quarantined, the last
    good version keeps serving."""


# ------------------------------------------------------- atomic primitives


def _fsync_dir(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(path: str | Path, obj: Any) -> None:
    """The blessed pointer-file writer: temp in the same directory, fsync,
    ``os.replace`` (atomic on POSIX), fsync the directory so the rename
    itself is durable.  A reader sees the old complete file or the new
    complete file, never a torn one."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "w") as f:
        f.write(json.dumps(obj, indent=1, sort_keys=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path.parent)


def publish_dir(staged: str | Path, final: str | Path) -> None:
    """The blessed directory publisher: fsync every file in the staged
    directory (its contents were written by ordinary buffered I/O), fsync
    the directory, then ONE rename to the final name.  Readers discover
    bundles by final name only, so a half-written bundle is unreachable."""
    staged, final = Path(staged), Path(final)
    for p in sorted(staged.rglob("*")):
        if p.is_file():
            fd = os.open(p, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
    _fsync_dir(staged)
    os.replace(staged, final)
    _fsync_dir(final.parent)


# --------------------------------------------------------------- the store


def _version_name(version: int) -> str:
    return f"v{version:06d}"


def _read_manifest(vdir: Path) -> dict:
    """Whole-file manifest read (NOT a line tailer — the quality suite
    confines line-oriented json.loads loops to data/replay.py)."""
    return json.loads((vdir / "bundle.json").read_text())


class BundleStore:
    """Versioned, digest-verified bundle store with an atomic CURRENT pointer.

    Every bundle directory under ``versions/`` is fully materialized (deltas
    are composed at ingest, not at serve time), so recovery never needs to
    re-walk a chain: the newest directory whose digest verifies IS the last
    fully-verified version.
    """

    def __init__(self, root: str | Path, *, keep_versions: int = 0):
        if keep_versions < 0:
            raise ValueError(
                f"keep_versions must be >= 0 (0 = keep everything), "
                f"got {keep_versions}")
        self.root = Path(root)
        self.versions = self.root / "versions"
        self.versions.mkdir(parents=True, exist_ok=True)
        # retention budget beyond the protected CURRENT/CANARY chain
        # ([serving] keep_versions); 0 disables gc_versions entirely
        self.keep_versions = int(keep_versions)

    # ------------------------------------------------------------ queries

    def _read_pointer(self, name: str) -> dict | None:
        p = self.root / name
        if not p.exists():
            return None
        rec = json.loads(p.read_text())
        return {"version": int(rec["version"]), "digest": rec["digest"]}

    def current_version(self) -> int | None:
        cur = self._read_pointer(_CURRENT)
        return None if cur is None else cur["version"]

    def current_dir(self) -> Path | None:
        v = self.current_version()
        return None if v is None else self.versions / _version_name(v)

    def canary_version(self) -> int | None:
        can = self._read_pointer(_CANARY)
        return None if can is None else can["version"]

    def canary_dir(self) -> Path | None:
        v = self.canary_version()
        return None if v is None else self.versions / _version_name(v)

    def quarantined(self) -> list[dict]:
        qpath = self.root / _QUARANTINE
        return json.loads(qpath.read_text()) if qpath.exists() else []

    def rejections(self) -> list[dict]:
        rpath = self.root / _REJECTIONS
        return json.loads(rpath.read_text()) if rpath.exists() else []

    def _rejected_keys(self) -> set[tuple[int, str]]:
        return {(int(r["version"]), r["digest"]) for r in self.rejections()}

    def _read_current(self) -> tuple[dict, dict[str, np.ndarray]]:
        cdir = self.current_dir()
        if cdir is None:
            raise ValueError(f"bundle store {self.root} has no CURRENT version")
        return retry_call(read_raw_bundle, cdir,
                          description=f"bundle read {cdir.name}")

    # ------------------------------------------------------------- writes

    def _publish(self, manifest: dict, arrays: dict[str, np.ndarray],
                 version: int, *, is_swap: bool = False) -> Path:
        final = self.versions / _version_name(version)
        if final.exists():
            raise ValueError(
                f"bundle store already holds {final.name} — versions are "
                "immutable once published")
        staged = self.versions / (_version_name(version) + ".tmp")
        if staged.exists():
            shutil.rmtree(staged)  # leftover from a crashed apply
        write_raw_bundle(staged, manifest, arrays)
        inj = faults.active()
        if is_swap and inj is not None:
            inj.maybe_kill_swap()  # the canonical half-applied crash point
        publish_dir(staged, final)
        atomic_write_json(self.root / _CURRENT,
                          {"version": version, "digest": manifest["digest"]})
        _trace.emit("swap", "pointer_flip", op="publish", pointer=_CURRENT,
                    version=version, digest=manifest["digest"])
        return final

    def ingest_full(self, bundle_dir: str | Path) -> int:
        """Verify and publish a FULL bundle (chain head / chain reset).

        Refuses a digest-corrupt bundle and a version that does not advance
        the store (re-ingesting the head is idempotent-by-refusal, not
        silent overwrite)."""
        manifest, arrays = retry_call(
            read_raw_bundle, bundle_dir,
            description=f"full bundle read {Path(bundle_dir).name}")
        got = bundle_digest(manifest, arrays)
        if got != manifest.get("digest"):
            raise ValueError(
                f"full bundle {bundle_dir}: digest {got} != manifest "
                f"{manifest.get('digest')!r} — refusing a corrupt bundle")
        if manifest.get("kind") == "delta":
            raise ValueError(
                f"{bundle_dir} is a delta, not a full bundle — deltas go "
                "through apply_delta against the current version")
        version = int(manifest.get("version", 0))
        cur = self.current_version()
        if cur is not None and version <= cur:
            raise ValueError(
                f"full bundle {bundle_dir} is v{version}, store already "
                f"serves v{cur} — stale full export refused")
        self._publish(manifest, arrays, version)
        return version

    def apply_delta(self, delta_dir: str | Path) -> int:
        """Compose a delta onto CURRENT and publish the result atomically.

        Chain violations (gap / re-order / wrong parent) raise
        :class:`DeltaChainError`; payload corruption raises
        :class:`CorruptDeltaError` (the caller quarantines).  Either way
        CURRENT is untouched until the composed bundle is fully staged,
        fsynced, published, and digest-verified.
        """
        manifest, arrays = self.compose_delta(delta_dir)
        self._publish(manifest, arrays, int(manifest["version"]), is_swap=True)
        return int(manifest["version"])

    def compose_delta(self, delta_dir: str | Path
                      ) -> tuple[dict, dict[str, np.ndarray]]:
        """Verify a delta end-to-end (own digest, chain position, base
        bytes) and compose it onto CURRENT **in memory** — nothing is
        published.  The gated supervisor scores this composition on the
        shadow slice before any pointer moves; :meth:`apply_delta` and
        :meth:`publish_canary` both build on it."""
        delta_dir = Path(delta_dir)
        dmanifest, darrays = retry_call(
            read_raw_bundle, delta_dir,
            description=f"delta read {delta_dir.name}")
        inj = faults.active()
        if inj is not None and inj.corrupt_delta_due():
            # bit-flip the payload IN MEMORY so digest verification runs
            # against real corruption, not a mocked exception
            if darrays:
                k = sorted(darrays)[0]
                a = np.array(darrays[k])
                a.view(np.uint8).reshape(-1)[0] ^= 0xFF
                darrays = dict(darrays, **{k: a})
            else:
                dmanifest = dict(dmanifest, digest="0" * 16)
        if dmanifest.get("kind") != "delta":
            raise DeltaChainError(
                f"{delta_dir} is not a delta (kind={dmanifest.get('kind')!r})")
        own = bundle_digest(dmanifest, darrays)
        if own != dmanifest.get("digest"):
            raise CorruptDeltaError(
                f"delta {delta_dir.name}: payload hashes to {own}, manifest "
                f"says {dmanifest.get('digest')!r} — corrupt delta")
        base_manifest, base_arrays = self._read_current()
        # verify the served base's ACTUAL bytes, not just its manifest field:
        # a delta that happens to rewrite the torn rows would otherwise
        # launder parent corruption into a result whose digest verifies
        base_got = bundle_digest(base_manifest, base_arrays)
        if base_got != base_manifest.get("digest"):
            raise CorruptDeltaError(
                f"serving base v{base_manifest.get('version')}: payload "
                f"hashes to {base_got}, manifest says "
                f"{base_manifest.get('digest')!r} — corrupt base, refusing "
                "to compose")
        try:
            manifest, arrays = apply_delta_arrays(
                base_manifest, base_arrays, dmanifest, darrays)
        except ValueError as e:
            msg = str(e)
            if "out of order" in msg or "parent digest" in msg:
                raise DeltaChainError(msg) from e
            raise CorruptDeltaError(msg) from e
        return manifest, arrays

    # ------------------------------------------------------------- canary

    def publish_canary(self, delta_dir: str | Path,
                       composed: tuple[dict, dict[str, np.ndarray]] | None
                       = None) -> int:
        """Publish a gated candidate under the ``CANARY`` pointer; CURRENT
        is untouched.  ``composed`` reuses the (manifest, arrays) the
        shadow gate already verified via :meth:`compose_delta`.

        Pointer-first ordering + deterministic re-export make this
        redoable: a restarted supervisor recomposes identical bytes, finds
        the pointer naming the same digest and either adopts the already-
        published directory or re-stages it — a kill at ANY byte of a
        canary publish converges on retry."""
        manifest, arrays = (composed if composed is not None
                            else self.compose_delta(delta_dir))
        version = int(manifest["version"])
        final = self.versions / _version_name(version)
        atomic_write_json(self.root / _CANARY,
                          {"version": version, "digest": manifest["digest"]})
        _trace.emit("swap", "pointer_flip", op="canary", pointer=_CANARY,
                    version=version, digest=manifest["digest"])
        if final.exists():
            try:
                m, a = read_raw_bundle(final)
                if (bundle_digest(m, a) == m.get("digest")
                        == manifest["digest"]):
                    return version  # redo after a kill: already published
            except Exception:
                pass
            shutil.rmtree(final)  # torn or stale bytes at this version
        staged = self.versions / (_version_name(version) + ".tmp")
        if staged.exists():
            shutil.rmtree(staged)
        write_raw_bundle(staged, manifest, arrays)
        inj = faults.active()
        if inj is not None:
            inj.maybe_kill_swap()  # same half-applied crash point as CURRENT
        publish_dir(staged, final)
        return version

    def promote_canary(self) -> int | None:
        """Advance ``CURRENT`` to the watched canary version (digest-
        re-verified from disk) and clear the ``CANARY`` pointer.
        Idempotent: with no pending canary — or one at/below CURRENT, the
        crashed-between-pointer-writes window — it just clears and returns
        the serving head."""
        can = self._read_pointer(_CANARY)
        cur = self.current_version()
        if can is None:
            return cur
        if cur is not None and can["version"] <= cur:
            (self.root / _CANARY).unlink(missing_ok=True)
            return cur
        vdir = self.versions / _version_name(can["version"])
        manifest, arrays = retry_call(
            read_raw_bundle, vdir, description=f"canary read {vdir.name}")
        got = bundle_digest(manifest, arrays)
        if got != can["digest"]:
            raise ValueError(
                f"canary {vdir.name}: payload hashes to {got}, pointer says "
                f"{can['digest']!r} — refusing to promote corrupt bytes")
        atomic_write_json(self.root / _CURRENT,
                          {"version": can["version"], "digest": can["digest"]})
        (self.root / _CANARY).unlink(missing_ok=True)
        _trace.emit("swap", "pointer_flip", op="promote", pointer=_CURRENT,
                    version=can["version"], digest=can["digest"])
        self.gc_versions()
        return can["version"]

    def rollback_canary(self, reason: str) -> int | None:
        """Reject the pending canary: record its ``(version, digest)`` in
        ``rejections.json`` (durable FIRST — recover() then prunes the
        directory even if this process dies mid-rollback), delete its
        directory so the version number is reusable by the next candidate,
        clear ``CANARY``, and digest-verify that CURRENT still serves the
        last good bytes — the bitwise rollback guarantee.  Idempotent:
        with no pending canary only the CURRENT verification runs."""
        can = self._read_pointer(_CANARY)
        if can is not None:
            self._record_rejection(can["version"], can["digest"], reason)
            vdir = self.versions / _version_name(can["version"])
            if vdir.exists():
                shutil.rmtree(vdir)
            (self.root / _CANARY).unlink(missing_ok=True)
            _trace.emit("swap", "pointer_flip", op="rollback",
                        pointer=_CANARY, version=can["version"],
                        digest=can["digest"], reason=reason)
        cdir = self.current_dir()
        if cdir is not None:
            manifest, arrays = self._read_current()
            got = bundle_digest(manifest, arrays)
            if got != manifest.get("digest"):
                raise ValueError(
                    f"rollback target v{manifest.get('version')}: payload "
                    f"hashes to {got}, manifest says "
                    f"{manifest.get('digest')!r} — the last good version is "
                    "itself corrupt")
        return self.current_version()

    def _record_rejection(self, version: int, digest: str,
                          reason: str) -> None:
        rec = {"version": int(version), "digest": digest,
               "reason": reason, "time": time.time()}
        existing = self.rejections()
        if any(r["version"] == rec["version"] and r["digest"] == rec["digest"]
               for r in existing):
            return  # redo of a crashed rollback: already recorded
        atomic_write_json(self.root / _REJECTIONS, existing + [rec])

    # ---------------------------------------------------------- retention

    def gc_versions(self) -> list[int]:
        """Retention sweep ([serving] keep_versions): beyond the protected
        CURRENT/CANARY chain, keep only the ``keep_versions`` newest
        published directories.  CURRENT's bytes are digest-verified BEFORE
        anything is deleted — a sweep never removes fallback history while
        the serving head is corrupt.  Returns the pruned versions."""
        if not self.keep_versions:
            return []
        protect = {v for v in (self.current_version(), self.canary_version())
                   if v is not None}
        try:
            manifest, arrays = self._read_current()
            if bundle_digest(manifest, arrays) != manifest.get("digest"):
                return []  # corrupt head: recover(), don't prune history
        except Exception:
            return []
        listed: list[tuple[int, Path]] = []
        for vdir in self.versions.iterdir():
            if vdir.is_dir() and not vdir.name.endswith(".tmp"):
                try:
                    listed.append((int(vdir.name.lstrip("v")), vdir))
                except ValueError:
                    continue
        listed.sort(reverse=True)
        pruned: list[int] = []
        survivors = 0
        for version, vdir in listed:
            if version in protect:
                continue
            if survivors < self.keep_versions:
                survivors += 1
                continue
            shutil.rmtree(vdir)
            pruned.append(version)
        return pruned

    # ----------------------------------------------------------- recovery

    def recover(self) -> int | None:
        """Restart-after-crash entry point: delete stray ``*.tmp`` staging
        directories and gatekeeper-rejected version directories, validate
        the ``CANARY`` pointer (cleared when it names rejected, missing,
        corrupt, or already-promoted bytes), then walk published versions
        newest-first — EXCLUDING a surviving canary, which is staged but
        unvetted — and point CURRENT at the first one whose content digest
        verifies (pruning any newer corrupt/torn directory).  Ends with the
        retention sweep.  Returns the recovered version, or ``None`` for an
        empty store."""
        for stray in self.versions.glob("*.tmp"):
            shutil.rmtree(stray)
        rejected = self._rejected_keys()
        if rejected:
            # a crash between rejection record and directory delete leaves
            # the rolled-back bytes on disk; finish the delete here so the
            # walk below can never re-adopt them
            for vdir in list(self.versions.iterdir()):
                if not vdir.is_dir():
                    continue
                try:
                    manifest = _read_manifest(vdir)
                    key = (int(manifest["version"]), manifest.get("digest"))
                except Exception:
                    continue  # torn directory: the walk below prunes it
                if key in rejected:
                    shutil.rmtree(vdir)
        canary_v: int | None = None
        can = self._read_pointer(_CANARY)
        cur_ptr = self._read_pointer(_CURRENT)
        if (can is not None and cur_ptr is not None
                and can["version"] <= cur_ptr["version"]):
            # promotion advanced CURRENT but crashed before clearing the
            # canary pointer: the candidate IS the vetted head now, so the
            # pointer is a completed promotion's leftover, not a pending one
            (self.root / _CANARY).unlink(missing_ok=True)
            can = None
        if can is not None:
            cdir = self.versions / _version_name(can["version"])
            ok = False
            if (can["version"], can["digest"]) not in rejected and cdir.exists():
                try:
                    manifest, arrays = read_raw_bundle(cdir)
                    ok = (bundle_digest(manifest, arrays)
                          == manifest.get("digest") == can["digest"])
                except Exception:
                    ok = False
                if not ok and cdir.exists():
                    shutil.rmtree(cdir)
            if ok:
                canary_v = can["version"]
            else:
                # pointer-before-directory crash window (or a rejected /
                # corrupt candidate): the supervisor's redo republishes
                (self.root / _CANARY).unlink(missing_ok=True)
        best: tuple[int, dict] | None = None
        for vdir in sorted(self.versions.iterdir(), reverse=True):
            if not vdir.is_dir():
                continue
            if canary_v is not None and vdir.name == _version_name(canary_v):
                continue  # staged but unvetted: never the serving head
            try:
                manifest, arrays = read_raw_bundle(vdir)
                if bundle_digest(manifest, arrays) != manifest.get("digest"):
                    raise ValueError("digest mismatch")
                if (int(manifest["version"]),
                        manifest.get("digest")) in rejected:
                    raise ValueError("gatekeeper-rejected bytes")
                best = (int(manifest["version"]), manifest)
                break
            except Exception:
                # torn/corrupt directory: unreachable once CURRENT skips it
                shutil.rmtree(vdir)
        if best is None:
            cur = self.root / _CURRENT
            if cur.exists():
                cur.unlink()
            if canary_v is not None:
                # a canary with no base to fall back on is unservable
                (self.root / _CANARY).unlink(missing_ok=True)
                shutil.rmtree(self.versions / _version_name(canary_v),
                              ignore_errors=True)
            return None
        version, manifest = best
        if canary_v is not None and canary_v <= version:
            # promotion completed before the crash cleared the pointer
            (self.root / _CANARY).unlink(missing_ok=True)
        atomic_write_json(self.root / _CURRENT,
                          {"version": version, "digest": manifest["digest"]})
        _trace.emit("swap", "pointer_flip", op="recover", pointer=_CURRENT,
                    version=version, digest=manifest["digest"])
        self.gc_versions()
        return version

    def record_quarantine(self, delta_dir: str | Path, error: str) -> None:
        rec = {"path": str(delta_dir), "error": error, "time": time.time()}
        atomic_write_json(self.root / _QUARANTINE, self.quarantined() + [rec])


# ------------------------------------------------------------ orchestration


class DeltaPoller:
    """Cadence gate + chain-directory discovery for the serving loop.

    The exporter drops chain entries next to each other
    (``<chain_root>/v000001`` …); the poller checks for the successor of the
    store's current version at most once per ``poll_s`` (the ``[serving]
    swap_poll_s`` knob), injectable clock for tests.

    Clock robustness: ``poll_s <= 0`` degenerates to "always due" (poll
    every tick) instead of arming a gate that floating-point drift could
    wedge, and a BACKWARDS clock jump (NTP step, VM migration — the
    injectable clock is not guaranteed monotonic) re-arms the deadline
    relative to the new ``now`` rather than stalling until the old epoch is
    reached again."""

    def __init__(self, chain_root: str | Path, *, poll_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.chain_root = Path(chain_root)
        self.poll_s = float(poll_s)
        self._clock = clock
        self._next = self._clock()  # first poll is due immediately

    def due(self) -> bool:
        if self.poll_s <= 0:
            return True  # no cadence gate: every tick polls
        now = self._clock()
        if now < self._next - self.poll_s:
            # the clock jumped backwards: the stored deadline is unreachable
            # garbage from the old epoch.  Re-arm one full interval out so
            # the cadence contract (at most one poll per poll_s) holds in
            # the new epoch instead of stalling for the jump's magnitude.
            self._next = now + self.poll_s
            return False
        if now < self._next:
            return False
        self._next = now + self.poll_s
        return True

    def next_delta(self, current_version: int) -> Path | None:
        cand = self.chain_root / _version_name(current_version + 1)
        return cand if (cand / "bundle.json").exists() else None


class SwapController:
    """Drives the store + MicroBatcher through verified hot-swaps, absorbing
    corrupt deltas into quarantine and surfacing degraded mode.

    ``build_score_fn(bundle_dir) -> score_fn`` rebuilds the scorer from a
    published bundle directory (typically ``make_scorer(load_bundle(d,
    verify=True))``); the controller never lets a failed rebuild take down
    the frontend — the old scorer keeps serving.
    """

    def __init__(self, store: BundleStore,
                 build_score_fn: Callable[[Path], Callable],
                 batcher=None, *, max_bad_deltas: int = 3,
                 logger=None, watchdog=None):
        if max_bad_deltas < 1:
            raise ValueError(f"max_bad_deltas must be >= 1, got {max_bad_deltas}")
        self.store = store
        self.build_score_fn = build_score_fn
        self.batcher = batcher
        self.max_bad_deltas = int(max_bad_deltas)
        self.logger = logger
        self.watchdog = watchdog
        self.consecutive_bad = 0
        self.degraded = False

    def _log(self, **rec) -> None:
        if self.logger is not None:
            self.logger.log(**rec)

    def _set_degraded(self, flag: bool) -> None:
        if flag != self.degraded:
            self.degraded = flag
            self._log(event="serving_degraded", degraded=flag,
                      bad_deltas=self.consecutive_bad)
        if self.watchdog is not None:
            self.watchdog.set_status(degraded=self.degraded,
                                     bad_deltas=self.consecutive_bad)

    def apply(self, delta_dir: str | Path) -> bool:
        """Apply one delta end to end: verify + compose + publish + rebuild
        scorer + drain-and-flip the batcher.  Returns True on a completed
        swap; False when the delta was quarantined (still serving the last
        good version).  Chain violations raise — a gap or re-order is an
        exporter-side bug the frontend must not paper over."""
        try:
            version = self.store.apply_delta(delta_dir)
        except CorruptDeltaError as e:
            self.store.record_quarantine(delta_dir, str(e))
            self.consecutive_bad += 1
            self._log(event="delta_quarantined", path=str(delta_dir),
                      error=str(e), consecutive_bad=self.consecutive_bad)
            self._set_degraded(self.consecutive_bad >= self.max_bad_deltas)
            return False
        score_fn = retry_call(
            self.build_score_fn, self.store.current_dir(),
            description=f"scorer rebuild v{version}")
        if self.batcher is not None:
            self.batcher.swap(score_fn, version=version)
        self.consecutive_bad = 0
        self._set_degraded(False)
        return True

    def poll(self, poller: DeltaPoller) -> bool:
        """One serving-loop tick: when the poller is due and the chain has a
        successor delta, apply it.  Returns True when a swap completed."""
        if not poller.due():
            return False
        cur = self.store.current_version()
        if cur is None:
            return False
        nxt = poller.next_delta(cur)
        if nxt is None:
            return False
        if any(q["path"] == str(nxt) for q in self.store.quarantined()):
            # a quarantined PATH is re-tried only when the bytes on disk
            # have verifiably changed since the refusal (the exporter
            # re-wrote a good delta at the same chain position) — that is
            # how a degraded frontend recovers without an operator poke,
            # while still-corrupt bytes are never re-applied in a loop
            try:
                m, a = read_raw_bundle(nxt)
                if (m.get("kind") != "delta"
                        or bundle_digest(m, a) != m.get("digest")):
                    return False
            except Exception:
                return False
        return self.apply(nxt)
