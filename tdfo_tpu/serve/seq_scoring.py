"""Train-parity sequence scoring from a ``model="bert4rec"`` serving bundle.

The serving forward IS the trainer's seq eval forward (``train/trainer.py
_build_bert4rec`` eval_accum) re-pointed at the bundle's merged tables: the
same ``ShardedEmbeddingCollection`` lookup (replicated table, ``mode="gspmd"``
— plain row gathers), the same :class:`~tdfo_tpu.models.bert4rec.Bert4RecBackbone`
module rebuilt from the manifest's ``seq`` hyperparameters, and the
appended-MASK-position candidate slice of
:func:`~tdfo_tpu.train.seq.score_candidates` (``torchrec/train.py:44-58``)
— with ONE serving-only restructuring: ``out_proj`` is applied to the
last-position hidden state ``[B, d]`` instead of the full sequence, a row
slice of the Dense lhs that keeps every computed element bitwise equal to
the eval step's ``logits[:, -1, :]`` while never materializing the
``[B, T, V]`` logits cube (XLA does not sink the slice into the matmul —
at B=8192/V=200k that cube is 420 GB).  That chain is what makes served
masked-position logits bitwise-equal to the eval step for f32 bundles
(``tests/test_serve_seq.py``), the same contract ``serve/scoring.py``
establishes for the CTR family.

Request payloads are the eval schema's shapes (``trainer._eval_schema``):
``seqs`` [B, max_len] int32 eval windows (history truncated LEFT at
``max_len - 1``, appended MASK, LEFT-padded with ``PAD_ID`` —
``torchrec/preprocessing.py:229-239``, see :func:`history_window`) and
``cands`` [B, C] int32 candidate ids.  Scoring steps are jitted with the
request batch DONATED and take tables/params as ARGUMENTS, never closures
(CLAUDE.md tunnel rules).

Next-item retrieval searches the OUTPUT HEAD as the corpus
(:func:`item_corpus`): Bert4Rec's ``out_proj`` is an UNTIED Dense
(``models/bert4rec.py`` — its own ``[d, V]`` kernel and bias, no weight
tying with the input item table), and it scores item ``v`` as
``h_last @ W_out[:, v] + b_out[v]``.  The corpus row for item ``v`` is
therefore the head column with the bias folded in, ``[W_out[:, v]; b_out[v]]``,
and the MIPS query is the last-position hidden state with a constant 1
appended (:meth:`SeqScorer.query_embed`) — every corpus inner product IS
the served logit, so retrieval ranks exactly like :meth:`SeqScorer.score`
(pinned by ``tests/test_serve_seq.py`` against the full-catalog argsort).
The input embedding table would rank by ``h @ e_v`` — a different function;
no separate corpus sweep is needed either way, the head already lives in
the bundle's dense params.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.core.mesh import DATA_AXIS, replicated_sharding
from tdfo_tpu.models.bert4rec import (
    PAD_ID,
    Bert4RecBackbone,
    Bert4RecConfig,
    key_padding_mask,
)
from tdfo_tpu.ops.quant import STORAGE_DTYPES, quantize_rows
from tdfo_tpu.serve.corpus import Corpus
from tdfo_tpu.serve.export import ServingBundle
__all__ = ["SeqScorer", "make_seq_scorer", "history_window", "item_corpus"]

# the seq request schema: categorical-panel columns score() consumes
SEQ_FEATURES = ("seqs", "cands")


@dataclass
class SeqScorer:
    """Jitted sequence-serving programs bound to one bundle's parameters.

    ``score(batch) -> [B, C] f32`` ranks ``cands`` at the appended-MASK
    position (batch donated).  ``query_embed(batch) -> [B, D+1] f32`` is the
    last-position hidden state with a constant 1 appended — the MIPS query
    against the bias-folded output-head corpus of :func:`item_corpus`.
    ``cont_columns`` is empty (sequence requests carry no continuous
    features); fleet/frontend code must not assume a CTR column set.
    """

    model: str
    embed_dim: int
    max_len: int
    n_items: int
    features: tuple[str, ...]
    cont_columns: tuple[str, ...]
    _score: Callable = field(repr=False)
    _params: tuple = field(repr=False)  # trailing args for the jitted fns
    _query: Callable = field(repr=False)

    @property
    def mask_id(self) -> int:
        return self.n_items + 1

    def score(self, batch: Mapping[str, jax.Array]) -> jax.Array:
        return self._score(dict(batch), *self._params)

    def query_embed(self, batch: Mapping[str, jax.Array]) -> jax.Array:
        return self._query(dict(batch), *self._params)

    def score_cache_size(self) -> int:
        """Compiled-program count of the scoring step (one per padded batch
        shape) — the frontend's compile-count regression hook."""
        return self._score._cache_size()


def _device_tree(tree, mesh):
    put = (partial(jax.device_put, device=replicated_sharding(mesh))
           if mesh is not None else jnp.asarray)
    return jax.tree.map(put, tree)


def _check_seq_bundle(bundle: ServingBundle) -> tuple[int, dict]:
    """Schema refusals shared by the scorer and the corpus builder: wrong
    family, missing/incomplete seq hyperparameters, vocab drift."""
    if bundle.model != "bert4rec":
        raise ValueError(
            f"seq scorer got a {bundle.model!r} bundle — the CTR family "
            "(twotower/dlrm) is served by serve.scoring.make_scorer")
    if bundle.kind != "sparse":
        raise ValueError(
            "bert4rec bundles are sparse (item table + dense backbone split, "
            f"the DMP regime), got kind={bundle.kind!r}")
    seq = bundle.seq
    if not seq:
        raise ValueError(
            "bundle carries no seq hyperparameters — re-export with "
            "export_bundle(..., seq={'max_len': ..., 'n_heads': ..., "
            "'n_layers': ...}); a bundle without them cannot rebuild the "
            "backbone geometry")
    missing = [k for k in ("max_len", "n_heads", "n_layers") if k not in seq]
    if missing:
        raise ValueError(f"bundle seq hyperparameters missing {missing}")
    n_items = int(bundle.size_map.get(
        "n_items", bundle.size_map.get("item", 0)))
    if not n_items:
        raise ValueError("bert4rec bundle needs n_items in size_map")
    if set(bundle.tables) != {"item_embedding"}:
        raise ValueError(
            f"bundle tables {sorted(bundle.tables)} do not match the "
            "bert4rec schema ['item_embedding'] — wrong bundle for this "
            "model/config")
    vocab = n_items + 2  # PAD(0) + items(1..n) + MASK(n+1)
    rows, dim = bundle.tables["item_embedding"].shape
    if rows != vocab or dim != bundle.embed_dim:
        raise ValueError(
            f"item_embedding is [{rows}, {dim}] but size_map says n_items="
            f"{n_items} (vocab {vocab}) at embed_dim {bundle.embed_dim} — "
            "vocab drift; the bundle and the catalog disagree")
    return n_items, dict(seq)


def make_seq_scorer(bundle: ServingBundle, *, mesh=None) -> SeqScorer:
    """Bundle -> :class:`SeqScorer`.  ``mesh`` replicates the parameters
    over it (the table is replicated at serve time; retrieval shards the
    CORPUS, not the table — ``serve/retrieval.py``)."""
    from tdfo_tpu.parallel.embedding import (
        EmbeddingSpec,
        ShardedEmbeddingCollection,
    )

    n_items, seq = _check_seq_bundle(bundle)
    cfg = Bert4RecConfig(
        n_items=n_items,
        max_len=int(seq["max_len"]),
        embed_dim=bundle.embed_dim,
        n_heads=int(seq["n_heads"]),
        n_layers=int(seq["n_layers"]),
    )
    # replicated + non-fused: the single logical table keeps its own [V, d]
    # array under its own name, exactly the merged-bundle layout
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("item_embedding", num_embeddings=cfg.vocab_size,
                       embedding_dim=cfg.embed_dim, features=("item",),
                       sharding="replicated", init_scale=1.0)],
        mesh=mesh,
    )
    backbone = Bert4RecBackbone(cfg=cfg, dtype=bundle.jax_dtype)
    tables = _device_tree(dict(bundle.tables), mesh)
    dense_params = _device_tree(bundle.dense_params, mesh)

    last_block = f"block_{cfg.n_layers - 1}"

    def last_hidden(tables, dense_params, seqs):
        # the hidden state FEEDING out_proj at the appended-MASK (last)
        # position — the last transformer block's output; flax intermediate
        # capture reads it without restructuring the module, and the unused
        # full [B, T, V] primal output is dead code XLA eliminates
        embs = coll.lookup(tables, {"item": seqs}, mode="gspmd")
        _, st = backbone.apply(
            {"params": dense_params}, embs["item"], key_padding_mask(seqs),
            capture_intermediates=lambda mdl, _: mdl.name == last_block,
            mutable=["intermediates"],
        )
        h = st["intermediates"][last_block]["__call__"][0]
        return h[:, -1, :]

    @partial(jax.jit, donate_argnums=(0,))
    def score(batch, tables, dense_params):
        # masked-position scoring: only the last position is ever served, so
        # out_proj runs on [B, d] — a row slice of the Dense lhs, bitwise
        # equal per computed element to the trainer eval's full-sequence
        # projection (trainer.py seq eval_accum) while the [B, T, V] logits
        # cube never materializes (XLA does NOT sink the slice into the
        # matmul: measured [B*T, V] live at bench scale, 420 GB at B=8192)
        h = last_hidden(tables, dense_params, batch["seqs"])
        op = dense_params["out_proj"]
        logits = (jnp.dot(h, jnp.asarray(op["kernel"], h.dtype))
                  + jnp.asarray(op["bias"], h.dtype))  # [B, V]
        return jnp.take_along_axis(logits, batch["cands"], axis=1)

    @jax.jit
    def query(batch, tables, dense_params):
        # the MIPS query against item_corpus: [h, 1] — the appended
        # constant picks up the head-bias column folded into every corpus
        # row, so dot(query, corpus[v]) = h @ W_out[:, v] + b_out[v], the
        # served logit itself
        h = last_hidden(tables, dense_params, batch["seqs"])
        h = h.astype(jnp.float32)
        return jnp.concatenate(
            [h, jnp.ones((h.shape[0], 1), jnp.float32)], axis=1)

    return SeqScorer(
        model=bundle.model, embed_dim=bundle.embed_dim, max_len=cfg.max_len,
        n_items=n_items, features=SEQ_FEATURES, cont_columns=(),
        _score=score, _params=(tables, dense_params), _query=query,
    )


def history_window(
    history: Sequence[int],
    *,
    n_items: int,
    max_len: int,
    max_history: int = 0,
) -> np.ndarray:
    """Ragged user history -> the fixed ``[max_len]`` eval window: truncate
    LEFT (keep the newest items), append the MASK token, LEFT-pad with
    ``PAD_ID`` so the tail stays right-aligned — the eval-sequence
    construction of ``torchrec/preprocessing.py:229-239`` applied to a live
    request.  ``max_history`` caps the kept raw items (0 = the protocol's
    full ``max_len - 1`` window)."""
    keep = max_len - 1
    if max_history > 0:
        keep = min(max_history, keep)
    hist = np.asarray(list(history), dtype=np.int64).reshape(-1)
    if hist.size and (hist.min() < 1 or hist.max() > n_items):
        bad = hist[(hist < 1) | (hist > n_items)]
        raise ValueError(
            f"history item id {int(bad[0])} outside the catalog [1, "
            f"{n_items}] — PAD({PAD_ID}) and MASK({n_items + 1}) are "
            "reserved ids, not items")
    tail = np.concatenate(
        [hist[-keep:] if keep else hist[:0], [n_items + 1]]).astype(np.int32)
    out = np.full((max_len,), PAD_ID, np.int32)
    out[-len(tail):] = tail
    return out


def item_corpus(
    bundle: ServingBundle,
    *,
    mesh=None,
    axis: str = DATA_AXIS,
    dtype: str = "float32",
) -> Corpus:
    """The bundle's trained OUTPUT-PROJECTION head as a retrieval
    :class:`~tdfo_tpu.serve.corpus.Corpus`: row ``v`` is the head column
    ``[W_out[:, v]; b_out[v]]`` (a ``[D+1]`` vector, bias folded in) for the
    catalog items ``v = 1..n_items`` (the PAD and MASK columns are reserved,
    never candidates), ids = the 1-based catalog item ids.  Queried with
    :meth:`SeqScorer.query_embed` (``[h, 1]``) every inner product is the
    served masked-position logit, so retrieval ranks exactly like
    ``SeqScorer.score`` — ``out_proj`` is untied from the input item table
    (``models/bert4rec.py``), which is why the table rows are NOT the
    corpus.  Shard-aligned exactly like ``build_corpus`` (zero rows,
    ids = -1) and storable through ``export_corpus`` / searchable by
    ``make_retrieval`` unchanged — including the int8 two-stage path."""
    if dtype not in STORAGE_DTYPES:
        raise ValueError(f"corpus dtype {dtype!r} not in {STORAGE_DTYPES}")
    n_items, _ = _check_seq_bundle(bundle)
    op = (bundle.dense_params or {}).get("out_proj")
    if not isinstance(op, Mapping) or "kernel" not in op or "bias" not in op:
        raise ValueError(
            "bundle dense params carry no out_proj kernel/bias — the "
            "retrieval corpus is the output head (out_proj is untied from "
            "the item table), so a headless bundle cannot retrieve")
    kernel = np.asarray(op["kernel"], dtype=np.float32)  # [d, V]
    bias = np.asarray(op["bias"], dtype=np.float32)  # [V]
    vocab = n_items + 2
    if kernel.shape != (bundle.embed_dim, vocab) or bias.shape != (vocab,):
        raise ValueError(
            f"out_proj geometry kernel{kernel.shape} bias{bias.shape} does "
            f"not match embed_dim {bundle.embed_dim} x vocab {vocab} — "
            "head drift; the bundle and the catalog disagree")
    head = np.concatenate([kernel.T, bias[:, None]], axis=1)  # [V, d+1]
    vectors = jnp.asarray(head[1:n_items + 1])
    ids = jnp.arange(1, n_items + 1, dtype=jnp.int32)

    n_shards = mesh.shape[axis] if mesh is not None else 1
    n_pad = -(-n_items // n_shards) * n_shards - n_items
    if n_pad:
        vectors = jnp.pad(vectors, [(0, n_pad), (0, 0)])
        ids = jnp.pad(ids, [(0, n_pad)], constant_values=-1)
    qscale = None
    if dtype == "bfloat16":
        vectors = vectors.astype(jnp.bfloat16)
    elif dtype == "int8":
        vectors, qscale = quantize_rows(vectors)
    if mesh is not None:
        vectors = jax.device_put(vectors, NamedSharding(mesh, P(axis, None)))
        ids = jax.device_put(ids, NamedSharding(mesh, P(axis)))
        if qscale is not None:
            qscale = jax.device_put(
                qscale, NamedSharding(mesh, P(axis, None)))
    return Corpus(vectors=vectors, ids=ids, n_items=n_items, qscale=qscale)
