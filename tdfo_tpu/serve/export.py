"""Checkpoint -> serving bundle: the training/serving parameter contract.

Industrial recsys stacks keep this contract explicit (Monolith, Liu et al.
2022: training checkpoints are periodically snapshotted into parameter-server
serving replicas); the reference's closest analogue is the flax byte blob
written once at train end (``jax-flax/models.py:128-139``).  Here the bundle
is a directory with a JSON manifest + one ``arrays.npz``:

  * optimizer slots are DROPPED — fused fat-line tables are unpacked
    (``ops/pallas_kernels.fat_unpack``) back to plain ``[V, d]`` rows, stacked
    arrays (``__tablestack_`` / ``__fatstack_`` / ``__stack_``) are de-stacked
    to logical tables, and row-shard padding rows are sliced off;
  * ``{name}__hot`` replicated heads are merged back into their cold rows
    (the live values — the duplicated cold rows are dead storage during
    training, ``parallel/embedding.py init``), so bundles are
    hot/cold-agnostic: a split and an unsplit run of the same state export
    byte-identical tables;
  * an optional bf16 cast policy via :func:`tdfo_tpu.core.precision.compute_dtype`
    (off by default: f32 bundles keep serving logits bitwise equal to
    training eval logits);
  * the manifest stamps ``bundle_version`` + a per-array schema, and
    :func:`load_bundle` REFUSES version/schema mismatches with a clear error
    instead of serving scrambled rows — the same stance as the training
    restore path (``train/checkpoint.py LAYOUT_VERSION`` / stamps sidecar).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from tdfo_tpu.core.precision import compute_dtype
from tdfo_tpu.parallel.embedding import CACHE_PREFIX, ShardedEmbeddingCollection

__all__ = [
    "BUNDLE_VERSION",
    "ServingBundle",
    "export_bundle",
    "load_bundle",
    "merged_tables",
]

# Bundle schema version, stamped into every manifest and verified on load.
# Bump on any change that would load without shape errors but scramble
# values (array key scheme, table packing, param flattening).
BUNDLE_VERSION = 1

_MANIFEST = "bundle.json"
_ARRAYS = "arrays.npz"


def merged_tables(
    coll: ShardedEmbeddingCollection,
    tables: Mapping[str, jax.Array],
    caches: Mapping[str, Any] | None = None,
) -> dict[str, np.ndarray]:
    """Live ``init()`` pytree -> logical ``{table_name: [V, d] f32}`` rows.

    Inverts every storage transform the collection applies: fat-line packing
    (optimizer state dropped), table stacking (member slices), row-shard
    padding (sliced to ``num_embeddings``), and the hot/cold split (hot head
    rows written back over their dead cold duplicates).  Host-side numpy —
    export is offline, so the scatter-avoidance rules for jitted steps do
    not apply here.

    ``caches``: the ``state.slots`` update-cache entries (keys prefixed
    ``CACHE_PREFIX``) of a cache-enabled run whose state was NOT flushed
    first — dirty cached rows overlay their stale big-table values
    verbatim, so bundles from cached and eager runs of the same trajectory
    stay bitwise-identical.  Flushed (or cache-off) states need no
    ``caches``; the trainer flushes before every checkpoint so exports
    from checkpoints never do.
    """
    from tdfo_tpu.ops.pallas_kernels import fat_view

    views: dict[str, np.ndarray] = {}  # array name -> [rows, >=d] host view
    out: dict[str, np.ndarray] = {}
    for tname, spec in coll.specs.items():
        aname, _, off = coll.resolve_table(tname)
        if aname not in views:
            arr = jax.device_get(tables[aname])
            if arr.ndim == 3:  # fused fat lines [L, T, 128]
                lay = coll.fat_layout(coll.array_embedding_dim(aname))
                arr = np.asarray(fat_view(jnp.asarray(arr), lay))
            arr = np.asarray(arr)
            cache = (caches or {}).get(CACHE_PREFIX + aname)
            if cache is not None:
                # write dirty cached rows back over their stale big-table
                # values (bit-copy, the host twin of cache_flush)
                c = jax.device_get(cache)
                ids = np.asarray(c["ids"])
                slot = np.asarray(c["slot"])
                dirty = np.asarray(c["dirty"])[slot] & (ids < 2**31 - 1)
                if dirty.any():
                    arr = arr.copy()
                    arr[ids[dirty]] = np.asarray(c["rows"])[slot[dirty]]
            views[aname] = arr
        d = spec.embedding_dim
        rows = np.array(
            views[aname][off:off + spec.num_embeddings, :d], dtype=np.float32
        )
        hids = coll.hot_ids.get(tname)
        if hids is not None:
            hot = np.asarray(
                jax.device_get(tables[coll.hot_array_name(tname)]),
                dtype=np.float32,
            )
            rows[hids] = hot
        out[tname] = rows
    return out


@dataclass(frozen=True)
class ServingBundle:
    """A loaded serving bundle (see :func:`export_bundle` for the contract).

    ``kind`` = "sparse" (DMP regime: logical ``tables`` + backbone
    ``dense_params``) or "dense" (replicated TwoTower: one full flax
    ``params`` tree, ``nn.Embed`` tables included)."""

    kind: str
    model: str
    embed_dim: int
    cat_columns: tuple[str, ...]
    cont_columns: tuple[str, ...]
    size_map: dict[str, int]
    step: int
    dtype: str  # "float32" | "bfloat16" — the export cast policy
    tables: dict[str, np.ndarray] | None  # sparse kind
    dense_params: dict | None  # sparse kind
    params: dict | None  # dense kind

    @property
    def jax_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> dict[str, np.ndarray]:
    flat: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, Mapping):
            flat.update(_flatten(v, key))
        else:
            flat[key] = np.asarray(jax.device_get(v))
    return flat


def _unflatten(flat: Mapping[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        node = tree
        *parents, leaf = key.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = v
    return tree


def _store(arr: np.ndarray, dtype: jnp.dtype) -> np.ndarray:
    """Apply the cast policy; bf16 ships as uint16 bit patterns (npz has no
    native bfloat16) and the manifest dtype tells the loader to view back."""
    if not np.issubdtype(arr.dtype, np.floating):
        return arr
    if dtype == jnp.bfloat16:
        return np.asarray(arr, dtype=jnp.bfloat16).view(np.uint16)
    return np.asarray(arr, np.float32)


def _load_stored(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if dtype_name == "bfloat16" and arr.dtype == np.uint16:
        return arr.view(jnp.bfloat16)
    return arr


def export_bundle(
    out_dir: str | Path,
    *,
    model: str,
    embed_dim: int,
    cat_columns: tuple[str, ...],
    cont_columns: tuple[str, ...],
    size_map: Mapping[str, int],
    step: int = 0,
    coll: ShardedEmbeddingCollection | None = None,
    tables: Mapping[str, jax.Array] | None = None,
    dense_params: Mapping[str, Any] | None = None,
    params: Mapping[str, Any] | None = None,
    caches: Mapping[str, Any] | None = None,
    mixed_precision: bool = False,
    platform: str | None = None,
) -> Path:
    """Write a serving bundle directory and return its path.

    Sparse/DMP regime: pass ``coll`` + ``tables`` + ``dense_params`` (the
    ``SparseTrainState`` pieces); tables are merged via :func:`merged_tables`.
    Dense regime (replicated TwoTower): pass ``params`` (the full flax tree).
    ``caches``: forwarded to :func:`merged_tables` — REQUIRED when exporting
    an UNFLUSHED cache-enabled live state (checkpointed states are always
    flushed).  ``mixed_precision=True`` applies the platform cast policy
    (:func:`compute_dtype`: bf16 on TPU) to every floating array; the default
    keeps f32 so serving logits stay bitwise equal to training eval logits.
    """
    if (coll is None) == (params is None):
        raise ValueError(
            "export_bundle takes either coll+tables+dense_params (sparse "
            "regime) or params (dense regime), not both/neither")
    dtype = compute_dtype(mixed_precision, platform)
    dtype_name = jnp.dtype(dtype).name
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    arrays: dict[str, np.ndarray] = {}
    manifest: dict[str, Any] = {
        "bundle_version": BUNDLE_VERSION,
        "kind": "sparse" if coll is not None else "dense",
        "model": model,
        "embed_dim": int(embed_dim),
        "cat_columns": list(cat_columns),
        "cont_columns": list(cont_columns),
        "size_map": {k: int(v) for k, v in size_map.items()},
        "step": int(step),
        "dtype": dtype_name,
    }
    if coll is not None:
        if tables is None or dense_params is None:
            raise ValueError("sparse export needs tables and dense_params")
        logical = merged_tables(coll, tables, caches)
        manifest["tables"] = {
            n: [int(t.shape[0]), int(t.shape[1])] for n, t in logical.items()
        }
        for n, t in logical.items():
            arrays[f"table:{n}"] = _store(t, dtype)
        for k, v in _flatten(dense_params).items():
            arrays[f"dense:{k}"] = _store(v, dtype)
    else:
        for k, v in _flatten(params).items():
            arrays[f"params:{k}"] = _store(v, dtype)

    np.savez(out / _ARRAYS, **arrays)
    (out / _MANIFEST).write_text(json.dumps(manifest, indent=1, sort_keys=True))
    return out


def load_bundle(bundle_dir: str | Path) -> ServingBundle:
    """Load and VALIDATE a serving bundle; refuses anything suspect.

    Refusal cases (each a ``ValueError`` naming the cause, mirroring the
    training restore discipline): missing manifest, ``bundle_version``
    mismatch, manifest/array key drift, and per-table shape drift — all of
    which could otherwise serve scrambled or stale parameters silently.
    """
    bdir = Path(bundle_dir)
    mpath = bdir / _MANIFEST
    if not mpath.exists():
        raise ValueError(f"{bdir} is not a serving bundle (no {_MANIFEST})")
    manifest = json.loads(mpath.read_text())
    found = manifest.get("bundle_version")
    if found != BUNDLE_VERSION:
        raise ValueError(
            f"serving bundle {bdir} has bundle_version {found!r}, this build "
            f"serves {BUNDLE_VERSION}.  The array schemas are not "
            "value-compatible across versions; re-export the checkpoint.")
    dtype_name = manifest["dtype"]
    with np.load(bdir / _ARRAYS) as z:
        arrays = {k: _load_stored(z[k], dtype_name) for k in z.files}

    kind = manifest["kind"]
    tables = dense_params = params = None
    if kind == "sparse":
        schema = manifest["tables"]
        stored = {k.removeprefix("table:") for k in arrays if k.startswith("table:")}
        if stored != set(schema):
            raise ValueError(
                f"serving bundle {bdir}: manifest tables {sorted(schema)} != "
                f"stored arrays {sorted(stored)} — refusing a torn bundle")
        tables = {}
        for n, (rows, dim) in schema.items():
            t = arrays[f"table:{n}"]
            if t.shape != (rows, dim):
                raise ValueError(
                    f"serving bundle {bdir}: table {n!r} is {t.shape}, "
                    f"manifest says {(rows, dim)} — refusing a torn bundle")
            tables[n] = t
        dense_params = _unflatten({
            k.removeprefix("dense:"): v
            for k, v in arrays.items() if k.startswith("dense:")
        })
    elif kind == "dense":
        params = _unflatten({
            k.removeprefix("params:"): v
            for k, v in arrays.items() if k.startswith("params:")
        })
        if not params:
            raise ValueError(f"serving bundle {bdir}: dense bundle holds no params")
    else:
        raise ValueError(f"serving bundle {bdir}: unknown kind {kind!r}")

    return ServingBundle(
        kind=kind,
        model=manifest["model"],
        embed_dim=int(manifest["embed_dim"]),
        cat_columns=tuple(manifest["cat_columns"]),
        cont_columns=tuple(manifest["cont_columns"]),
        size_map={k: int(v) for k, v in manifest["size_map"].items()},
        step=int(manifest["step"]),
        dtype=dtype_name,
        tables=tables,
        dense_params=dense_params,
        params=params,
    )
