"""Checkpoint -> serving bundle: the training/serving parameter contract.

Industrial recsys stacks keep this contract explicit (Monolith, Liu et al.
2022: training checkpoints are periodically snapshotted into parameter-server
serving replicas); the reference's closest analogue is the flax byte blob
written once at train end (``jax-flax/models.py:128-139``).  Here the bundle
is a directory with a JSON manifest + one ``arrays.npz``:

  * optimizer slots are DROPPED — fused fat-line tables are unpacked
    (``ops/pallas_kernels.fat_unpack``) back to plain ``[V, d]`` rows, stacked
    arrays (``__tablestack_`` / ``__fatstack_`` / ``__stack_``) are de-stacked
    to logical tables, and row-shard padding rows are sliced off;
  * ``{name}__hot`` replicated heads are merged back into their cold rows
    (the live values — the duplicated cold rows are dead storage during
    training, ``parallel/embedding.py init``), so bundles are
    hot/cold-agnostic: a split and an unsplit run of the same state export
    byte-identical tables;
  * an optional bf16 cast policy via :func:`tdfo_tpu.core.precision.compute_dtype`
    (off by default: f32 bundles keep serving logits bitwise equal to
    training eval logits);
  * the manifest stamps ``bundle_version`` + a per-array schema, and
    :func:`load_bundle` REFUSES version/schema mismatches with a clear error
    instead of serving scrambled rows — the same stance as the training
    restore path (``train/checkpoint.py LAYOUT_VERSION`` / stamps sidecar).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from tdfo_tpu.core.precision import compute_dtype
from tdfo_tpu.ops.quant import QSCALE_LAYOUT, STORAGE_DTYPES, dequantize_rows
from tdfo_tpu.parallel.embedding import (
    CACHE_PREFIX,
    ShardedEmbeddingCollection,
    qscale_name,
)

__all__ = [
    "BUNDLE_VERSION",
    "QSCALE_LAYOUT",
    "ServingBundle",
    "apply_delta_arrays",
    "bundle_digest",
    "bundle_from_raw",
    "export_bundle",
    "export_corpus",
    "export_delta",
    "load_bundle",
    "load_corpus",
    "merged_tables",
    "read_raw_bundle",
    "write_raw_bundle",
]

# Bundle schema version, stamped into every manifest and verified on load.
# Bump on any change that would load without shape errors but scramble
# values (array key scheme, table packing, param flattening).
BUNDLE_VERSION = 1

_MANIFEST = "bundle.json"
_ARRAYS = "arrays.npz"


def merged_tables(
    coll: ShardedEmbeddingCollection,
    tables: Mapping[str, jax.Array],
    caches: Mapping[str, Any] | None = None,
) -> dict[str, np.ndarray]:
    """Live ``init()`` pytree -> logical ``{table_name: [V, d] f32}`` rows.

    Inverts every storage transform the collection applies: fat-line packing
    (optimizer state dropped), table stacking (member slices), row-shard
    padding (sliced to ``num_embeddings``), and the hot/cold split (hot head
    rows written back over their dead cold duplicates).  Host-side numpy —
    export is offline, so the scatter-avoidance rules for jitted steps do
    not apply here.

    ``caches``: the ``state.slots`` update-cache entries (keys prefixed
    ``CACHE_PREFIX``) of a cache-enabled run whose state was NOT flushed
    first — dirty cached rows overlay their stale big-table values
    verbatim, so bundles from cached and eager runs of the same trajectory
    stay bitwise-identical.  Flushed (or cache-off) states need no
    ``caches``; the trainer flushes before every checkpoint so exports
    from checkpoints never do.
    """
    from tdfo_tpu.ops.pallas_kernels import fat_view

    views: dict[str, np.ndarray] = {}  # array name -> [rows, >=d] host view
    out: dict[str, np.ndarray] = {}
    for tname, spec in coll.specs.items():
        aname, _, off = coll.resolve_table(tname)
        if aname not in views:
            arr = jax.device_get(tables[aname])
            if arr.ndim == 3:  # fused fat lines [L, T, 128]
                lay = coll.fat_layout(coll.array_embedding_dim(aname))
                arr = np.asarray(fat_view(jnp.asarray(arr), lay))
            arr = np.asarray(arr)
            cache = (caches or {}).get(CACHE_PREFIX + aname)
            if cache is not None:
                # write dirty cached rows back over their stale big-table
                # values (bit-copy, the host twin of cache_flush)
                c = jax.device_get(cache)
                ids = np.asarray(c["ids"])
                slot = np.asarray(c["slot"])
                dirty = np.asarray(c["dirty"])[slot] & (ids < 2**31 - 1)
                if dirty.any():
                    arr = arr.copy()
                    arr[ids[dirty]] = np.asarray(c["rows"])[slot[dirty]]
            views[aname] = arr
        d = spec.embedding_dim
        view = views[aname]
        if view.dtype == np.int8:
            # int8 arrays dequantize through their __qscale__/ sidecar —
            # a raw cast would export codes, not values
            qs = np.asarray(jax.device_get(tables[qscale_name(aname)]))
            rows = np.asarray(
                dequantize_rows(
                    view[off:off + spec.num_embeddings, :d],
                    qs[off:off + spec.num_embeddings]),
                dtype=np.float32)
        else:
            rows = np.array(
                view[off:off + spec.num_embeddings, :d], dtype=np.float32
            )
        hids = coll.hot_ids.get(tname)
        if hids is not None:
            hot = np.asarray(
                jax.device_get(tables[coll.hot_array_name(tname)]),
                dtype=np.float32,
            )
            rows[hids] = hot
        out[tname] = rows
    return out


@dataclass(frozen=True)
class ServingBundle:
    """A loaded serving bundle (see :func:`export_bundle` for the contract).

    ``kind`` = "sparse" (DMP regime: logical ``tables`` + backbone
    ``dense_params``) or "dense" (replicated TwoTower: one full flax
    ``params`` tree, ``nn.Embed`` tables included)."""

    kind: str
    model: str
    embed_dim: int
    cat_columns: tuple[str, ...]
    cont_columns: tuple[str, ...]
    size_map: dict[str, int]
    step: int
    dtype: str  # "float32" | "bfloat16" — the export cast policy
    tables: dict[str, np.ndarray] | None  # sparse kind
    dense_params: dict | None  # sparse kind
    params: dict | None  # dense kind
    # sequence-model hyperparameters (bert4rec bundles: max_len / n_heads /
    # n_layers — the backbone geometry the scorer must rebuild EXACTLY; a
    # drifted max_len would silently mis-position the appended MASK).  None
    # for the CTR family.
    seq: dict | None = None
    version: int = 0  # chain position (delta exports stack on this)
    digest: str = ""  # manifest content digest (see bundle_digest)

    @property
    def jax_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> dict[str, np.ndarray]:
    flat: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, Mapping):
            flat.update(_flatten(v, key))
        else:
            flat[key] = np.asarray(jax.device_get(v))
    return flat


def _unflatten(flat: Mapping[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        node = tree
        *parents, leaf = key.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = v
    return tree


def _store(arr: np.ndarray, dtype: jnp.dtype) -> np.ndarray:
    """Apply the cast policy; bf16 ships as uint16 bit patterns (npz has no
    native bfloat16) and the manifest dtype tells the loader to view back."""
    if not np.issubdtype(arr.dtype, np.floating):
        return arr
    if dtype == jnp.bfloat16:
        return np.asarray(arr, dtype=jnp.bfloat16).view(np.uint16)
    return np.asarray(arr, np.float32)


def _load_stored(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if dtype_name == "bfloat16" and arr.dtype == np.uint16:
        return arr.view(jnp.bfloat16)
    return arr


def bundle_digest(manifest: Mapping[str, Any],
                  arrays: Mapping[str, np.ndarray]) -> str:
    """Content digest of a bundle: canonical manifest (minus ``digest``) +
    every STORED array's key/dtype/shape/bytes, sha256 truncated to 16 hex.

    Computed over the stored representation (bf16 ships as uint16 bit
    patterns), so the digest is stable across save/load round trips —
    ``np.savez`` container bytes are NOT hashed (zip metadata is not
    deterministic)."""
    core = {k: v for k, v in manifest.items() if k != "digest"}
    h = hashlib.sha256(json.dumps(core, sort_keys=True).encode())
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def read_raw_bundle(bundle_dir: str | Path) -> tuple[dict, dict[str, np.ndarray]]:
    """Read a bundle/delta directory as (manifest, STORED arrays) — no
    dtype view-back, no validation beyond file presence.  The form
    :func:`bundle_digest` hashes; the swap store verifies on top of this."""
    bdir = Path(bundle_dir)
    mpath = bdir / _MANIFEST
    if not mpath.exists():
        raise ValueError(f"{bdir} is not a serving bundle (no {_MANIFEST})")
    manifest = json.loads(mpath.read_text())
    with np.load(bdir / _ARRAYS) as z:
        arrays = {k: z[k] for k in z.files}
    return manifest, arrays


def write_raw_bundle(out_dir: str | Path, manifest: Mapping[str, Any],
                     arrays: Mapping[str, np.ndarray]) -> Path:
    """Write arrays.npz first, manifest last (the manifest is the commit
    point a reader keys off).  Durable/atomic publication of whole bundle
    directories is :mod:`tdfo_tpu.serve.swap`'s job, not this writer's."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.savez(out / _ARRAYS, **arrays)
    (out / _MANIFEST).write_text(json.dumps(manifest, indent=1, sort_keys=True))
    return out


def export_bundle(
    out_dir: str | Path,
    *,
    model: str,
    embed_dim: int,
    cat_columns: tuple[str, ...],
    cont_columns: tuple[str, ...],
    size_map: Mapping[str, int],
    step: int = 0,
    coll: ShardedEmbeddingCollection | None = None,
    tables: Mapping[str, jax.Array] | None = None,
    dense_params: Mapping[str, Any] | None = None,
    params: Mapping[str, Any] | None = None,
    caches: Mapping[str, Any] | None = None,
    mixed_precision: bool = False,
    platform: str | None = None,
    version: int = 0,
    seq: Mapping[str, int] | None = None,
) -> Path:
    """Write a serving bundle directory and return its path.

    Sparse/DMP regime: pass ``coll`` + ``tables`` + ``dense_params`` (the
    ``SparseTrainState`` pieces); tables are merged via :func:`merged_tables`.
    Dense regime (replicated TwoTower): pass ``params`` (the full flax tree).
    ``caches``: forwarded to :func:`merged_tables` — REQUIRED when exporting
    an UNFLUSHED cache-enabled live state (checkpointed states are always
    flushed).  ``mixed_precision=True`` applies the platform cast policy
    (:func:`compute_dtype`: bf16 on TPU) to every floating array; the default
    keeps f32 so serving logits stay bitwise equal to training eval logits.
    ``version`` is the bundle's chain position (delta exports stack on top
    of it, :func:`export_delta`); the manifest also stamps a content
    ``digest`` so consumers can verify integrity end to end.
    ``seq``: sequence-model hyperparameters (bert4rec: max_len / n_heads /
    n_layers) stamped into the manifest so the serving scorer rebuilds the
    exact backbone geometry — and so deltas refuse max_len drift.
    """
    if (coll is None) == (params is None):
        raise ValueError(
            "export_bundle takes either coll+tables+dense_params (sparse "
            "regime) or params (dense regime), not both/neither")
    dtype = compute_dtype(mixed_precision, platform)
    manifest, arrays = _materialize(
        model=model, embed_dim=embed_dim, cat_columns=cat_columns,
        cont_columns=cont_columns, size_map=size_map, step=step, coll=coll,
        tables=tables, dense_params=dense_params, params=params,
        caches=caches, dtype=dtype, version=version, seq=seq)
    manifest["digest"] = bundle_digest(manifest, arrays)
    return write_raw_bundle(out_dir, manifest, arrays)


def _materialize(
    *, model, embed_dim, cat_columns, cont_columns, size_map, step, coll,
    tables, dense_params, params, caches, dtype, version, seq=None,
) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    """Shared bundle materialization: (manifest sans digest, stored arrays)."""
    dtype_name = jnp.dtype(dtype).name
    arrays: dict[str, np.ndarray] = {}
    manifest: dict[str, Any] = {
        "bundle_version": BUNDLE_VERSION,
        "kind": "sparse" if coll is not None else "dense",
        "model": model,
        "embed_dim": int(embed_dim),
        "cat_columns": list(cat_columns),
        "cont_columns": list(cont_columns),
        "size_map": {k: int(v) for k, v in size_map.items()},
        "step": int(step),
        "dtype": dtype_name,
        "version": int(version),
    }
    if seq is not None:
        manifest["seq"] = {k: int(v) for k, v in dict(seq).items()}
    if coll is not None:
        if tables is None or dense_params is None:
            raise ValueError("sparse export needs tables and dense_params")
        logical = merged_tables(coll, tables, caches)
        manifest["tables"] = {
            n: [int(t.shape[0]), int(t.shape[1])] for n, t in logical.items()
        }
        for n, t in logical.items():
            arrays[f"table:{n}"] = _store(t, dtype)
        for k, v in _flatten(dense_params).items():
            arrays[f"dense:{k}"] = _store(v, dtype)
    else:
        for k, v in _flatten(params).items():
            arrays[f"params:{k}"] = _store(v, dtype)
    return manifest, arrays


def _row_diff(new: np.ndarray, base: np.ndarray) -> np.ndarray:
    """Boolean [rows] mask of rows whose STORED bytes differ (byte compare,
    so NaNs and negative zeros diff exactly like the digest sees them)."""
    a = np.ascontiguousarray(new).view(np.uint8).reshape(new.shape[0], -1)
    b = np.ascontiguousarray(base).view(np.uint8).reshape(base.shape[0], -1)
    return np.any(a != b, axis=1)


def export_delta(
    out_dir: str | Path,
    base_dir: str | Path,
    *,
    model: str,
    embed_dim: int,
    cat_columns: tuple[str, ...],
    cont_columns: tuple[str, ...],
    size_map: Mapping[str, int],
    step: int,
    coll: ShardedEmbeddingCollection,
    tables: Mapping[str, jax.Array],
    dense_params: Mapping[str, Any],
    caches: Mapping[str, Any] | None = None,
    mixed_precision: bool = False,
    platform: str | None = None,
    touched: Mapping[str, np.ndarray] | None = None,
    seq: Mapping[str, int] | None = None,
) -> Path:
    """Export only the rows that changed since the ``base_dir`` bundle.

    The serving-side twin of incremental checkpointing (fbgemm inference
    model-update idiom; Monolith's minute-level sparse parameter sync, Liu
    et al. 2022 §3.3): per table, rows whose stored bytes differ from the
    base ship as ``delta_ids:{name}`` + ``delta_rows:{name}``; dense/backbone
    arrays that changed ship whole (they are KBs, not GBs).  The manifest is
    a chain entry — ``version = base + 1``, ``parent_digest``, and the
    ``result_digest`` the materialized bundle must hash to after
    :func:`apply_delta_arrays` — so a consumer can refuse gaps, re-orders,
    and corruption.

    ``touched``: optional per-table row-id hint (the PR-6 cache dirty sets /
    stream cursors).  The byte diff stays authoritative; a changed row
    OUTSIDE the hint is a loud error (a stale hint must never ship a stale
    delta silently).
    """
    base_manifest, base_arrays = read_raw_bundle(base_dir)
    want = base_manifest.get("digest")
    got = bundle_digest(base_manifest, base_arrays)
    if want != got:
        raise ValueError(
            f"delta base {base_dir}: digest {got} != manifest {want!r} — "
            "refusing to chain onto a corrupt base")
    if base_manifest["kind"] != "sparse":
        raise ValueError(
            f"delta export needs a sparse base bundle, got kind "
            f"{base_manifest['kind']!r} (dense bundles re-export whole)")
    dtype = compute_dtype(mixed_precision, platform)
    new_manifest, new_arrays = _materialize(
        model=model, embed_dim=embed_dim, cat_columns=cat_columns,
        cont_columns=cont_columns, size_map=size_map, step=step, coll=coll,
        tables=tables, dense_params=dense_params, params=None, caches=caches,
        dtype=dtype, version=int(base_manifest["version"]) + 1, seq=seq)
    # "seq" freezes the bert4rec backbone geometry (max_len/n_heads/
    # n_layers); CTR bundles compare absent == absent, no behaviour change
    frozen = ("kind", "model", "embed_dim", "cat_columns", "cont_columns",
              "size_map", "dtype", "tables", "seq")
    for key in frozen:
        if new_manifest.get(key) != base_manifest.get(key):
            raise ValueError(
                f"delta export schema drift on {key!r}: base "
                f"{base_manifest.get(key)!r} != new {new_manifest.get(key)!r}"
                " — a delta cannot change the bundle schema; re-export full")
    result_digest = bundle_digest(new_manifest, new_arrays)

    delta_arrays: dict[str, np.ndarray] = {}
    tables_delta: dict[str, int] = {}
    replaced: list[str] = []
    for key in sorted(new_arrays):
        if key.startswith("table:"):
            name = key.removeprefix("table:")
            mask = _row_diff(new_arrays[key], base_arrays[key])
            ids = np.nonzero(mask)[0].astype(np.int64)
            if touched is not None:
                hint = np.asarray(touched.get(name, ()), dtype=np.int64)
                stray = np.setdiff1d(ids, hint)
                if stray.size:
                    raise ValueError(
                        f"delta export: table {name!r} rows {stray[:8].tolist()}"
                        " changed outside the touched-row hint — the hint is "
                        "stale; refusing to ship a delta that would miss them")
            if ids.size:
                delta_arrays[f"delta_ids:{name}"] = ids
                delta_arrays[f"delta_rows:{name}"] = np.ascontiguousarray(
                    new_arrays[key][ids])
                tables_delta[name] = int(ids.size)
        elif _row_diff(new_arrays[key].reshape(1, -1),
                       base_arrays[key].reshape(1, -1))[0]:
            delta_arrays[key] = new_arrays[key]
            replaced.append(key)

    delta_manifest: dict[str, Any] = {
        "bundle_version": BUNDLE_VERSION,
        "kind": "delta",
        "base_kind": base_manifest["kind"],
        "model": model,
        "step": int(step),
        "dtype": new_manifest["dtype"],
        "version": int(base_manifest["version"]) + 1,
        "parent_version": int(base_manifest["version"]),
        "parent_digest": base_manifest["digest"],
        "result_digest": result_digest,
        "tables_delta": tables_delta,
        "replaced": replaced,
    }
    delta_manifest["digest"] = bundle_digest(delta_manifest, delta_arrays)
    return write_raw_bundle(out_dir, delta_manifest, delta_arrays)


def apply_delta_arrays(
    base_manifest: Mapping[str, Any],
    base_arrays: Mapping[str, np.ndarray],
    delta_manifest: Mapping[str, Any],
    delta_arrays: Mapping[str, np.ndarray],
) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    """Compose a delta onto its parent: (result manifest, stored arrays).

    Pure chain math (durability is :mod:`tdfo_tpu.serve.swap`'s job).
    Refuses, with a loud ``ValueError`` naming the cause: a non-delta
    manifest, a version gap or re-order (``parent_version`` mismatch), a
    parent whose digest is not the delta's ``parent_digest``, a delta whose
    own digest does not match its payload, and a composed result that does
    not hash to ``result_digest``.
    """
    if delta_manifest.get("kind") != "delta":
        raise ValueError(
            f"not a delta manifest (kind={delta_manifest.get('kind')!r})")
    if delta_manifest.get("bundle_version") != BUNDLE_VERSION:
        raise ValueError(
            f"delta has bundle_version {delta_manifest.get('bundle_version')!r},"
            f" this build serves {BUNDLE_VERSION}")
    base_v = int(base_manifest.get("version", 0))
    parent_v = int(delta_manifest["parent_version"])
    if parent_v != base_v:
        raise ValueError(
            f"delta chain out of order: delta v{delta_manifest['version']} "
            f"expects parent v{parent_v}, current bundle is v{base_v} — "
            "deltas apply strictly in version order, no gaps or re-orders")
    if delta_manifest["parent_digest"] != base_manifest.get("digest"):
        raise ValueError(
            f"delta parent digest mismatch: delta expects parent "
            f"{delta_manifest['parent_digest']}, current bundle digest is "
            f"{base_manifest.get('digest')!r} — the parent is not the bundle "
            "this delta was exported against")
    own = bundle_digest(delta_manifest, delta_arrays)
    if own != delta_manifest.get("digest"):
        raise ValueError(
            f"delta digest mismatch: payload hashes to {own}, manifest says "
            f"{delta_manifest.get('digest')!r} — corrupt delta")

    out_arrays = {k: v for k, v in base_arrays.items()}
    for key in delta_manifest.get("replaced", ()):
        out_arrays[key] = delta_arrays[key]
    for name in delta_manifest.get("tables_delta", {}):
        ids = delta_arrays[f"delta_ids:{name}"]
        rows = delta_arrays[f"delta_rows:{name}"]
        arr = np.array(base_arrays[f"table:{name}"])
        arr[ids] = rows
        out_arrays[f"table:{name}"] = arr

    out_manifest = {k: v for k, v in base_manifest.items() if k != "digest"}
    out_manifest["step"] = int(delta_manifest["step"])
    out_manifest["version"] = int(delta_manifest["version"])
    digest = bundle_digest(out_manifest, out_arrays)
    if digest != delta_manifest["result_digest"]:
        raise ValueError(
            f"delta result digest mismatch: composed bundle hashes to "
            f"{digest}, delta promises {delta_manifest['result_digest']} — "
            "refusing to serve an unverified composition")
    out_manifest["digest"] = digest
    return out_manifest, out_arrays


def load_bundle(bundle_dir: str | Path, *, verify: bool = False) -> ServingBundle:
    """Load and VALIDATE a serving bundle; refuses anything suspect.

    Refusal cases (each a ``ValueError`` naming the cause, mirroring the
    training restore discipline): missing manifest, ``bundle_version``
    mismatch, manifest/array key drift, and per-table shape drift — all of
    which could otherwise serve scrambled or stale parameters silently.
    ``verify=True`` additionally recomputes the content digest over the
    stored arrays and refuses a mismatch — the swap store's stance
    (:mod:`tdfo_tpu.serve.swap`) for every bundle it publishes or serves.
    """
    bdir = Path(bundle_dir)
    mpath = bdir / _MANIFEST
    if not mpath.exists():
        raise ValueError(f"{bdir} is not a serving bundle (no {_MANIFEST})")
    manifest = json.loads(mpath.read_text())
    with np.load(bdir / _ARRAYS) as z:
        raw = {k: z[k] for k in z.files}
    return bundle_from_raw(manifest, raw, source=str(bdir), verify=verify)


def bundle_from_raw(manifest: Mapping[str, Any],
                    raw_arrays: Mapping[str, np.ndarray], *,
                    source: str = "<memory>",
                    verify: bool = False) -> ServingBundle:
    """Validate ``(manifest, STORED arrays)`` into a :class:`ServingBundle`
    without touching disk — the same refusal cases as :func:`load_bundle`,
    which delegates here.  The gated supervisor uses this to score a
    candidate composition (``BundleStore.compose_delta``) on the shadow
    slice BEFORE any pointer names it; ``source`` labels the refusals."""
    found = manifest.get("bundle_version")
    if found != BUNDLE_VERSION:
        raise ValueError(
            f"serving bundle {source} has bundle_version {found!r}, this build "
            f"serves {BUNDLE_VERSION}.  The array schemas are not "
            "value-compatible across versions; re-export the checkpoint.")
    dtype_name = manifest["dtype"]
    if verify:
        got = bundle_digest(manifest, raw_arrays)
        if got != manifest.get("digest"):
            raise ValueError(
                f"serving bundle {source}: content digest {got} != manifest "
                f"{manifest.get('digest')!r} — refusing a corrupt bundle")
    arrays = {k: _load_stored(v, dtype_name) for k, v in raw_arrays.items()}

    kind = manifest["kind"]
    tables = dense_params = params = None
    if kind == "sparse":
        schema = manifest["tables"]
        stored = {k.removeprefix("table:") for k in arrays if k.startswith("table:")}
        if stored != set(schema):
            raise ValueError(
                f"serving bundle {source}: manifest tables {sorted(schema)} != "
                f"stored arrays {sorted(stored)} — refusing a torn bundle")
        tables = {}
        for n, (rows, dim) in schema.items():
            t = arrays[f"table:{n}"]
            if t.shape != (rows, dim):
                raise ValueError(
                    f"serving bundle {source}: table {n!r} is {t.shape}, "
                    f"manifest says {(rows, dim)} — refusing a torn bundle")
            tables[n] = t
        dense_params = _unflatten({
            k.removeprefix("dense:"): v
            for k, v in arrays.items() if k.startswith("dense:")
        })
    elif kind == "dense":
        params = _unflatten({
            k.removeprefix("params:"): v
            for k, v in arrays.items() if k.startswith("params:")
        })
        if not params:
            raise ValueError(f"serving bundle {source}: dense bundle holds no params")
    else:
        raise ValueError(f"serving bundle {source}: unknown kind {kind!r}")

    return ServingBundle(
        kind=kind,
        model=manifest["model"],
        embed_dim=int(manifest["embed_dim"]),
        cat_columns=tuple(manifest["cat_columns"]),
        cont_columns=tuple(manifest["cont_columns"]),
        size_map={k: int(v) for k, v in manifest["size_map"].items()},
        step=int(manifest["step"]),
        dtype=dtype_name,
        tables=tables,
        dense_params=dense_params,
        params=params,
        seq=({k: int(v) for k, v in manifest["seq"].items()}
             if manifest.get("seq") else None),
        version=int(manifest.get("version", 0)),
        digest=str(manifest.get("digest", "")),
    )


# ----------------------------------------------------------- corpus store
# Retrieval corpora persist like bundles: one npz + a stamped manifest.  A
# 100M-item int8 corpus is the artifact worth shipping (the f32 one it came
# from may never have fit a host), so the store keeps the STORED dtype —
# codes + the per-row (scale, offset) sidecar — and load_corpus re-shards
# for whatever mesh is serving, which need not match the exporting mesh.

_CORPUS_MANIFEST = "corpus.json"
_CORPUS_ARRAYS = "corpus.npz"


def export_corpus(out_dir: str | Path, corpus, *, step: int = 0) -> Path:
    """Write a retrieval corpus directory and return its path.

    Stores the UNPADDED rows at their stored dtype (int8 corpora ship codes
    plus the ``qscale`` sidecar; bf16 ships as uint16 bit patterns, the
    :func:`_store` idiom).  The manifest stamps ``bundle_version``, the
    storage ``dtype``, and — for int8 — the ``qscale_layout`` string, so
    :func:`load_corpus` refuses drift in BOTH directions (a corpus from a
    future re-grid, or an int8 corpus predating the stamp)."""
    n = corpus.n_items
    vectors = np.asarray(jax.device_get(corpus.vectors))[:n]
    ids = np.asarray(jax.device_get(corpus.ids))[:n]
    dtype_name = jnp.dtype(vectors.dtype).name
    if dtype_name not in STORAGE_DTYPES:
        raise ValueError(
            f"corpus dtype {dtype_name!r} not in {STORAGE_DTYPES}")
    arrays: dict[str, np.ndarray] = {
        "vectors": (vectors.view(np.uint16)
                    if dtype_name == "bfloat16" else vectors),
        "ids": np.asarray(ids, np.int32),
    }
    manifest: dict[str, Any] = {
        "bundle_version": BUNDLE_VERSION,
        "kind": "corpus",
        "dtype": dtype_name,
        "n_items": int(n),
        "dim": int(vectors.shape[1]),
        "step": int(step),
    }
    if dtype_name == "int8":
        if corpus.qscale is None:
            raise ValueError(
                "int8 corpus has no qscale sidecar — it cannot be "
                "dequantized; refusing to export garbage")
        arrays["qscale"] = np.asarray(
            jax.device_get(corpus.qscale), np.float32)[:n]
        manifest["qscale_layout"] = QSCALE_LAYOUT
    elif corpus.qscale is not None:
        raise ValueError(
            f"{dtype_name} corpus carries a qscale sidecar — only int8 "
            "rows are scaled; refusing an inconsistent corpus")
    manifest["digest"] = bundle_digest(manifest, arrays)
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.savez(out / _CORPUS_ARRAYS, **arrays)
    (out / _CORPUS_MANIFEST).write_text(
        json.dumps(manifest, indent=1, sort_keys=True))
    return out


def load_corpus(corpus_dir: str | Path, *, mesh=None, axis: str = "data"):
    """Load a stored corpus and re-shard it for ``mesh`` -> ``Corpus``.

    Refusal cases (each a ``ValueError`` naming the cause, the
    :func:`load_bundle` stance): missing manifest, ``bundle_version``
    mismatch, unknown dtype, content-digest mismatch, shape drift, an int8
    corpus whose ``qscale_layout`` is missing (pre-stamp export) or not the
    one this build reads (future re-grid), a missing sidecar array, and a
    float corpus that carries one.  Padding re-derives from the TARGET mesh
    (zero rows, ids -1, int8 padding re-quantized so a same-mesh round trip
    is bitwise)."""
    from tdfo_tpu.serve.corpus import Corpus  # circular at module scope

    cdir = Path(corpus_dir)
    mpath = cdir / _CORPUS_MANIFEST
    if not mpath.exists():
        raise ValueError(f"{cdir} is not a corpus store (no {_CORPUS_MANIFEST})")
    manifest = json.loads(mpath.read_text())
    found = manifest.get("bundle_version")
    if found != BUNDLE_VERSION:
        raise ValueError(
            f"corpus store {cdir} has bundle_version {found!r}, this build "
            f"serves {BUNDLE_VERSION} — re-export the corpus.")
    if manifest.get("kind") != "corpus":
        raise ValueError(
            f"{cdir} is a {manifest.get('kind')!r} bundle, not a corpus")
    dtype_name = manifest["dtype"]
    if dtype_name not in STORAGE_DTYPES:
        raise ValueError(
            f"corpus store {cdir}: unknown dtype {dtype_name!r} (this build "
            f"reads {STORAGE_DTYPES})")
    with np.load(cdir / _CORPUS_ARRAYS) as z:
        arrays = {k: z[k] for k in z.files}
    got = bundle_digest(manifest, arrays)
    if got != manifest.get("digest"):
        raise ValueError(
            f"corpus store {cdir}: content digest {got} != manifest "
            f"{manifest.get('digest')!r} — refusing a corrupt corpus")

    n = int(manifest["n_items"])
    dim = int(manifest["dim"])
    vectors = arrays["vectors"]
    if dtype_name == "bfloat16":
        vectors = vectors.view(jnp.bfloat16)
    if vectors.shape != (n, dim):
        raise ValueError(
            f"corpus store {cdir}: vectors are {vectors.shape}, manifest "
            f"says {(n, dim)} — refusing a torn corpus")
    qscale = None
    if dtype_name == "int8":
        layout = manifest.get("qscale_layout")
        if layout != QSCALE_LAYOUT:
            raise ValueError(
                f"corpus store {cdir}: int8 qscale_layout {layout!r}, this "
                f"build reads {QSCALE_LAYOUT!r} — the sidecar grids are not "
                "value-compatible; re-export the corpus.")
        if "qscale" not in arrays:
            raise ValueError(
                f"corpus store {cdir}: int8 corpus is missing the qscale "
                "sidecar — refusing a torn corpus")
        qscale = arrays["qscale"]
        if qscale.shape != (n, 2):
            raise ValueError(
                f"corpus store {cdir}: qscale is {qscale.shape}, expected "
                f"{(n, 2)} — refusing a torn corpus")
    elif "qscale" in arrays or "qscale_layout" in manifest:
        raise ValueError(
            f"corpus store {cdir}: {dtype_name} corpus carries a qscale "
            "sidecar — only int8 rows are scaled; refusing an "
            "inconsistent corpus")

    from jax.sharding import NamedSharding, PartitionSpec as P

    n_shards = mesh.shape[axis] if mesh is not None else 1
    pad = -(-n // n_shards) * n_shards - n
    vecs = jnp.asarray(vectors)
    ids = jnp.asarray(arrays["ids"], jnp.int32)
    if pad:
        if qscale is not None:
            from tdfo_tpu.ops.quant import quantize_rows

            zv, zq = quantize_rows(jnp.zeros((pad, dim), jnp.float32))
            vecs = jnp.concatenate([vecs, zv])
            qscale = jnp.concatenate([jnp.asarray(qscale, jnp.float32), zq])
        else:
            vecs = jnp.pad(vecs, [(0, pad), (0, 0)])
        ids = jnp.pad(ids, [(0, pad)], constant_values=-1)
    if qscale is not None:
        qscale = jnp.asarray(qscale, jnp.float32)
    if mesh is not None:
        vecs = jax.device_put(vecs, NamedSharding(mesh, P(axis, None)))
        ids = jax.device_put(ids, NamedSharding(mesh, P(axis)))
        if qscale is not None:
            qscale = jax.device_put(
                qscale, NamedSharding(mesh, P(axis, None)))
    return Corpus(vectors=vecs, ids=ids, n_items=n, qscale=qscale)
