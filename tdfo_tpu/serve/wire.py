"""Length-prefixed JSON wire protocol for the out-of-process serving fleet.

The fleet's process boundary (torchrec inference runs its predictors as real
server processes; Monolith §3.3 syncs parameters INTO a serving fleet, not a
Python object graph) needs a wire format.  This module is the ONLY place in
``tdfo_tpu/`` allowed to open sockets (enforced by a ``tests/test_quality.py``
AST rule; ``serve/supervisor.py`` holds the matching ``subprocess`` monopoly):
everything above it — ingress, supervisor, replica main — speaks in framed
messages and never touches a file descriptor directly.

Frame format: a 4-byte big-endian unsigned length followed by that many bytes
of UTF-8 JSON.  The length is validated against ``max_frame`` BEFORE the body
is read, on both send and receive — the bound on memory a malformed or
hostile peer can demand (``[serving] max_frame_bytes``).  EOF at a frame
boundary is a clean :class:`Disconnect`; EOF mid-frame is a torn frame and
raises :class:`WireError` — the distinction the ingress uses to tell a
drained peer from a SIGKILLed one.

Message types are dict conventions, not classes (the payload is JSON either
way): ``{"type": "score", "rid": ..., "feats": ...}`` answered by
``{"type": "reply", "rid": ..., ...}``; plus ``sync`` / ``heartbeat`` /
``probe`` / ``drain`` / ``shutdown``.  Feature batches ride the
:func:`encode_feats`/:func:`decode_feats` codec — dtype + shape + nested
lists, exact for int32/float32 (binary64 JSON carries f32 losslessly), so
probe logits across the wire stay bitwise comparable.

Connect retries route through ``utils/retry.retry_call`` — the single
``backoff_delay`` law — because the respawn window (supervisor restarting a
SIGKILLed replica) is exactly when connects fail transiently.
"""

from __future__ import annotations

import json
import os
import random
import socket
import struct
import time
from pathlib import Path
from typing import Any, Callable, Mapping

import numpy as np

from tdfo_tpu.utils.retry import retry_call

__all__ = [
    "MAX_FRAME_BYTES", "WireError", "FrameTooLarge", "Disconnect",
    "send_msg", "recv_msg", "encode_feats", "decode_feats",
    "listen", "connect",
]

# default frame cap; [serving] max_frame_bytes overrides per fleet
MAX_FRAME_BYTES = 8 << 20

_HEADER = struct.Struct(">I")


class WireError(RuntimeError):
    """Protocol violation: torn frame, undecodable payload."""


class FrameTooLarge(WireError):
    """Declared frame length exceeds the cap — refused before the body is
    read.  The connection is poisoned (the body bytes are still in flight);
    callers must close it."""


class Disconnect(WireError):
    """Clean EOF at a frame boundary — the peer closed deliberately (drain,
    shutdown) or died between messages.  NOT raised mid-frame."""


def send_msg(sock: socket.socket, obj: Mapping[str, Any], *,
             max_frame: int = MAX_FRAME_BYTES) -> None:
    """Serialize ``obj`` and send it as one length-prefixed frame."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > max_frame:
        raise FrameTooLarge(
            f"refusing to send a {len(payload)}-byte frame (max_frame = "
            f"{max_frame}); shrink the batch or raise "
            "[serving] max_frame_bytes")
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int, *, at_boundary: bool) -> bytes:
    """Read exactly ``n`` bytes.  EOF with zero bytes read at a frame
    boundary is a :class:`Disconnect`; any other short read is a torn
    frame."""
    chunks: list[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if at_boundary and got == 0:
                raise Disconnect("peer closed the connection")
            raise WireError(
                f"torn frame: EOF after {got} of {n} expected bytes "
                f"({'header' if at_boundary else 'body'})")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, *,
             max_frame: int = MAX_FRAME_BYTES) -> dict[str, Any]:
    """Receive one frame and decode it.  Raises :class:`FrameTooLarge` from
    the DECLARED length, before any body byte is read."""
    header = _recv_exact(sock, _HEADER.size, at_boundary=True)
    (length,) = _HEADER.unpack(header)
    if length > max_frame:
        raise FrameTooLarge(
            f"peer declared a {length}-byte frame (max_frame = {max_frame}); "
            "refusing before reading the body")
    body = _recv_exact(sock, length, at_boundary=False)
    try:
        obj = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"undecodable frame payload: {e}") from e
    if not isinstance(obj, dict):
        raise WireError(f"frame payload must be a JSON object, got "
                        f"{type(obj).__name__}")
    return obj


def encode_feats(batch: Mapping[str, np.ndarray]) -> dict[str, Any]:
    """Feature batch -> JSON-safe codec.  int32 is exact; float32 round-trips
    bitwise through JSON's binary64 (f32 ⊂ f64), which keeps cross-process
    probe logits bitwise comparable to in-process scoring."""
    out: dict[str, Any] = {}
    for name, v in batch.items():
        arr = np.asarray(v)
        out[name] = {"dtype": arr.dtype.name, "shape": list(arr.shape),
                     "data": arr.ravel().tolist()}
    return out


def decode_feats(enc: Mapping[str, Any]) -> dict[str, np.ndarray]:
    """Inverse of :func:`encode_feats`."""
    out: dict[str, np.ndarray] = {}
    for name, spec in enc.items():
        arr = np.asarray(spec["data"], dtype=np.dtype(spec["dtype"]))
        out[name] = arr.reshape(spec["shape"])
    return out


def listen(path: str | Path, *, backlog: int = 16) -> socket.socket:
    """Bind an ``AF_UNIX`` listener at ``path`` (stale socket files from a
    SIGKILLed predecessor are unlinked — the respawn case)."""
    path = Path(path)
    if path.exists():
        path.unlink()
    path.parent.mkdir(parents=True, exist_ok=True)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.bind(str(path))
    sock.listen(backlog)
    return sock


def listener_from_fd(fd: int) -> socket.socket:
    """Adopt an inherited, already-listening ``AF_UNIX`` socket (the
    socket-activation handoff: the supervisor binds BEFORE spawning and
    passes the fd, so the ingress can connect the instant the child
    exists — a cold interpreter importing jax for a minute never widens
    the connect window)."""
    return socket.socket(socket.AF_UNIX, socket.SOCK_STREAM, fileno=fd)


def _dial(path: str) -> socket.socket:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.connect(path)
    except OSError:
        sock.close()
        raise
    return sock


def connect(path: str | Path, *,
            attempts: int = 5,
            base_ms: float = 10.0,
            max_ms: float = 2000.0,
            sleep: Callable[[float], None] = time.sleep,
            rng: random.Random | None = None) -> socket.socket:
    """Connect to a replica's listener, retrying through ``retry_call`` (the
    repo's one backoff law) — a freshly respawned replica needs a beat to
    bind, and that window is exactly what the schedule covers.
    ``sleep``/``rng`` are injectable so tests pin the schedule."""
    return retry_call(
        _dial, str(path),
        description=f"wire.connect:{os.path.basename(str(path))}",
        attempts=attempts,
        base_delay=base_ms / 1000.0,
        max_delay=max_ms / 1000.0,
        retry_on=(OSError,),
        sleep=sleep,
        rng=rng,
    )
