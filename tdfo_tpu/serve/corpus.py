"""Candidate corpus build: batched item-tower sweep over the full catalog.

Materialises the ``[N_items, D]`` corpus the retrieval layer searches —
the offline half of the ScaNN-style retrieval split (Guo et al. 2020): item
vectors are precomputed in bulk, only the user tower runs per request.  The
sweep reuses the scorer's jitted item tower (``serve/scoring.py``), i.e. the
``ShardedEmbeddingCollection`` lookup path — plain full-row gathers, ZERO
scatters (CLAUDE.md: scatters are ~170 ns/row on v5e and have no place in
any serving program).  One compiled program (fixed ``corpus_batch`` chunk
shape, last chunk padded) serves the whole sweep.

The finished corpus is sharded over the mesh DATA axis — retrieval is
corpus-sharded, every device scores all queries against its slice — with
zero-row padding (ids = -1) up to a shard multiple so uneven catalogs
(``N % devices != 0``) shard cleanly; retrieval masks padded rows to -inf.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.core.mesh import DATA_AXIS
from tdfo_tpu.models.twotower import (
    TWOTOWER_CONTINUOUS,
    TWOTOWER_ITEM_CATEGORICAL,
    _FEATURE_TO_INPUT,
)
from tdfo_tpu.ops.quant import STORAGE_DTYPES, quantize_rows
from tdfo_tpu.serve.scoring import Scorer

__all__ = ["Corpus", "build_corpus", "synthetic_item_features"]

# item-side input columns of the TwoTower catalog, id column first
ITEM_COLUMNS = tuple(_FEATURE_TO_INPUT[f] for f in TWOTOWER_ITEM_CATEGORICAL)


@dataclass(frozen=True)
class Corpus:
    """Sharded candidate corpus: ``vectors[i]`` scores item ``ids[i]``;
    rows with ``ids[i] == -1`` are shard-alignment padding (masked to -inf
    by retrieval, never returned).  ``qscale`` is the per-row f32
    ``(scale, offset)`` sidecar of an int8 corpus (``ops/quant.py`` grid):
    the stored row dequantizes as ``row * scale + offset``; ``None`` for
    float corpora."""

    vectors: jax.Array  # [N_pad, D], sharded P(data, None) under a mesh
    ids: jax.Array  # [N_pad] int32, sharded P(data); -1 = padding
    n_items: int  # real rows (N_pad >= n_items)
    qscale: jax.Array | None = None  # [N_pad, 2] f32 when vectors are int8


def synthetic_item_features(
    size_map: Mapping[str, int], n_items: int, seed: int = 0
) -> dict[str, np.ndarray]:
    """Deterministic per-item catalog features for demos/tests: categorical
    attributes drawn within each vocab, continuous in [0, 1).  Real
    deployments replace this with the item-attribute catalog the CTR ETL
    joins on (``jax-flax/preprocessing.py`` book metadata)."""
    rng = np.random.default_rng(seed)
    feats: dict[str, np.ndarray] = {
        "item_id": np.arange(n_items, dtype=np.int32)}
    for feat in TWOTOWER_ITEM_CATEGORICAL[1:]:  # skip the id column itself
        col = _FEATURE_TO_INPUT[feat]
        feats[col] = rng.integers(
            0, int(size_map[feat]), size=n_items, dtype=np.int32)
    for col in TWOTOWER_CONTINUOUS:
        feats[col] = rng.random(n_items, dtype=np.float32)
    return feats


def build_corpus(
    scorer: Scorer,
    item_features: Mapping[str, np.ndarray],
    *,
    corpus_batch: int = 8192,
    mesh=None,
    axis: str = DATA_AXIS,
    dtype: str = "float32",
) -> Corpus:
    """Sweep the item tower over ``item_features`` -> :class:`Corpus`.

    ``item_features`` maps every item-side input column (``item_id``,
    attribute columns, continuous columns) to an aligned ``[N]`` array;
    ``item_id`` defaults to ``arange(N)``.  Chunks of ``corpus_batch`` rows
    keep the sweep at ONE compiled program; the last chunk zero-pads (valid
    ids, rows sliced off after) rather than compiling a ragged tail shape.

    ``dtype`` picks the storage format: ``"float32"`` (exact), ``"bfloat16"``
    (half the HBM, score-identical — :func:`mips_scores` casts operands to
    bf16 anyway), or ``"int8"`` (quarter the HBM plus a [N_pad, 2] f32
    per-row (scale, offset) sidecar; keyless round-to-nearest on the
    ``ops/quant.py`` grid, searched by the two-stage coarse scan).
    """
    if dtype not in STORAGE_DTYPES:
        raise ValueError(
            f"corpus dtype {dtype!r} not in {STORAGE_DTYPES}")
    feats = {k: np.asarray(v) for k, v in item_features.items()}
    n_items = len(next(iter(feats.values())))
    feats.setdefault("item_id", np.arange(n_items, dtype=np.int32))
    for k, v in feats.items():
        if len(v) != n_items:
            raise ValueError(
                f"item_features column {k!r} has {len(v)} rows, expected "
                f"{n_items} (all columns must align)")
    missing = [c for c in (*ITEM_COLUMNS, *scorer.cont_columns)
               if c not in feats]
    if missing:
        raise ValueError(f"item_features missing columns {missing}")

    chunks = []
    for start in range(0, n_items, corpus_batch):
        stop = min(start + corpus_batch, n_items)
        pad = corpus_batch - (stop - start)
        batch = {
            k: jnp.asarray(np.pad(v[start:stop], [(0, pad)]))
            for k, v in feats.items()
        }
        vecs = scorer.item_embed(batch)
        chunks.append(vecs[:stop - start] if pad else vecs)
    vectors = jnp.concatenate(chunks, axis=0).astype(jnp.float32)
    ids = jnp.arange(n_items, dtype=jnp.int32)

    n_shards = mesh.shape[axis] if mesh is not None else 1
    n_pad = -(-n_items // n_shards) * n_shards - n_items
    if n_pad:
        vectors = jnp.pad(vectors, [(0, n_pad), (0, 0)])
        ids = jnp.pad(ids, [(0, n_pad)], constant_values=-1)
    qscale = None
    if dtype == "bfloat16":
        vectors = vectors.astype(jnp.bfloat16)
    elif dtype == "int8":
        vectors, qscale = quantize_rows(vectors)
    if mesh is not None:
        vectors = jax.device_put(
            vectors, NamedSharding(mesh, P(axis, None)))
        ids = jax.device_put(ids, NamedSharding(mesh, P(axis)))
        if qscale is not None:
            qscale = jax.device_put(
                qscale, NamedSharding(mesh, P(axis, None)))
    return Corpus(vectors=vectors, ids=ids, n_items=n_items, qscale=qscale)
