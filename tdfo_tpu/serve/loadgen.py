"""Load generation against the process fleet: zipf traffic, knee curves.

The harness the ``[loadgen]`` table configures (``python -m tdfo_tpu.launch
loadgen``).  It drives the socket ingress with synthetic requests whose ids
follow a **zipf** popularity law (``zipf_a``) — recommendation traffic is
head-heavy, and a uniform trace would understate batcher cache locality and
overstate shed rates — under one of two arrival disciplines:

* ``mode = "closed"``: a fixed number of outstanding requests
  (``concurrency``); a reply immediately funds the next request.  Measures
  the fleet's capacity at a given parallelism — throughput saturates, and
  latency IS the feedback loop.
* ``mode = "open"``: Poisson-free fixed-rate arrivals (``rate_qps``);
  requests are submitted on schedule whether or not replies came back.
  Measures behaviour PAST saturation — queues grow, deadlines expire,
  admission control sheds — which a closed loop structurally cannot show
  (coordinated omission).

:meth:`LoadGenerator.knee` sweeps the load axis (doubling concurrency in
closed mode, doubling rate in open mode) and records one
``loadgen_step`` span per step; the latency/throughput knee — the last
step whose p99 still meets ``p99_slo_ms`` — then falls out of the
existing trace assembler (``obs/aggregate.assemble`` folds
``ingress_request`` and ``loadgen_step`` spans) rather than a bespoke
report path.

Clock discipline: wall time comes from ``_trace.clock()`` stamps measured
with the injectable ``elapsed_ms`` helper — never a raw clock difference —
and pacing sleeps go through an injectable ``sleep``, so the unit tests
drive a whole sweep without waiting wall-clock.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Callable, Mapping

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.obs.aggregate import percentile

__all__ = ["LoadGenerator", "loadgen_from_config", "serve_fleet_from_config"]

_POLL_S = 0.02  # ingress poll granularity between submissions


class LoadGenerator:
    """Drive an :class:`~tdfo_tpu.serve.ingress.Ingress` (or any duck-typed
    ``submit``/``poll``/``inflight``/``completed`` surface) with zipf
    traffic."""

    def __init__(self, ingress, spec, vocab: Mapping[str, int],
                 cont_cols=(), *,
                 elapsed_ms: Callable[[float], float] | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._ingress = ingress
        self.spec = spec
        self._vocab = dict(vocab)
        self._cont_cols = tuple(cont_cols)
        self._rng = np.random.default_rng(spec.seed)
        self._elapsed_ms = elapsed_ms or _trace.elapsed_ms
        self._sleep = sleep
        self._serial = 0

    def request(self) -> tuple[str, dict[str, np.ndarray]]:
        """One synthetic request: zipf-popular ids (rank r with probability
        ~ r^-a, folded into the vocab), uniform floats for the continuous
        columns."""
        i = self._serial
        self._serial += 1
        n = int(self.spec.rows_per_request)
        batch: dict[str, np.ndarray] = {}
        for c, v in self._vocab.items():
            ranks = self._rng.zipf(self.spec.zipf_a, size=n)
            batch[c] = ((ranks - 1) % max(int(v), 1)).astype(np.int32)
        for c in self._cont_cols:
            batch[c] = self._rng.random(n, dtype=np.float32)
        return f"lg{i}", batch

    # ----------------------------------------------------------- one run

    def run(self, *, requests: int | None = None,
            concurrency: int | None = None,
            rate_qps: float | None = None) -> dict[str, Any]:
        """Run one load step and return its stats record (also emitted as
        a ``loadgen_step`` span).  ``requests``/``concurrency``/``rate_qps``
        override the spec for knee sweeps."""
        spec = self.spec
        total = int(requests if requests is not None else spec.requests)
        conc = int(concurrency if concurrency is not None else
                   spec.concurrency)
        rate = float(rate_qps if rate_qps is not None else spec.rate_qps)
        ing = self._ingress
        lat0 = len(ing.latencies_ms)
        shed0, fail0, done0 = ing.sheds, ing.failures, len(ing.completed)
        submitted = 0
        t0 = _trace.clock()

        def done() -> int:
            return len(ing.completed) - done0

        if spec.mode == "closed":
            while done() < total:
                while submitted < total and ing.inflight() < conc:
                    rid, batch = self.request()
                    ing.submit(rid, batch)
                    submitted += 1
                ing.poll(_POLL_S if ing.inflight() else 0.0)
                if not ing.inflight() and submitted >= total \
                        and done() < total:
                    break  # every remaining request died with a connection
        else:  # open loop: fixed-rate arrivals, replies never gate sends
            while submitted < total or (ing.inflight() and done() < total):
                if submitted < total:
                    target_ms = submitted * 1000.0 / rate
                    ahead_ms = target_ms - self._elapsed_ms(t0)
                    if ahead_ms <= 0.0:
                        rid, batch = self.request()
                        ing.submit(rid, batch)
                        submitted += 1
                        continue
                    wait_s = min(ahead_ms / 1000.0, _POLL_S)
                else:
                    wait_s = _POLL_S
                ing.poll(wait_s)

        wall_s = self._elapsed_ms(t0) / 1000.0
        lat = list(ing.latencies_ms[lat0:])
        n_done = done()
        stats = {
            "mode": spec.mode,
            "offered": total,
            "concurrency": conc if spec.mode == "closed" else None,
            "offered_qps": rate if spec.mode == "open" else None,
            "completed": n_done,
            "achieved_qps": (n_done / wall_s) if wall_s > 0 else 0.0,
            "p50_ms": percentile(lat, 50),
            "p99_ms": percentile(lat, 99),
            "shed": ing.sheds - shed0,
            "failed": ing.failures - fail0,
            "p99_slo_ms": spec.p99_slo_ms,
            "slo_ok": bool(lat) and percentile(lat, 99) <= spec.p99_slo_ms,
        }
        _trace.emit("loadgen", "loadgen_step", **stats)
        return stats

    # -------------------------------------------------------------- knee

    def knee(self, *, steps: int = 4) -> dict[str, Any]:
        """Sweep the load axis doubling per step and locate the
        latency/throughput knee: the last step whose p99 still met
        ``p99_slo_ms``.  Closed mode doubles concurrency from 1; open mode
        doubles the rate from ``rate_qps / 2**(steps-1)`` up to
        ``rate_qps``."""
        spec = self.spec
        records = []
        for s in range(int(steps)):
            if spec.mode == "closed":
                rec = self.run(concurrency=2 ** s)
            else:
                rec = self.run(
                    rate_qps=spec.rate_qps / float(2 ** (steps - 1 - s)))
            records.append(rec)
        knee = None
        for rec in records:
            if rec["slo_ok"]:
                knee = rec
        return {"steps": records, "knee": knee}


def _build_process_fleet(config, log_dir):
    """Shared ``serve-fleet``/``loadgen`` preamble: export a bundle
    (restoring the newest checkpoint when one exists), ingest it into a
    :class:`~tdfo_tpu.serve.swap.BundleStore`, and spawn a
    :class:`~tdfo_tpu.serve.supervisor.ProcessFleet` of
    ``[serving] replicas`` real processes following it."""
    from tdfo_tpu.serve.export import export_bundle
    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.serve.supervisor import ProcessFleet
    from tdfo_tpu.serve.swap import BundleStore
    from tdfo_tpu.train.trainer import Trainer, _ctr_columns

    if config.model not in ("twotower", "dlrm"):
        raise ValueError(
            f"the process fleet serves the CTR family (twotower/dlrm), not "
            f"{config.model!r}")
    trainer = Trainer(config, log_dir=log_dir)
    state, step = trainer.state, 0
    if trainer._ckpt is not None and trainer._ckpt.latest_step() is not None:
        step, state, _ = trainer._ckpt.restore(
            trainer.state, stamps=trainer._ckpt_stamps)
    cat_cols, cont_cols = _ctr_columns(config)
    base = Path(log_dir or config.checkpoint_dir or ".")
    out_dir = base / "serving_bundle"
    kwargs: dict[str, Any] = (
        dict(coll=trainer.coll, tables=state.tables,
             dense_params=state.dense_params)
        if hasattr(state, "tables") else dict(params=state.params))
    export_bundle(
        out_dir, model=config.model, embed_dim=config.embed_dim,
        cat_columns=cat_cols, cont_columns=cont_cols,
        size_map=config.size_map, step=step,
        mixed_precision=config.mixed_precision, **kwargs)

    store = BundleStore(base / "bundle_store")
    if store.recover() is None:
        store.ingest_full(out_dir)
    fleet = ProcessFleet(
        store, config, workdir=base, logger=trainer.logger,
        request_log_root=(base / "request_log"
                          if config.serving.log_features else None))
    return trainer, fleet, _column_vocab(config, cat_cols), cont_cols, \
        step, out_dir


def loadgen_from_config(config, *, log_dir: str | Path | None = None,
                        knee_steps: int = 4) -> dict[str, Any]:
    """The ``python -m tdfo_tpu.launch loadgen`` body: stand the process
    fleet up and sweep the ``[loadgen]`` traffic through the socket
    ingress.  Returns the knee report."""
    trainer, fleet, vocab, cont_cols, step, out_dir = \
        _build_process_fleet(config, log_dir)
    try:
        fleet.sync()
        gen = LoadGenerator(fleet.ingress, config.loadgen, vocab, cont_cols)
        report = gen.knee(steps=knee_steps)
    finally:
        fleet.close()
        trainer.logger.close()
    report["replicas"] = int(config.serving.replicas)
    report["bundle"] = str(out_dir)
    report["step"] = int(step)
    return report


def serve_fleet_from_config(config, *, log_dir: str | Path | None = None,
                            n_requests: int = 64) -> dict[str, Any]:
    """The ``python -m tdfo_tpu.launch serve-fleet`` body: the process twin
    of ``serve`` with ``[serving] replicas > 1`` — same synthetic ragged
    trace, but routed through the P2C ingress to real replica processes.
    Returns the latency/throughput stats dict (printed by ``launch``)."""
    trainer, fleet, vocab, cont_cols, step, out_dir = \
        _build_process_fleet(config, log_dir)
    spec = config.serving
    rng = np.random.default_rng(config.seed)
    label_rng = np.random.default_rng(config.seed + 1)
    hi = min(spec.max_batch, spec.buckets[0])
    requests = []
    for i in range(n_requests):
        n = int(rng.integers(1, hi + 1))
        batch: dict[str, np.ndarray] = {
            c: rng.integers(0, v, size=n, dtype=np.int32)
            for c, v in vocab.items()}
        for c in cont_cols:
            batch[c] = rng.random(n, dtype=np.float32)
        if spec.log_features:
            batch["label"] = label_rng.integers(0, 2, size=n, dtype=np.int8)
        requests.append((f"req{i}", batch))
    try:
        fleet.sync()
        t0 = _trace.clock()
        results = fleet.run(requests)
        wall_s = _trace.elapsed_ms(t0) / 1000.0
        lat = list(fleet.ingress.latencies_ms)
        stats = {
            "requests": len(results),
            "answered": sum(1 for v in results.values() if v is not None),
            "p50_ms": percentile(lat, 50),
            "p99_ms": percentile(lat, 99),
            "shed": fleet.ingress.sheds,
            "failed": fleet.ingress.failures,
            "replicas": len(fleet.alive_ids()),
            "version": int(fleet.store.current_version() or 0),
            "qps": (len(results) / wall_s) if wall_s > 0 else float("inf"),
        }
        if spec.log_features:
            stats["request_log"] = str(
                Path(log_dir or config.checkpoint_dir or ".") / "request_log")
    finally:
        fleet.close()
        trainer.logger.close()
    stats["bundle"] = str(out_dir)
    stats["step"] = int(step)
    return stats
