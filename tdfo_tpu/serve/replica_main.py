"""Replica process entry: ``python -m tdfo_tpu.serve.replica_main spec.json``.

One :class:`~tdfo_tpu.serve.fleet.ReplicaFrontend` behind an ``AF_UNIX``
listener, speaking the ``serve/wire.py`` framed protocol.  The supervisor
(``serve/supervisor.py``) writes the spec file and spawns this module; the
ingress connects and drives it.  The process IS the replica: ``kill -9``
takes the scorer, the batcher, and the connection down with it, and the
respawned lineage proves the robustness bar — it re-reads the SAME spec,
re-follows ``CURRENT``/``CANARY`` by (version, digest) through the shared
:class:`~tdfo_tpu.serve.swap.BundleStore` (a pointer FOLLOWER — ``recover``
belongs to the one writer, the online supervisor), and reopens the SAME
``replica-<k>`` request-log directory, whose writer resumes seq-contiguously
by construction (``data/replay.RequestLog`` scans seals + active segment on
open).

Startup: the supervisor binds the listener BEFORE spawning and passes it
down as ``--listen-fd`` (socket activation), because ``python -m``
resolves the package — jax included — before ``main`` runs: on a loaded
single-core box that import takes minutes, far past any sane
connect-retry budget.  With the fd handoff the ingress's connect lands
in the kernel backlog at spawn time and the first RPC simply blocks
until the replica has imported, synced, and called ``accept``.  Run
manually (no ``--listen-fd``), the child binds for itself and the
ingress's connect-retry schedule (``[serving] connect_retries`` x
``connect_base_ms`` through the single ``utils/retry.backoff_delay``
law) covers the import window instead.

Spec keys: ``replica_id``, ``socket`` (listener path), ``store_dir``,
``serving`` (a ``[serving]`` dict), ``canary_member``, ``request_log_root``
(optional), ``trace_dir`` (optional — spans append to the SHARED sinks;
``obs/trace.emit`` writes one complete line per record so concurrent
multi-process appends never tear), ``slow_score_ms`` (the only fault a
replica child honours — kill faults belong to the parent), and
``jax_platforms`` (default ``"cpu"``: replica children must never contend
for the single tunnelled TPU — CLAUDE.md, one TPU job at a time).
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Any


def _serve(spec: dict[str, Any], listener) -> None:
    import select

    import numpy as np

    from tdfo_tpu.core.config import ServingSpec
    from tdfo_tpu.obs import trace as _trace
    from tdfo_tpu.serve import wire
    from tdfo_tpu.serve.fleet import ReplicaFrontend
    from tdfo_tpu.serve.swap import BundleStore
    from tdfo_tpu.train.metrics import binary_auc
    from tdfo_tpu.utils import faults as _faults

    if spec.get("trace_dir"):
        _trace.configure(spec["trace_dir"])
    slow_ms = float(spec.get("slow_score_ms") or 0.0)
    if slow_ms:
        _faults.configure(_faults.FaultSpec(slow_score_ms=slow_ms))

    serving_raw = dict(spec["serving"])
    serving_raw["buckets"] = tuple(serving_raw["buckets"])
    serving = ServingSpec(**serving_raw)
    max_frame = serving.max_frame_bytes
    replica_id = int(spec["replica_id"])

    store = BundleStore(spec["store_dir"])  # follower: no recover()
    replica = ReplicaFrontend(
        replica_id, store, serving, mesh=None,
        request_log_root=spec.get("request_log_root"),
        canary_member=bool(spec.get("canary_member", False)))

    warmed: set[Any] = set()
    poll_s = max(serving.batch_deadline_ms / 1000.0, 0.001)

    def flush_replies(conn, pending: set) -> None:
        # every completed rid (scored or shed) answers exactly once, and
        # carries the batcher's queue state so score replies double as
        # balance observations at the ingress
        b = replica.batcher
        if b is None:
            return
        for rid in [r for r in list(b.results) if r in pending]:
            scores = b.results.pop(rid)
            pending.discard(rid)
            wire.send_msg(conn, {
                "type": "reply", "rid": rid,
                "scores": None if scores is None
                else np.asarray(scores, np.float32).ravel().tolist(),
                "queue_depth": b.last_queue_depth,
                "batch_fill": b.last_batch_fill,
            }, max_frame=max_frame)

    def handle(conn, msg: dict[str, Any], pending: set) -> bool:
        """Dispatch one message; False ends the process."""
        kind = msg.get("type")
        if kind == "score":
            replica.batcher.submit(msg["rid"], wire.decode_feats(msg["feats"]))
            pending.add(msg["rid"])
            replica.batcher.poll()
            flush_replies(conn, pending)
        elif kind == "sync":
            version = replica.sync(frozenset(msg.get("skew") or ()),
                                   frozenset(msg.get("slow") or ()))
            served = replica._served
            wire.send_msg(conn, {
                "type": "synced", "replica": replica_id, "version": version,
                "digest": None if served is None else served[1],
            }, max_frame=max_frame)
        elif kind == "heartbeat":
            feats = wire.decode_feats(msg["feats"])
            labels = np.asarray(msg["labels"])
            if replica._served not in warmed:
                # unmeasured warm-up, mirroring ServingFleet.heartbeat: jit
                # compilation is a one-time cost that would otherwise show
                # up as a per-cycle canary p99 regression
                warmed.add(replica._served)
                replica.score_direct({k: np.array(v)
                                      for k, v in feats.items()})
            t0 = _trace.clock()
            scores = replica.score_direct({k: np.array(v)
                                           for k, v in feats.items()})
            ms = _trace.elapsed_ms(t0)
            rec: dict[str, Any] = {
                "type": "heartbeat_reply", "replica": replica_id,
                "version": replica.version(),
                "auc": float(binary_auc(labels, scores)), "ms": ms,
                "canary": replica.canary_member,
            }
            if replica.batcher is not None:
                rec["queue_depth"] = replica.batcher.last_queue_depth
                rec["batch_fill"] = replica.batcher.last_batch_fill
            wire.send_msg(conn, rec, max_frame=max_frame)
        elif kind == "probe":
            trace = [(rid, wire.decode_feats(enc))
                     for rid, enc in msg["requests"]]
            results = replica.batcher.run(trace)
            pending.difference_update(results)
            wire.send_msg(conn, {
                "type": "probed", "replica": replica_id,
                "results": {str(rid): None if v is None
                            else np.asarray(v, np.float32).ravel().tolist()
                            for rid, v in results.items()},
            }, max_frame=max_frame)
        elif kind == "drain":
            if replica.batcher is not None:
                replica.batcher.drain()
            flush_replies(conn, pending)
            wire.send_msg(conn, {"type": "drained", "replica": replica_id},
                          max_frame=max_frame)
        elif kind == "shutdown":
            wire.send_msg(conn, {"type": "bye", "replica": replica_id},
                          max_frame=max_frame)
            return False
        else:
            raise wire.WireError(f"unknown message type {kind!r}")
        return True

    running = True
    while running:
        conn, _ = listener.accept()  # one ingress connection at a time
        pending: set = set()
        try:
            while True:
                readable, _, _ = select.select([conn], [], [], poll_s)
                if not readable:
                    # deadline tick: ship expired partial batches, answer
                    # their waiters
                    if replica.batcher is not None:
                        replica.batcher.poll()
                        flush_replies(conn, pending)
                    continue
                if not handle(conn, wire.recv_msg(conn, max_frame=max_frame),
                              pending):
                    running = False
                    break
        except wire.Disconnect:
            pass  # ingress went away; drop state, wait for a reconnect
        except wire.WireError as e:
            print(f"[replica {replica_id}] wire error: {e}", file=sys.stderr,
                  flush=True)
        finally:
            try:
                conn.close()
            except OSError:
                pass
    replica.close()


def main() -> None:
    spec = json.loads(Path(sys.argv[1]).read_text())
    # replica children never touch the tunnelled TPU: CPU unless the spec
    # explicitly says otherwise, set BEFORE any jax import — an assignment,
    # not setdefault, because a TPU parent's environment would otherwise
    # leak its platform into every child
    os.environ["JAX_PLATFORMS"] = str(spec.get("jax_platforms", "cpu"))

    from tdfo_tpu.serve import wire

    if "--listen-fd" in sys.argv:
        # socket activation: adopt the supervisor's pre-bound listener —
        # its backlog has been accepting connects since before this
        # interpreter existed
        fd = int(sys.argv[sys.argv.index("--listen-fd") + 1])
        listener = wire.listener_from_fd(fd)
    else:
        # manual run: bind here — the ingress can still connect (and
        # queue its first RPC in the backlog) while the scorer jits
        listener = wire.listen(spec["socket"])
    try:
        _serve(spec, listener)
    finally:
        listener.close()
        Path(spec["socket"]).unlink(missing_ok=True)


if __name__ == "__main__":
    main()
