"""Micro-batching request frontend + the ``launch.py serve`` entry point.

Online CTR traffic arrives as ragged little requests; TPU programs want a
few fixed shapes.  :class:`MicroBatcher` bridges the two the way production
serving stacks do (Monolith's serving tier, TF-Serving's batching layer):

  * requests queue until ``max_batch`` rows are pending (ship full) or the
    OLDEST request's ``batch_deadline_ms`` expires (ship partial — graceful
    degradation: latency bounds beat utilisation, a stalled queue is worse
    than a padded batch);
  * every shipped batch pads up to the smallest of the configured power-of-
    two ``buckets``, so the jit cache compiles AT MOST ``len(buckets)``
    programs no matter how ragged the trace
    (``tests/test_serve_frontend.py`` pins that count);
  * per-request latency lands in the metrics JSONL via the existing
    :class:`~tdfo_tpu.train.trainer.MetricLogger`, with a p50/p99 summary
    record at the end — the observability layer the reference lacks;
  * overload sheds instead of queueing unboundedly: with ``max_queue`` set,
    an arriving request first evicts pending requests already past the
    batch deadline (oldest first — they would miss their latency bound
    anyway), then either displaces the oldest survivor
    (``shed_policy="oldest"``) or bounces itself (``"reject"``); every shed
    lands in the JSONL with ``outcome="shed"``;
  * :meth:`MicroBatcher.swap` flips to a new scorer without dropping
    accepted traffic: in-flight requests drain on the OLD scorer, the flip
    itself is a host-side reference swap (atomic under the GIL), and the
    JSONL records swap latency plus per-request ``under_swap`` so
    p99-under-swap is measurable (torchrec inference model-update
    analogue; see ``tdfo_tpu/serve/swap.py`` for the on-disk half).

The clock is injectable so deadline behaviour is deterministic under test
(the fault-injection stance of ``utils/faults.py`` applied to time).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Callable, Mapping

import numpy as np

from tdfo_tpu.obs import trace as _trace

__all__ = ["MicroBatcher", "serve_from_config"]


class MicroBatcher:
    """Deadline/bucket batch assembly around one jitted ``score_fn``.

    ``score_fn(batch) -> [B] scores`` must accept any batch size in
    ``buckets`` (the scorer's jit retraces per shape — that is the whole
    compile-count contract).  Requests are dicts of aligned ``[n]`` columns;
    results come back unpadded, exactly ``n`` scores per request.
    """

    def __init__(
        self,
        score_fn: Callable,
        *,
        buckets: tuple[int, ...],
        max_batch: int,
        batch_deadline_ms: float,
        logger=None,
        clock: Callable[[], float] = time.monotonic,
        program_cache_size: Callable[[], int] | None = None,
        max_queue: int = 0,
        shed_policy: str = "oldest",
        watchdog=None,
        request_log=None,
    ):
        buckets = tuple(buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError("buckets must be non-empty, strictly increasing")
        if max_batch > buckets[-1]:
            raise ValueError(
                f"max_batch {max_batch} does not fit buckets[-1] {buckets[-1]}")
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0 (0 = unbounded), "
                             f"got {max_queue}")
        if shed_policy not in ("oldest", "reject"):
            raise ValueError(f"shed_policy must be 'oldest' or 'reject', "
                             f"got {shed_policy!r}")
        self._score = score_fn
        self._buckets = buckets
        self._max_batch = int(max_batch)
        self._deadline_s = float(batch_deadline_ms) / 1000.0
        self._logger = logger
        self._clock = clock
        # bounded-jit-cache invariant as a RUNTIME assertion (not just the
        # test pin): when the scorer exposes its compiled-program count,
        # every ship verifies it stays <= len(buckets)
        self._cache_size = program_cache_size
        self._max_queue = int(max_queue)  # pending REQUESTS cap, 0 = off
        self._shed_policy = shed_policy
        # serving heartbeat: beat per shipped batch so a wedged scorer trips
        # the same stack-dump path as a wedged train step (obs/watchdog.py)
        self._watchdog = watchdog
        # replayable traffic record ([serving] log_features): a
        # data/replay.RequestLog that every served request's feature payload
        # (+ label when the caller attached one) is appended to, so the
        # online loop can replay traffic as a training stream.  Labels ride
        # in as a reserved "label" column and are STRIPPED before scoring —
        # the scorer's jit cache never sees them.
        self._request_log = request_log
        self._labels: dict[Any, np.ndarray] = {}
        self._ships = 0
        self._pending: list[tuple[Any, dict[str, np.ndarray], int, float]] = []
        self._pending_rows = 0
        self.results: dict[Any, np.ndarray] = {}
        self.latencies_ms: list[float] = []
        # (rows, padded) per shipped batch — the knob-observability hook:
        # the bucket set changes `padded`, the deadline changes when a
        # partial (rows < max_batch) batch ships
        self.shipped: list[tuple[int, int]] = []
        self.shed: list[tuple[Any, str]] = []  # (request_id, reason)
        self.swaps: list[dict[str, Any]] = []
        self._version: Any = None  # bundle chain version being served
        self._digest: Any = None   # served bundle digest (trace identity)
        # trace identity: which fleet replica this batcher serves for (the
        # ReplicaFrontend stamps it; 0 for the single-frontend layout)
        self.replica = 0
        # saturation fields of the LAST shipped batch — the fleet heartbeat
        # merges these into its per-replica health record
        self.last_queue_depth = 0
        self.last_batch_fill = 0.0
        self._swapping = False
        self._under_swap_ms: list[float] = []

    # ------------------------------------------------------------- intake

    def submit(self, request_id: Any, batch: Mapping[str, np.ndarray]) -> None:
        """Queue one request; ships (possibly several) full batches as soon
        as ``max_batch`` rows are pending."""
        cols = {k: np.asarray(v) for k, v in batch.items()}
        n = len(next(iter(cols.values())))
        if any(len(v) != n for v in cols.values()):
            raise ValueError(f"request {request_id!r}: ragged columns")
        if self._request_log is not None:
            # feedback column: logged for replay, stripped before scoring
            label = cols.pop("label", None)
            if label is not None:
                self._labels[request_id] = label
        if n > self._max_batch:
            raise ValueError(
                f"request {request_id!r} has {n} rows > max_batch "
                f"{self._max_batch}; split it upstream")
        if self._max_queue and len(self._pending) >= self._max_queue:
            # admission control: shed already-doomed requests first (past
            # the deadline they were promised), then apply the policy
            now = self._clock()
            while (self._pending and len(self._pending) >= self._max_queue
                   and now - self._pending[0][3] >= self._deadline_s):
                self._shed_oldest("past_deadline")
            if len(self._pending) >= self._max_queue:
                if self._shed_policy == "reject":
                    self._record_shed(request_id, n, self._clock(), "rejected")
                    return
                self._shed_oldest("displaced")
        self._pending.append((request_id, cols, n, self._clock()))
        self._pending_rows += n
        while self._pending_rows >= self._max_batch:
            self._ship()

    def _shed_oldest(self, reason: str) -> None:
        rid, _, n, t0 = self._pending.pop(0)
        self._pending_rows -= n
        self._record_shed(rid, n, t0, reason)

    def _record_shed(self, rid: Any, n: int, t0: float, reason: str) -> None:
        self.results[rid] = None  # the caller sees the outcome, not a KeyError
        self.shed.append((rid, reason))
        if self._request_log is not None:
            self._labels.pop(rid, None)
            # shed requests were never scored: replay must see (and skip)
            # them, so the record carries no feature payload
            self._request_log.append({
                "event": "serve_request", "request": str(rid), "rows": n,
                "outcome": "shed", "shed_reason": reason,
                "version": self._version})
        if self._logger is not None:
            self._logger.log(event="serve_request", request=str(rid), rows=n,
                             batch_rows=0, padded=0, queue_depth=len(self._pending),
                             batch_fill=0.0,
                             latency_ms=(self._clock() - t0) * 1000.0,
                             outcome="shed", shed_reason=reason,
                             under_swap=self._swapping, version=self._version)

    def poll(self) -> None:
        """Ship a PARTIAL batch iff the oldest pending request's deadline
        has expired (deadline 0 ships on every poll)."""
        if not self._pending:
            return
        age = self._clock() - self._pending[0][3]
        if age >= self._deadline_s:
            self._ship()

    def drain(self) -> None:
        """Flush everything still pending (shutdown path)."""
        while self._pending:
            self._ship()

    # ----------------------------------------------------------- shipping

    def _bucket(self, rows: int) -> int:
        for b in self._buckets:
            if b >= rows:
                return b
        raise ValueError(
            f"batch of {rows} rows exceeds buckets[-1] {self._buckets[-1]}")

    def _ship(self) -> None:
        take: list[tuple[Any, dict[str, np.ndarray], int, float]] = []
        rows = 0
        # whole requests only, first-come-first-served, up to max_batch
        while self._pending and (
                not take or rows + self._pending[0][2] <= self._max_batch):
            item = self._pending.pop(0)
            take.append(item)
            rows += item[2]
        self._pending_rows -= rows
        padded = self._bucket(rows)
        batch: dict[str, np.ndarray] = {}
        for k in take[0][1]:
            col = np.concatenate([cols[k] for _, cols, _, _ in take])
            batch[k] = np.pad(col, [(0, padded - rows)] +
                              [(0, 0)] * (col.ndim - 1))
        from tdfo_tpu.utils import faults

        inj = faults.active()
        if inj is not None:
            inj.maybe_slow_score()  # deterministic wedged-scorer stand-in
        scores = np.asarray(self._score(batch))[:rows]
        self.shipped.append((rows, padded))
        self._ships += 1
        if self._watchdog is not None:
            self._watchdog.beat(self._ships)
        if self._cache_size is not None:
            n_progs = self._cache_size()
            if n_progs > len(self._buckets):
                raise RuntimeError(
                    f"bounded-jit-cache invariant violated: the scorer holds "
                    f"{n_progs} compiled programs for {len(self._buckets)} "
                    f"buckets — a non-bucket batch shape reached score_fn")
        done = self._clock()
        # saturation observability: requests still waiting after this ship,
        # and how much of the padded program the batch actually used
        depth = self.last_queue_depth = len(self._pending)
        fill = self.last_batch_fill = rows / padded
        off = 0
        for rid, cols, n, t0 in take:
            self.results[rid] = scores[off:off + n]
            off += n
            latency_ms = (done - t0) * 1000.0
            self.latencies_ms.append(latency_ms)
            if self._swapping:
                self._under_swap_ms.append(latency_ms)
            if self._request_log is not None:
                feats = {k: v.tolist() for k, v in cols.items()}
                label = self._labels.pop(rid, None)
                if label is not None:
                    feats["label"] = label.tolist()
                seq = self._request_log.append({
                    "event": "serve_request", "request": str(rid),
                    "rows": n, "outcome": "ok", "features": feats,
                    "under_swap": self._swapping, "version": self._version,
                    "latency_ms": latency_ms})
                # the causal-chain anchor: (replica, seq) is the id the
                # replay batch span quotes back, (version, digest) is what
                # served it — obs/aggregate.py joins the two offline
                _trace.emit(
                    "frontend", "serve_request", replica=self.replica,
                    seq=seq, version=self._version, digest=self._digest,
                    rows=n, latency_ms=round(latency_ms, 3),
                    queue_depth=depth, batch_fill=round(fill, 4))
            if self._logger is not None:
                self._logger.log(event="serve_request", request=str(rid),
                                 rows=n, batch_rows=rows, padded=padded,
                                 queue_depth=depth, batch_fill=fill,
                                 latency_ms=latency_ms, outcome="ok",
                                 under_swap=self._swapping,
                                 version=self._version)

    # ------------------------------------------------------------ hot swap

    def swap(self, score_fn: Callable, *, version: Any = None,
             digest: Any = None,
             program_cache_size: Callable[[], int] | None = None) -> float:
        """Flip to a new scorer without dropping accepted traffic.

        In-flight requests drain on the OLD scorer (they were admitted
        against its latency promise), then the function reference flips —
        atomic under the GIL, so the next ship sees exactly one scorer.
        Requests served inside the drain window are tagged ``under_swap``
        in the JSONL and feed ``p99_under_swap_ms``.  Returns the swap
        latency in ms (also logged as a ``serve_swap`` event).  The durable
        on-disk half (verify + publish + crash recovery) lives in
        :class:`tdfo_tpu.serve.swap.BundleStore`.
        """
        t0 = self._clock()
        drained = self._pending_rows
        self._swapping = True
        try:
            self.drain()
        finally:
            self._swapping = False
        self._score = score_fn
        # the old scorer's program-cache probe is stale the moment we flip
        self._cache_size = program_cache_size
        old_version, self._version = self._version, version
        self._digest = digest
        swap_ms = (self._clock() - t0) * 1000.0
        self.swaps.append({"version": version, "from_version": old_version,
                           "drained_rows": drained, "swap_ms": swap_ms})
        _trace.emit("frontend", "swap", replica=self.replica,
                    version=version, digest=digest,
                    from_version=old_version, drained_rows=drained,
                    swap_ms=round(swap_ms, 3))
        if self._request_log is not None:
            # replay SKIPS non-request events; recording the swap in-stream
            # timestamps which traffic each served version covers
            self._request_log.append({
                "event": "serve_swap", "version": version,
                "from_version": old_version})
        if self._logger is not None:
            self._logger.log(event="serve_swap", version=version,
                             from_version=old_version, drained_rows=drained,
                             swap_ms=swap_ms)
        return swap_ms

    # -------------------------------------------------------------- stats

    def run(self, requests) -> dict[Any, np.ndarray]:
        """Replay ``(request_id, batch)`` pairs through submit+poll, then
        drain.  The trace-replay path tests and the serve command share."""
        for rid, batch in requests:
            self.submit(rid, batch)
            self.poll()
        self.drain()
        return self.results

    def stats(self) -> dict[str, float]:
        lat = np.asarray(self.latencies_ms, np.float64)
        out = {
            "requests": int(lat.size),
            "batches": len(self.shipped),
            "p50_ms": float(np.percentile(lat, 50)) if lat.size else 0.0,
            "p99_ms": float(np.percentile(lat, 99)) if lat.size else 0.0,
            "shed": len(self.shed),
            "swaps": len(self.swaps),
        }
        if self._under_swap_ms:
            out["p99_under_swap_ms"] = float(
                np.percentile(np.asarray(self._under_swap_ms, np.float64), 99))
        if self._logger is not None and lat.size:
            self._logger.log(event="serve_summary", **out)
        return out


def serve_from_config(config, *, log_dir: str | Path | None = None,
                      n_requests: int = 64) -> dict[str, Any]:
    """The ``python -m tdfo_tpu.launch serve`` body: restore the newest
    checkpoint (fresh init when none exists), export the serving bundle,
    build the scorer, and run a synthetic ragged request trace through the
    micro-batcher — plus a corpus build + one retrieval round (TwoTower
    user tower / Bert4Rec item table) so every ``[serving]`` knob is
    exercised by the real command.  The seq family ships ragged HISTORIES:
    each request's variable-length item history folds into the fixed eval
    window via ``serve/seq_scoring.py:history_window`` and rides with a
    1-positive + 100-negative candidate panel, the replayable schema.
    Returns the latency/throughput stats dict (printed by ``launch``)."""
    import jax

    from tdfo_tpu.core.config import serving_model_kind
    from tdfo_tpu.serve.export import export_bundle, load_bundle
    from tdfo_tpu.serve.scoring import make_scorer
    from tdfo_tpu.train.trainer import Trainer, _ctr_columns

    kind = serving_model_kind(config)  # refuses unknown models actionably
    trainer = Trainer(config, log_dir=log_dir)
    state, step = trainer.state, 0
    if trainer._ckpt is not None and trainer._ckpt.latest_step() is not None:
        step, state, _ = trainer._ckpt.restore(
            trainer.state, stamps=trainer._ckpt_stamps)

    out_dir = Path(log_dir or config.checkpoint_dir or ".") / "serving_bundle"
    kwargs: dict[str, Any] = {}
    if hasattr(state, "tables"):  # DMP/sparse regime
        kwargs = dict(coll=trainer.coll, tables=state.tables,
                      dense_params=state.dense_params)
    else:
        kwargs = dict(params=state.params)
    if kind == "seq":
        cat_cols: tuple[str, ...] = ()
        cont_cols: tuple[str, ...] = ()
        kwargs["seq"] = {"max_len": config.max_len, "n_heads": config.n_heads,
                         "n_layers": config.n_layers}
    else:
        cat_cols, cont_cols = _ctr_columns(config)
    export_bundle(
        out_dir, model=config.model, embed_dim=config.embed_dim,
        cat_columns=cat_cols, cont_columns=cont_cols,
        size_map=config.size_map, step=step,
        mixed_precision=config.mixed_precision, **kwargs)
    bundle = load_bundle(out_dir)
    scorer = make_scorer(bundle, mesh=trainer.mesh)

    rng = np.random.default_rng(config.seed)
    spec = config.serving
    base = Path(log_dir or config.checkpoint_dir or ".")
    fleet_mode = spec.replicas > 1
    request_log = None
    if spec.log_features and not fleet_mode:
        from tdfo_tpu.data.replay import RequestLog

        request_log = RequestLog(base / "request_log",
                                 segment_bytes=spec.log_segment_bytes)
    buckets = ((spec.history_buckets or spec.buckets) if kind == "seq"
               else spec.buckets)
    hi = min(spec.max_batch, buckets[0])
    requests = []
    if kind == "seq":
        # synthetic ragged-history trace: per-row histories of 1..2*max_len
        # raw items fold into the fixed window (truncate-left, append MASK,
        # left-pad) exactly like a live request would; the candidate panel
        # is the replayable 1+100 eval schema, no label column (the panel's
        # column 0 IS the feedback)
        from tdfo_tpu.serve.seq_scoring import history_window

        n_items, max_len = scorer.n_items, scorer.max_len
        for i in range(n_requests):
            n = int(rng.integers(1, hi + 1))
            seqs = np.stack([
                history_window(
                    rng.integers(1, n_items + 1,
                                 size=int(rng.integers(1, 2 * max_len))),
                    n_items=n_items, max_len=max_len,
                    max_history=spec.max_history)
                for _ in range(n)])
            cands = rng.integers(1, n_items + 1, size=(n, 101),
                                 dtype=np.int32)
            requests.append((f"req{i}", {"seqs": seqs, "cands": cands}))
    else:
        # synthetic ragged trace: ids within each vocab, floats in [0, 1)
        vocab = _column_vocab(config, cat_cols)
        # labels come from a SEPARATE rng so turning log_features on never
        # perturbs the request trace itself (the feedback join is
        # out-of-band)
        label_rng = np.random.default_rng(config.seed + 1)
        for i in range(n_requests):
            n = int(rng.integers(1, hi + 1))
            batch: dict[str, np.ndarray] = {
                c: rng.integers(0, vocab[c], size=n, dtype=np.int32)
                for c in cat_cols
            }
            for c in cont_cols:
                batch[c] = rng.random(n, dtype=np.float32)
            if spec.log_features:
                batch["label"] = label_rng.integers(0, 2, size=n,
                                                    dtype=np.int8)
            requests.append((f"req{i}", batch))

    if fleet_mode:
        # [serving] replicas > 1: the fleet quickstart — N frontends over
        # one BundleStore, each following CURRENT and (with log_features)
        # writing its own replica-<k> request log for the merged replay
        from tdfo_tpu.serve.fleet import ServingFleet
        from tdfo_tpu.serve.swap import BundleStore

        store = BundleStore(base / "bundle_store")
        if store.recover() is None:
            store.ingest_full(out_dir)
        flt = ServingFleet(
            store, config, mesh=trainer.mesh, logger=trainer.logger,
            request_log_root=(base / "request_log" if spec.log_features
                              else None))
        flt.sync()
        t0 = _trace.clock()
        flt.run(requests)
        wall = _trace.elapsed_s(t0)
        reps = [r for r in flt.alive() if r.batcher is not None]
        lat = np.asarray([ms for r in reps for ms in r.batcher.latencies_ms],
                         np.float64)
        stats = {
            "requests": int(lat.size),
            "batches": sum(len(r.batcher.shipped) for r in reps),
            "p50_ms": float(np.percentile(lat, 50)) if lat.size else 0.0,
            "p99_ms": float(np.percentile(lat, 99)) if lat.size else 0.0,
            "shed": sum(len(r.batcher.shed) for r in reps),
            "swaps": sum(len(r.batcher.swaps) for r in reps),
            "replicas": len(reps),
            "version": store.current_version(),
        }
        if spec.log_features:
            stats["request_log"] = str(base / "request_log")
        flt.close()
    else:
        watchdog = None
        if config.telemetry.stall_timeout_s > 0:
            from tdfo_tpu.obs.watchdog import StallWatchdog

            watchdog = StallWatchdog(
                base / "heartbeat_serve.jsonl",
                config.telemetry.stall_timeout_s, label="serve",
                rotate_bytes=config.telemetry.log_rotate_bytes).start()

        t0 = _trace.clock()
        mb = MicroBatcher(
            scorer.score, buckets=buckets, max_batch=spec.max_batch,
            batch_deadline_ms=spec.batch_deadline_ms, logger=trainer.logger,
            program_cache_size=scorer.score_cache_size,
            max_queue=spec.max_queue, shed_policy=spec.shed_policy,
            watchdog=watchdog, request_log=request_log)
        mb.run(requests)
        wall = _trace.elapsed_s(t0)
        if watchdog is not None:
            watchdog.stop()
        stats = mb.stats()
        if request_log is not None:
            request_log.close()
            stats["request_log"] = str(request_log.root)
        stats["programs"] = scorer.score_cache_size()
    stats["qps"] = stats["requests"] / wall if wall > 0 else float("inf")
    stats["bundle"] = str(out_dir)
    stats["step"] = int(step)

    if config.model == "twotower":
        from tdfo_tpu.serve.corpus import build_corpus, synthetic_item_features
        from tdfo_tpu.serve.retrieval import make_retrieval

        n_items = int(config.size_map.get("item", 0))
        if n_items > spec.top_k:
            # coarse_k > 0 switches on the two-stage program; the corpus
            # then stores at coarse_dtype (int8 default — the ScaNN-style
            # memory/scan budget the knob exists for)
            corpus = build_corpus(
                scorer,
                synthetic_item_features(config.size_map, n_items,
                                        seed=config.seed),
                corpus_batch=spec.corpus_batch, mesh=trainer.mesh,
                dtype=spec.coarse_dtype if spec.coarse_k > 0
                else "float32")
            retrieve = make_retrieval(
                corpus, mesh=trainer.mesh, top_k=spec.top_k,
                coarse_k=spec.coarse_k)
            q_batch = {"user_id": np.arange(8, dtype=np.int32) %
                       max(vocab.get("user_id", 1), 1)}
            _, ids = retrieve(scorer.user_embed(q_batch))
            stats["retrieved"] = int(jax.device_get(ids).shape[1])
    elif kind == "seq":
        # next-item retrieval: the bundle's output head IS the corpus
        # (bias-folded out_proj columns — out_proj is untied, the input
        # table would rank by the wrong function), queried by the [h, 1]
        # last-position hidden state — same two-stage int8 knobs as the
        # TwoTower path
        from tdfo_tpu.serve.retrieval import make_retrieval
        from tdfo_tpu.serve.seq_scoring import history_window, item_corpus

        if scorer.n_items > spec.top_k:
            corpus = item_corpus(
                bundle, mesh=trainer.mesh,
                dtype=spec.coarse_dtype if spec.coarse_k > 0 else "float32")
            retrieve = make_retrieval(
                corpus, mesh=trainer.mesh, top_k=spec.top_k,
                coarse_k=spec.coarse_k)
            q = np.stack([
                history_window(
                    rng.integers(1, scorer.n_items + 1, size=scorer.max_len),
                    n_items=scorer.n_items, max_len=scorer.max_len)
                for _ in range(8)])
            _, ids = retrieve(scorer.query_embed({"seqs": q}))
            stats["retrieved"] = int(jax.device_get(ids).shape[1])
    trainer.logger.close()
    return stats


def _column_vocab(config, cat_cols) -> dict[str, int]:
    """Vocab size per categorical INPUT column (the size_map keys by feature
    for the TwoTower schema, by column for custom schemas)."""
    if config.categorical_features:
        return {c: int(config.size_map[c]) for c in cat_cols}
    from tdfo_tpu.models.twotower import TWOTOWER_CATEGORICAL, _FEATURE_TO_INPUT

    return {_FEATURE_TO_INPUT[f]: int(config.size_map[f])
            for f in TWOTOWER_CATEGORICAL}
