"""Sharded top-k MIPS over the candidate corpus: exact scan + two-stage.

On v5e the measured cost model makes brute force the right first retrieval
subsystem (no ANN index): bf16 MXU matmuls run 100-350 us at Goodreads/
Criteo corpus scales and ``lax.top_k``/argsort ~16 us, so a corpus-sharded
scan saturates the chip — ScaNN's quantized search (Guo et al. 2020) only
pays once corpora outgrow HBM.

Exact program (one ``shard_map`` over the corpus shards, queries
replicated):

  1. per-shard ``[B, D] x [D, rows/shard]`` bf16 matmul with
     ``preferred_element_type=f32`` (CLAUDE.md: bf16 INPUTS, f32
     accumulation), padding rows (id -1) masked to -inf;
  2. per-shard ``lax.top_k`` -> k local (score, id) candidates;
  3. global merge: the ``k x n_shards`` candidates concatenate shard-major
     and one final ``lax.top_k`` picks the answer.

Bitwise-equal to :func:`retrieval_reference` (single-device stable argsort)
including tie-breaks: ``lax.top_k`` prefers lower indices, which within a
shard means lower corpus position, and the shard-major merge order means
lower shard — i.e. lower corpus position globally — exactly the stable
argsort's preference.  Scores pass through selection untouched, so they are
the per-shard matmul's f32 bits.

Two-stage program (``coarse_k`` > 0, the ScaNN split for int8 corpora that
would not fit HBM at f32):

  1. COARSE: per-shard scan of the STORED rows.  For an int8 corpus the
     scores come from the quantized rows without materialising f32:
     ``dot(q, code_j * scale_j + offset_j) = scale_j * dot(q, code_j)
     + sum(q) * offset_j`` — one bf16 code matmul (int8 codes are exact in
     bf16: |code| <= 128 < 2^8) plus a rank-1 affine correction.  Top
     ``min(coarse_k, rows/shard)`` candidates per shard, shard-major merge,
     global top ``coarse_k`` by coarse score.
  2. RERANK: candidate corpus positions sort ascending (restoring the
     lower-position tie-break the coarse selection scrambled), full rows
     gather (CLAUDE.md: FULL-row gathers only) and dequantize, and
     ``lax.top_k`` over EXACT per-query :func:`mips_scores` bits picks the
     final k.  The per-query ``lax.map`` formulation is bit-identical to
     the full-corpus matmul; the batched ``dot_general`` is NOT (measured).

``coarse_k >= n_items`` routes STATICALLY to the exact program (the coarse
stage could drop nothing), so the degenerate case is bitwise-equal to the
exact scan by construction.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tdfo_tpu.core.mesh import DATA_AXIS, shard_map
from tdfo_tpu.ops.quant import dequantize_rows
from tdfo_tpu.serve.corpus import Corpus

__all__ = ["make_retrieval", "mips_scores", "retrieval_reference"]


def mips_scores(queries: jax.Array, vectors: jax.Array) -> jax.Array:
    """THE serving score formula: ``[B, D] x [N, D] -> [B, N]`` f32 inner
    products from bf16 operands.  One definition shared by the sharded
    program and the reference so the bitwise-equality contract compares
    identical arithmetic."""
    return jax.lax.dot_general(
        queries.astype(jnp.bfloat16),
        vectors.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _masked_top_k(scores: jax.Array, ids: jax.Array, k: int):
    """Top-k over one corpus block, padding rows (id -1) masked to -inf so
    shard-alignment padding can never be retrieved."""
    scores = jnp.where(ids >= 0, scores, -jnp.inf)
    s, pos = jax.lax.top_k(scores, k)
    return s, jnp.take(ids, pos)


def _coarse_scores(queries, block, qscale):
    """Approximate scores against STORED rows: exact :func:`mips_scores`
    for float blocks, the affine-corrected code matmul for int8 blocks
    (module docstring identity — nothing f32-dense materialises)."""
    if qscale is None:
        return mips_scores(queries, block)
    raw = mips_scores(queries, block)  # int8 codes are exact in bf16
    qsum = jnp.sum(
        queries.astype(jnp.bfloat16).astype(jnp.float32), axis=1)
    return raw * qscale[None, :, 0] + qsum[:, None] * qscale[None, :, 1]


def _gather_dequant(vectors, qscale, flat_pos):
    """FULL-row gather of candidate rows + f32 dequantize.  bf16 rows cast
    up exactly; :func:`mips_scores` casts back down, so rerank bits match
    the exact scan for every storage dtype."""
    rows = jnp.take(vectors, flat_pos, axis=0)
    if qscale is None:
        return rows.astype(jnp.float32)
    return dequantize_rows(rows, jnp.take(qscale, flat_pos, axis=0))


def _rerank_scores(queries, cand):
    """Exact re-rank: ``[B, D] x [B, m, D] -> [B, m]``, bit-identical to
    :func:`mips_scores` of the full corpus at the candidate columns.  Uses
    a per-query ``lax.map`` of the SAME dot_general — the batched
    formulation produces different f32 bits (measured on CPU)."""
    return jax.lax.map(
        lambda qc: mips_scores(qc[0][None, :], qc[1])[0], (queries, cand))


def make_retrieval(
    corpus: Corpus,
    *,
    mesh=None,
    axis: str = DATA_AXIS,
    top_k: int = 100,
    coarse_k: int = 0,
) -> Callable[[jax.Array], tuple[jax.Array, jax.Array]]:
    """Build the jitted retrieval program for one corpus.

    Returns ``retrieve(queries[B, D]) -> (scores[B, k] f32, ids[B, k]
    int32)``, candidates in descending score order.  The corpus rides as a
    jit ARGUMENT (bound here), never a closure constant (CLAUDE.md: big
    closed-over arrays serialize into the compile payload).  Without a mesh
    the program degenerates to the single-device scan.

    ``coarse_k`` = 0 runs the exact scan (int8 corpora dequantize in-shard
    first).  ``coarse_k`` >= ``top_k`` runs the two-stage program: coarse
    top-``coarse_k`` over stored rows, exact re-rank of the survivors.
    """
    if top_k < 1:
        raise ValueError("top_k must be >= 1")
    if top_k > corpus.n_items:
        raise ValueError(
            f"top_k ({top_k}) exceeds the corpus ({corpus.n_items} items)")
    if coarse_k < 0:
        raise ValueError("coarse_k must be >= 0 (0 = exact scan)")
    if coarse_k and coarse_k < top_k:
        raise ValueError(
            f"coarse_k ({coarse_k}) must be >= top_k ({top_k}) — the "
            "coarse stage must keep every row the final stage can return")
    if coarse_k >= corpus.n_items:
        coarse_k = 0  # static degenerate routing: nothing could be dropped
    n_shards = mesh.shape[axis] if mesh is not None else 1
    qs = corpus.qscale

    if coarse_k == 0 and n_shards == 1:
        if qs is None:
            @jax.jit
            def retrieve_single(queries, vectors, ids):
                return _masked_top_k(
                    mips_scores(queries, vectors), ids, top_k)
        else:
            @jax.jit
            def retrieve_single(queries, vectors, qscale, ids):
                vecs = dequantize_rows(vectors, qscale)
                return _masked_top_k(mips_scores(queries, vecs), ids, top_k)

        return _bind(retrieve_single, corpus)

    rows_per_shard = corpus.vectors.shape[0] // n_shards

    if coarse_k == 0:
        # a shard holds N_pad / n_shards rows; it can contribute at most
        # that many candidates (k_local < top_k only for tiny corpora,
        # where the merged k_local * n_shards >= N_pad >= top_k candidates
        # still suffice)
        k_local = min(top_k, rows_per_shard)

        if qs is None:
            def local(vec_shard, id_shard, queries):
                return _masked_top_k(
                    mips_scores(queries, vec_shard), id_shard, k_local)

            @jax.jit
            def retrieve_sharded(queries, vectors, ids):
                # out_specs concatenate the per-shard [B, k_local]
                # candidate blocks along dim 1 SHARD-MAJOR — the property
                # the tie-break proof needs
                cand_s, cand_i = shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(P(axis, None), P(axis), P()),
                    out_specs=(P(None, axis), P(None, axis)),
                    check_vma=False,
                )(vectors, ids, queries)
                top_s, pos = jax.lax.top_k(cand_s, top_k)
                return top_s, jnp.take_along_axis(cand_i, pos, axis=1)
        else:
            def local_q(vec_shard, qs_shard, id_shard, queries):
                vecs = dequantize_rows(vec_shard, qs_shard)
                return _masked_top_k(
                    mips_scores(queries, vecs), id_shard, k_local)

            @jax.jit
            def retrieve_sharded(queries, vectors, qscale, ids):
                cand_s, cand_i = shard_map(
                    local_q,
                    mesh=mesh,
                    in_specs=(P(axis, None), P(axis, None), P(axis), P()),
                    out_specs=(P(None, axis), P(None, axis)),
                    check_vma=False,
                )(vectors, qscale, ids, queries)
                top_s, pos = jax.lax.top_k(cand_s, top_k)
                return top_s, jnp.take_along_axis(cand_i, pos, axis=1)

        return _bind(retrieve_sharded, corpus)

    # ------------------------------------------------ two-stage program
    # coarse_k clamps to what a shard can contribute; the merged pool
    # always holds >= top_k real rows (each shard surfaces its real rows
    # before any -inf padding, and sum_s min(k_local, real_s) >=
    # min(coarse_k, n_items) >= top_k)
    k_local = min(coarse_k, rows_per_shard)
    n_cand = min(coarse_k, k_local * n_shards)

    if n_shards == 1:
        @jax.jit
        def retrieve_two_single(queries, vectors, qscale, ids):
            coarse = _coarse_scores(queries, vectors, qscale)
            coarse = jnp.where(ids[None, :] >= 0, coarse, -jnp.inf)
            _, pos = jax.lax.top_k(coarse, n_cand)
            pos = jnp.sort(pos, axis=1)  # restore the position tie-break
            flat = pos.reshape(-1)
            cand = _gather_dequant(vectors, qscale, flat).reshape(
                *pos.shape, -1)
            cand_ids = jnp.take(ids, pos)
            rr = jnp.where(
                cand_ids >= 0, _rerank_scores(queries, cand), -jnp.inf)
            s, sel = jax.lax.top_k(rr, top_k)
            return s, jnp.take_along_axis(cand_ids, sel, axis=1)

        return _bind(retrieve_two_single, corpus, with_qscale=True)

    def coarse_local(vec_shard, id_shard, queries, *qs_ops):
        qs_shard = qs_ops[0] if qs_ops else None
        scores = _coarse_scores(queries, vec_shard, qs_shard)
        scores = jnp.where(id_shard[None, :] >= 0, scores, -jnp.inf)
        s, pos = jax.lax.top_k(scores, k_local)
        base = jax.lax.axis_index(axis) * rows_per_shard
        return s, pos + base  # GLOBAL corpus positions

    def gather_local(vec_shard, id_shard, pos, *qs_ops):
        # each position lives on exactly one shard: the owner contributes
        # the dequantized row (and id), everyone else exact f32 zeros, and
        # the psum is a pure select — candidate rows come out replicated
        qs_shard = qs_ops[0] if qs_ops else None
        base = jax.lax.axis_index(axis) * rows_per_shard
        loc = pos - base
        mine = (loc >= 0) & (loc < rows_per_shard)
        flat = jnp.clip(loc, 0, rows_per_shard - 1).reshape(-1)
        rows = _gather_dequant(vec_shard, qs_shard, flat).reshape(
            *pos.shape, -1)
        rows = jnp.where(mine[..., None], rows, 0.0)
        idv = jnp.where(mine, jnp.take(id_shard, flat).reshape(pos.shape), 0)
        return jax.lax.psum(rows, axis), jax.lax.psum(idv, axis)

    @jax.jit
    def retrieve_two_sharded(queries, vectors, qscale, ids):
        qs_ops = () if qscale is None else (qscale,)
        qs_specs = tuple(P(axis, None) for _ in qs_ops)
        cand_s, cand_pos = shard_map(
            coarse_local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(), *qs_specs),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )(vectors, ids, queries, *qs_ops)
        _, sel = jax.lax.top_k(cand_s, n_cand)
        pos = jnp.sort(jnp.take_along_axis(cand_pos, sel, axis=1), axis=1)
        cand, cand_ids = shard_map(
            gather_local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(), *qs_specs),
            out_specs=(P(), P()),
            check_vma=False,
        )(vectors, ids, pos, *qs_ops)
        rr = jnp.where(
            cand_ids >= 0, _rerank_scores(queries, cand), -jnp.inf)
        s, sel2 = jax.lax.top_k(rr, top_k)
        return s, jnp.take_along_axis(cand_ids, sel2, axis=1)

    return _bind(retrieve_two_sharded, corpus, with_qscale=True)


def _bind(jitted, corpus: Corpus, *, with_qscale: bool | None = None):
    """Close the corpus over a jitted program as jit ARGUMENTS; ``.jitted``
    stays reachable for lowering inspection and compile-cache accounting
    (``tests/test_serve_frontend.py``, bench).  Float exact programs keep
    the historical ``(queries, vectors, ids)`` signature; qscale-bearing
    programs take ``(queries, vectors, qscale, ids)`` (two-stage programs
    always do — ``qscale`` rides as ``None`` for float corpora)."""
    if with_qscale is None:
        with_qscale = corpus.qscale is not None

    if with_qscale:
        def retrieve(queries):
            return jitted(
                queries, corpus.vectors, corpus.qscale, corpus.ids)
    else:
        def retrieve(queries):
            return jitted(queries, corpus.vectors, corpus.ids)

    retrieve.jitted = jitted
    retrieve.corpus = corpus
    return retrieve


def retrieval_reference(
    queries, corpus: Corpus, *, top_k: int = 100
) -> tuple[jax.Array, jax.Array]:
    """Single-device exact reference: full matmul + STABLE argsort (ties ->
    lowest corpus position, the same preference ``lax.top_k`` encodes).
    The bitwise yardstick for :func:`make_retrieval` — ids AND f32 scores.
    int8 corpora dequantize first: the reference scores the corpus as
    served, not the pre-quantization vectors."""
    vectors = jnp.asarray(jax.device_get(corpus.vectors))[:corpus.n_items]
    if corpus.qscale is not None:
        vectors = dequantize_rows(
            vectors,
            jnp.asarray(jax.device_get(corpus.qscale))[:corpus.n_items])
    ids = jnp.asarray(jax.device_get(corpus.ids))[:corpus.n_items]
    scores = mips_scores(jnp.asarray(queries), vectors)  # [B, N]
    order = jnp.argsort(-scores, axis=-1, stable=True)[:, :top_k]
    return jnp.take_along_axis(scores, order, axis=1), jnp.take(ids, order)
