"""Sharded EXACT top-k MIPS over the candidate corpus.

On v5e the measured cost model makes brute force the right first retrieval
subsystem (no ANN index): bf16 MXU matmuls run 100-350 us at Goodreads/
Criteo corpus scales and ``lax.top_k``/argsort ~16 us, so a corpus-sharded
scan saturates the chip — ScaNN's quantized search (Guo et al. 2020) only
pays once corpora outgrow HBM.

Program (one ``shard_map`` over the corpus shards, queries replicated):

  1. per-shard ``[B, D] x [D, rows/shard]`` bf16 matmul with
     ``preferred_element_type=f32`` (CLAUDE.md: bf16 INPUTS, f32
     accumulation), padding rows (id -1) masked to -inf;
  2. per-shard ``lax.top_k`` -> k local (score, id) candidates;
  3. global merge: the ``k x n_shards`` candidates concatenate shard-major
     and one final ``lax.top_k`` picks the answer.

Bitwise-equal to :func:`retrieval_reference` (single-device stable argsort)
including tie-breaks: ``lax.top_k`` prefers lower indices, which within a
shard means lower corpus position, and the shard-major merge order means
lower shard — i.e. lower corpus position globally — exactly the stable
argsort's preference.  Scores pass through selection untouched, so they are
the per-shard matmul's f32 bits.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tdfo_tpu.core.mesh import DATA_AXIS, shard_map
from tdfo_tpu.serve.corpus import Corpus

__all__ = ["make_retrieval", "mips_scores", "retrieval_reference"]


def mips_scores(queries: jax.Array, vectors: jax.Array) -> jax.Array:
    """THE serving score formula: ``[B, D] x [N, D] -> [B, N]`` f32 inner
    products from bf16 operands.  One definition shared by the sharded
    program and the reference so the bitwise-equality contract compares
    identical arithmetic."""
    return jax.lax.dot_general(
        queries.astype(jnp.bfloat16),
        vectors.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _masked_top_k(scores: jax.Array, ids: jax.Array, k: int):
    """Top-k over one corpus block, padding rows (id -1) masked to -inf so
    shard-alignment padding can never be retrieved."""
    scores = jnp.where(ids >= 0, scores, -jnp.inf)
    s, pos = jax.lax.top_k(scores, k)
    return s, jnp.take(ids, pos)


def make_retrieval(
    corpus: Corpus, *, mesh=None, axis: str = DATA_AXIS, top_k: int = 100
) -> Callable[[jax.Array], tuple[jax.Array, jax.Array]]:
    """Build the jitted retrieval program for one corpus.

    Returns ``retrieve(queries[B, D]) -> (scores[B, k] f32, ids[B, k]
    int32)``, candidates in descending score order.  The corpus rides as a
    jit ARGUMENT (bound here), never a closure constant (CLAUDE.md: big
    closed-over arrays serialize into the compile payload).  Without a mesh
    the program degenerates to the single-device scan.
    """
    if top_k < 1:
        raise ValueError("top_k must be >= 1")
    if top_k > corpus.n_items:
        raise ValueError(
            f"top_k ({top_k}) exceeds the corpus ({corpus.n_items} items)")
    n_shards = mesh.shape[axis] if mesh is not None else 1

    if n_shards == 1:
        @jax.jit
        def retrieve_single(queries, vectors, ids):
            return _masked_top_k(mips_scores(queries, vectors), ids, top_k)

        return _bind(retrieve_single, corpus)

    # a shard holds N_pad / n_shards rows; it can contribute at most that
    # many candidates (k_local < top_k only for tiny corpora, where the
    # merged k_local * n_shards >= N_pad >= top_k candidates still suffice)
    k_local = min(top_k, corpus.vectors.shape[0] // n_shards)

    def local(vec_shard, id_shard, queries):
        return _masked_top_k(
            mips_scores(queries, vec_shard), id_shard, k_local)

    @jax.jit
    def retrieve_sharded(queries, vectors, ids):
        # out_specs concatenate the per-shard [B, k_local] candidate blocks
        # along dim 1 SHARD-MAJOR — the property the tie-break proof needs
        cand_s, cand_i = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P()),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )(vectors, ids, queries)
        top_s, pos = jax.lax.top_k(cand_s, top_k)
        return top_s, jnp.take_along_axis(cand_i, pos, axis=1)

    return _bind(retrieve_sharded, corpus)


def _bind(jitted, corpus: Corpus):
    """Close the corpus over a jitted ``(queries, vectors, ids)`` program as
    jit ARGUMENTS; ``.jitted`` stays reachable for lowering inspection and
    compile-cache accounting (``tests/test_serve_frontend.py``, bench)."""

    def retrieve(queries):
        return jitted(queries, corpus.vectors, corpus.ids)

    retrieve.jitted = jitted
    retrieve.corpus = corpus
    return retrieve


def retrieval_reference(
    queries, corpus: Corpus, *, top_k: int = 100
) -> tuple[jax.Array, jax.Array]:
    """Single-device exact reference: full matmul + STABLE argsort (ties ->
    lowest corpus position, the same preference ``lax.top_k`` encodes).
    The bitwise yardstick for :func:`make_retrieval` — ids AND f32 scores."""
    vectors = jnp.asarray(jax.device_get(corpus.vectors))[:corpus.n_items]
    ids = jnp.asarray(jax.device_get(corpus.ids))[:corpus.n_items]
    scores = mips_scores(jnp.asarray(queries), vectors)  # [B, N]
    order = jnp.argsort(-scores, axis=-1, stable=True)[:, :top_k]
    return jnp.take_along_axis(scores, order, axis=1), jnp.take(ids, order)
