// Native data-plane core for tdfo_tpu — built as a plain C ABI shared
// library (ctypes-loaded; this image has no pybind11).
//
// The reference delegates its native data plane to TensorFlow's C++ runtime
// (TFRecord framing + gzip + tf.data, tensorflow2/data.py:108-210) and to
// torch's pinned-memory DataLoader workers.  This library provides the
// equivalents the Python layer needs without those runtimes:
//
//   * crc32c (Castagnoli, slicing-by-8) — the TFRecord integrity checksum.
//   * TFRecord frame reader/writer — the on-disk format:
//       u64le length | u32le masked_crc(length) | payload | u32le masked_crc(payload)
//   * in-place Fisher-Yates shuffle of fixed-stride rows (splitmix64 PRNG) —
//     the shuffle-buffer permutation without numpy's gather copy.
//
// Everything is exception-free, allocates nothing it does not free, and
// reports errors by return code (0 = ok).

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------- crc32c

static uint32_t kCrcTable[8][256];
static bool crc_init_done = false;

static void crc_init() {
  if (crc_init_done) return;
  const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  crc_init_done = true;
}

uint32_t tdfo_crc32c(const uint8_t* data, uint64_t n) {
  crc_init();
  uint32_t crc = 0xffffffffu;
  // slicing-by-8 over aligned middle
  while (n >= 8) {
    crc ^= (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
           ((uint32_t)data[2] << 16) | ((uint32_t)data[3] << 24);
    uint32_t hi = (uint32_t)data[4] | ((uint32_t)data[5] << 8) |
                  ((uint32_t)data[6] << 16) | ((uint32_t)data[7] << 24);
    crc = kCrcTable[7][crc & 0xff] ^ kCrcTable[6][(crc >> 8) & 0xff] ^
          kCrcTable[5][(crc >> 16) & 0xff] ^ kCrcTable[4][(crc >> 24) & 0xff] ^
          kCrcTable[3][hi & 0xff] ^ kCrcTable[2][(hi >> 8) & 0xff] ^
          kCrcTable[1][(hi >> 16) & 0xff] ^ kCrcTable[0][(hi >> 24) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return crc ^ 0xffffffffu;
}

// TFRecord "masked" crc: rotate right 15 + magic
uint32_t tdfo_masked_crc32c(const uint8_t* data, uint64_t n) {
  uint32_t crc = tdfo_crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// ------------------------------------------------------------- tfrecord IO
//
// Files are zlib gzFile streams: mode "wb" writes gzip (the reference's
// writer options, tensorflow2/data.py:114-116), "wbT" writes transparent
// (uncompressed), and reads auto-detect either via gzread.  This makes the
// native path cover the PRODUCTION format — the python gzip module never
// enters the hot loop.

void* tdfo_file_open(const char* path, const char* mode) {
  return (void*)gzopen(path, mode);
}

int tdfo_file_close(void* f) { return gzclose((gzFile)f); }

// gzwrite/gzread take 32-bit lengths: chunk so multi-GiB payloads never
// truncate silently.
static int gz_write_all(gzFile f, const uint8_t* p, uint64_t n) {
  const unsigned kChunk = 1u << 30;
  while (n) {
    unsigned take = n > kChunk ? kChunk : (unsigned)n;
    if (gzwrite(f, p, take) != (int)take) return 1;
    p += take;
    n -= take;
  }
  return 0;
}

static int gz_read_all(gzFile f, uint8_t* p, uint64_t n) {
  const unsigned kChunk = 1u << 30;
  while (n) {
    unsigned take = n > kChunk ? kChunk : (unsigned)n;
    if (gzread(f, p, take) != (int)take) return 1;
    p += take;
    n -= take;
  }
  return 0;
}

int tdfo_tfrecord_write(void* fv, const uint8_t* payload, uint64_t n) {
  gzFile f = (gzFile)fv;
  uint8_t hdr[12];
  memcpy(hdr, &n, 8);
  uint32_t len_crc = tdfo_masked_crc32c(hdr, 8);
  memcpy(hdr + 8, &len_crc, 4);
  if (gzwrite(f, hdr, 12) != 12) return 1;
  if (n && gz_write_all(f, payload, n) != 0) return 2;
  uint32_t data_crc = tdfo_masked_crc32c(payload, n);
  if (gzwrite(f, &data_crc, 4) != 4) return 3;
  return 0;
}

// One call per SHARD: write n_records framed records; record i occupies
// buf[offsets[i] .. offsets[i+1]).  Returns 0 on success, else the 1-based
// index of the failing record.
int64_t tdfo_tfrecord_write_batch(void* fv, const uint8_t* buf,
                                  const uint64_t* offsets, uint64_t n_records) {
  for (uint64_t i = 0; i < n_records; i++) {
    uint64_t n = offsets[i + 1] - offsets[i];
    if (tdfo_tfrecord_write(fv, buf + offsets[i], n) != 0) return (int64_t)(i + 1);
  }
  return 0;
}

// Read the next record's length (verifying the length crc).  Returns 0 and
// sets *len on success, 1 on clean EOF, negative on corruption.
int tdfo_tfrecord_next_len(void* fv, uint64_t* len) {
  gzFile f = (gzFile)fv;
  uint8_t hdr[12];
  int got = gzread(f, hdr, 12);
  if (got == 0) return 1;  // EOF
  if (got != 12) return -1;
  uint64_t n;
  memcpy(&n, hdr, 8);
  uint32_t crc_stored;
  memcpy(&crc_stored, hdr + 8, 4);
  if (tdfo_masked_crc32c(hdr, 8) != crc_stored) return -2;
  *len = n;
  return 0;
}

// Read payload of a record whose length was just returned; verifies data crc.
int tdfo_tfrecord_read_payload(void* fv, uint8_t* out, uint64_t n) {
  gzFile f = (gzFile)fv;
  if (gz_read_all(f, out, n) != 0) return -1;
  uint32_t crc_stored;
  if (gzread(f, &crc_stored, 4) != 4) return -2;
  if (tdfo_masked_crc32c(out, n) != crc_stored) return -3;
  return 0;
}

// ------------------------------------------------------- row-block shuffle

static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// In-place Fisher-Yates over n_rows rows of `stride` bytes each.
void tdfo_shuffle_rows(uint8_t* data, uint64_t n_rows, uint64_t stride,
                       uint64_t seed) {
  if (n_rows < 2) return;
  uint64_t s = seed ? seed : 1;
  // swap buffer on stack for small strides, heap otherwise
  uint8_t small[512];
  uint8_t* tmp = stride <= sizeof(small) ? small : new uint8_t[stride];
  for (uint64_t i = n_rows - 1; i > 0; i--) {
    uint64_t j = splitmix64(&s) % (i + 1);
    if (j != i) {
      memcpy(tmp, data + i * stride, stride);
      memcpy(data + i * stride, data + j * stride, stride);
      memcpy(data + j * stride, tmp, stride);
    }
  }
  if (tmp != small) delete[] tmp;
}

}  // extern "C"
