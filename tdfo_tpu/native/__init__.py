"""Native (C++) data-plane loader — builds on demand, ctypes ABI.

The library is compiled lazily with g++ the first time it is needed and
cached under ``native/build/``; a missing toolchain degrades gracefully
(``load_native()`` returns None and callers use their pure-Python paths).
This mirrors how the reference leans on prebuilt native wheels (fbgemm, TF's
C++ runtime — SURVEY.md §2.2) without requiring any here.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

__all__ = ["load_native", "native_available"]

_SRC = Path(__file__).parent / "tdfo_native.cc"
_BUILD_DIR = Path(__file__).parent / "build"
_LIB_PATH = _BUILD_DIR / "libtdfo_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    _BUILD_DIR.mkdir(exist_ok=True)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", str(_LIB_PATH),
        str(_SRC), "-lz",
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.tdfo_crc32c.argtypes = [u8p, ctypes.c_uint64]
    lib.tdfo_crc32c.restype = ctypes.c_uint32
    lib.tdfo_masked_crc32c.argtypes = [u8p, ctypes.c_uint64]
    lib.tdfo_masked_crc32c.restype = ctypes.c_uint32
    lib.tdfo_file_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.tdfo_file_open.restype = ctypes.c_void_p
    lib.tdfo_file_close.argtypes = [ctypes.c_void_p]
    lib.tdfo_tfrecord_write.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    lib.tdfo_tfrecord_write_batch.argtypes = [
        ctypes.c_void_p, u8p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
    ]
    lib.tdfo_tfrecord_write_batch.restype = ctypes.c_int64
    lib.tdfo_tfrecord_next_len.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.tdfo_tfrecord_read_payload.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    lib.tdfo_shuffle_rows.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
    lib.tdfo_shuffle_rows.restype = None
    return lib


def load_native() -> ctypes.CDLL | None:
    """The shared library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            _lib = _configure(ctypes.CDLL(str(_LIB_PATH)))
        except OSError:
            _lib = None
        return _lib


def native_available() -> bool:
    return load_native() is not None
