"""Measured v5e step-cost table — docs/BUDGET.md as an executable model.

Every constant in this module is a per-descriptor cost fitted to the
chain-differenced IN-SITU ablations in ``docs/BUDGET.md`` (the cumulative
piece tables measured on the real chip, NOT isolated-op microbenchmarks —
the fat-line kernel measured 3x slower in situ than isolated, so isolated
numbers are banned here).  This is the single sanctioned home for numeric
cost constants: ``tests/test_quality.py`` rejects ``*_NS``/``*_US``/``*_MS``
constants anywhere else in the tree, so the measured numbers cannot fork.

Calibration contract (``tests/test_planner.py``): :func:`estimate_step_ms`
must reproduce BOTH BUDGET.md in-situ step budgets with the correct
plain-vs-fused ordering —

  * DLRM-Criteo (26 tables, 33.76M rows, d=16, B=8192, rowwise-adagrad,
    213k ids -> 102k touched rows -> 77k touched lines): plain-scatter
    22.4 ms, fused fat-line 29-32 ms (plain must win);
  * TwoTower DMP (7 tables, ~2.4M rows, d=64, B=8192, adam, ~8k touched
    rows): fused 1.40 ms, plain ~2.8 ms (fused must win).

The model is deliberately descriptor-count-based: BUDGET.md's core finding
is that sparse steps on v5e (no SparseCore) bottom out at per-descriptor
issue costs, not bandwidth — the roofline "floor" is meaningless there.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

__all__ = [
    "TableLoad",
    "FULL_SLOT_BUFFERS",
    "SCATTER_BUFFERS",
    "DEDUPE_NS_PER_ID",
    "ROW_GATHER_BASE_NS",
    "EXPAND_NS_PER_ID",
    "SEGSUM_NS_PER_TARGET",
    "SCATTER_NS_PER_SLOT_PER_BUFFER",
    "CACHE_SCATTER_NS_PER_SLOT_PER_BUFFER",
    "CACHE_ROUTE_NS_PER_ID",
    "RESHAPE_MS_PER_GB",
    "LINE_GATHER_BASE_NS",
    "LINE_DMA_BASE_NS_PER_DIR",
    "A2A_US_PER_TABLE",
    "DENSE_STEP_MS_AT_B8192",
    "in_situ_multiplier",
    "line_geometry",
    "expected_lines",
    "one_hot_update_ms",
    "dense_step_ms",
    "padded_lane_width",
    "table_hbm_bytes",
    "cache_hbm_bytes",
    "estimate_step_ms",
]


# --------------------------------------------------------------------------
# per-descriptor constants (ns), fitted to the BUDGET.md cumulative ablations
# --------------------------------------------------------------------------

# dedupe_ids 2-sort formulation: 0.6 ms for 213k ids (BUDGET.md Criteo row
# "dedupe sort (213k ids -> 102k slots)"); the 16k-scale measurement is
# 0.24 ms (CLAUDE.md), i.e. the cost is ~linear in the id count.
DEDUPE_NS_PER_ID = 2.8

# compact row gather, IN SITU at the Criteo scale: ~3.9 ms for 102k
# scattered 64 B rows from a 2.2 GB stack (BUDGET.md "+ compact row
# gather", the ~40 ns/row multi-GB floor).  The BASE here is the
# small-touch-count rate (~60-90 us for 8192 rows, CLAUDE.md); the in-situ
# multiplier below ramps it to the measured large-touch-count floor:
# 13.3 * 3.0 = ~40 ns/row at >= 65k step touches.
ROW_GATHER_BASE_NS = 13.3

# expand compact rows to [B, d]: ~1.0 ms for 213k gathers from the compact
# 6.5 MB block (BUDGET.md "+ expand to [B, d]", ~4 ns/row — cache-resident
# source, so no in-situ ramp applies).
EXPAND_NS_PER_ID = 4.7

# row segment-sum: cost scales with the TARGET segment count at fixed
# input (CLAUDE.md: 213k -> [102k, 16] ~4 ms, -> [310k, 16] ~10 ms;
# BUDGET.md Criteo row says ~4.5 ms).  39 ns/target reproduces the 4 ms
# fact; sorted/cumsum/one-hot alternatives all measured slower.
SEGSUM_NS_PER_TARGET = 39.0

# XLA scatter serialization floor: ~60-110 ns per touched slot per
# scattered buffer (BUDGET.md "+ table scatter + accum scatter": ~11 ms
# for 102k rows x 2 buffers under rowwise-adagrad).  54 * 2 buffers
# lands the measured 11 ms at the Criteo profile.
SCATTER_NS_PER_SLOT_PER_BUFFER = 54.0

# update-cache scatters target the small [C, d] cache arrays (MBs, not
# GBs) — BUDGET.md's cache_zipf section brackets them 0.05-0.5 ms for ~3k
# rows x 2 buffers (8-80 ns/slot/buffer, the open question being whether
# a cache-resident target beats the multi-GB floor).  27 = half the
# big-table floor is the bracket's middle; the planner only reaches for
# it on int8 plans, where the eager path's extra sidecar buffer and
# requantize RMW shift the break-even structurally (module docstring of
# plan/planner.py records the stance).
CACHE_SCATTER_NS_PER_SLOT_PER_BUFFER = 27.0

# cache directory route: `searchsorted method="sort"` of the deduped ids
# into the [C] sorted directory + the admission pair-sorts (BUDGET.md
# cache_zipf "directory route" + "admission" rows: ~0.15-0.3 ms for 8k
# ids into 131k).
CACHE_ROUTE_NS_PER_ID = 25.0

# a trailing-dim retiling reshape MATERIALIZES the array on TPU:
# [L, 1, 128] -> [L*4, 32] of a 4.3 GB table measured ~10 ms/step
# (CLAUDE.md).  The int8 fat update goes through exactly that [L*R, W]
# byte view (ops/sparse._fat_apply_rows_int8) and pays it twice (view +
# write-back), so big fused-int8 tables carry a bytes-proportional term
# no descriptor count captures.
RESHAPE_MS_PER_GB = 2.3

# fat-line forward gather, IN SITU: ~10 ms for 77k x 512 B lines
# (BUDGET.md fused ablation "forward line gather + slot select" — the
# 512 B line granularity taxes the forward vs 64 B plain rows).  Base is
# the small-scale line-gather rate (~0.4 ms for ~8k 1 KB lines, BUDGET.md
# TwoTower "7 lookups" row); 45 * 3.0 = 135 ns/line at the Criteo scale.
LINE_GATHER_BASE_NS = 45.0

# in-place DMA update kernel: ~80-90 ns/line/direction IN SITU (BUDGET.md
# fused ablation "fused update kernel": ~14 ms for 77k lines read+write;
# the isolated 17-35 ns/row figure does NOT hold at that scale).  Base is
# the small-scale rate (TwoTower kernel ~0.5 ms for ~8k lines both
# directions); 30 * 2 dirs * 3.0 = 180 ns/line at the Criteo scale.
LINE_DMA_BASE_NS_PER_DIR = 30.0

# all-to-all launch allowance per sharded table per step (2 collectives
# per direction): the single-chip bench (bench.py alltoall_per_table8)
# measures PROGRAM OVERHEAD only and multichip ICI is unmeasured
# (BUDGET.md grouped-exchange section), so this is a nominal launch cost,
# not a measured ICI number — it exists so replication wins tiny tables
# (no exchange) while row sharding wins big ones (descriptor work / n).
A2A_US_PER_TABLE = 20.0

# one-hot MXU segment-sum update for a replicated hot head / small table:
# ~100-350 us for vocabs 5k-16k (CLAUDE.md; XLA fuses the one-hot away).
# Modeled linear in the head size over that range with a floor — the
# CEILING end of BUDGET.md's hot/cold expected-budget table, because the
# per-table updates serialize in situ (the fat-line 3x lesson).
ONE_HOT_BASE_US = 100.0
ONE_HOT_BASE_VOCAB = 5000
ONE_HOT_US_PER_ROW = (350.0 - 100.0) / (16384 - 5000)
ONE_HOT_FLOOR_US = 50.0

# dense fwd+bwd anchors at B=8192, bf16 MXU (BUDGET.md "+ model fwd+bwd"
# rows): DLRM bottom+top MLPs 1.5 ms, TwoTower towers 0.3 ms.  Scaled
# linearly in batch (MXU-bound at these widths).
DENSE_STEP_MS_AT_B8192 = {"dlrm": 1.5, "twotower": 0.3}

# in-situ descriptor-cost ramp: isolated/small-step descriptor rates hold
# up to ~16k touches per step; at the Criteo scale (~100k touches) every
# scattered-descriptor cost measured ~3x its small-scale rate (BUDGET.md
# fused-ablation finding: "the 17-35 ns/row figure from small-scale
# isolated runs does not hold at 77k lines"; custom calls serialize
# against the step).  Linear ramp between the two measured regimes,
# keyed on the STEP's total per-device touched rows — contention is a
# whole-step property, not a per-table one.
IN_SITU_RAMP_START = 16384
IN_SITU_RAMP_FULL = 65536
IN_SITU_MAX = 3.0

# optimizer state geometry (ops/sparse.py kinds): full table-shaped slot
# buffers, and the number of scattered buffers a plain update touches
# (table itself + full slots + the rowwise [V] accumulator cell-scatter).
FULL_SLOT_BUFFERS = {"sgd": 0, "adagrad": 1, "rowwise_adagrad": 0, "adam": 2}
SCATTER_BUFFERS = {"sgd": 1, "adagrad": 2, "rowwise_adagrad": 2, "adam": 3}


@dataclass(frozen=True)
class TableLoad:
    """One table's traffic + placement, as the estimator consumes it.

    ``ids_per_batch``/``unique_rows`` come from the ``table_stats.json``
    artifact (analytic estimates from preprocessing counts, optionally
    replaced by observed telemetry counters — ``plan/stats.py``).
    ``unique_lines`` is the observed fat-line touch count when telemetry
    recorded one; ``None`` falls back to the occupancy estimate
    (:func:`expected_lines`).  ``hot_mass`` is the lookup-mass fraction a
    ``hot_k``-row hot head absorbs (stats head-mass curve).
    ``flush_unique_rows`` is E[distinct rows touched across one
    ``cache_flush_every``-step interval] (``plan/stats.unique_rows_over``)
    — only read when the estimator prices the update cache; ``None``
    falls back to the no-reuse pessimum (``unique_rows`` per step, i.e.
    the cache never wins)."""

    name: str
    vocab: int
    dim: int
    ids_per_batch: float
    unique_rows: float
    unique_lines: float | None = None
    sharding: str = "row"  # "row" | "replicated" | "table"
    fused: bool = False
    dtype: str = "float32"
    hot_k: int = 0
    hot_mass: float = 0.0
    flush_unique_rows: float | None = None


def in_situ_multiplier(total_unique_rows: float) -> float:
    """Descriptor-cost multiplier for a step touching this many rows."""
    if total_unique_rows <= IN_SITU_RAMP_START:
        return 1.0
    if total_unique_rows >= IN_SITU_RAMP_FULL:
        return IN_SITU_MAX
    frac = (total_unique_rows - IN_SITU_RAMP_START) / (
        IN_SITU_RAMP_FULL - IN_SITU_RAMP_START)
    return 1.0 + (IN_SITU_MAX - 1.0) * frac


def _next_pow2(x: int) -> int:
    return 1 << max(0, (int(x) - 1).bit_length())


def line_geometry(dim: int, optimizer: str, dtype: str) -> tuple[int, int]:
    """Fat-line packing of one vocab row: ``(line_elems, rows_per_line)``.

    Mirrors ``ops/pallas_kernels.line_layout``: a row carries
    ``dim * (1 + full_slots)`` elements (+1 for the rowwise accumulator),
    padded to a power of two; rows pack into 128-lane f32 lines (256
    elements for bf16 — half the bytes per element, same 512 B line).

    ``dtype == "int8"`` is the BYTE-container line (elements are bytes):
    ``dim`` code bytes + 8 sidecar bytes (bitcast f32 scale, offset) + 4
    bytes per f32 state lane, padded to the next slot width from
    (8, 16, 32, 64, 128) or up to whole 128-byte tiles.  rowwise_adagrad
    is refused here exactly as ``ops/pallas_kernels.line_layout`` refuses
    it: its shared scalar accumulator has no per-row byte-container home.
    """
    if dtype == "int8":
        if optimizer == "rowwise_adagrad":
            raise ValueError(
                "fused int8 storage does not support rowwise_adagrad: the "
                "rowwise accumulator is a shared scalar per row with no "
                "byte-container slot in the fat line — keep the table on "
                "plain int8 storage (optionally cache-fronted) or switch "
                "the optimizer")
        need = dim + 8 + 4 * dim * FULL_SLOT_BUFFERS[optimizer]
        width = next((s for s in (8, 16, 32, 64, 128) if s >= need),
                     128 * math.ceil(need / 128))
        return width, max(1, 128 // width)
    elems = dim * (1 + FULL_SLOT_BUFFERS[optimizer])
    if optimizer == "rowwise_adagrad":
        elems += 1
    width = _next_pow2(elems)
    lane_elems = 128 if dtype == "float32" else 256
    rows_per_line = max(1, lane_elems // width)
    return width, rows_per_line


def expected_lines(unique_rows: float, vocab: int, rows_per_line: int) -> float:
    """Occupancy estimate of touched lines: ``unique_rows`` rows drawn over
    ``ceil(vocab / R)`` lines touch ``L * (1 - (1 - 1/L)^u)`` of them —
    saturated small tables compress ~R-fold, sparse big tables barely."""
    if unique_rows <= 0:
        return 0.0
    n_lines = math.ceil(vocab / max(1, rows_per_line))
    if n_lines <= 1:
        return 1.0
    return n_lines * -math.expm1(unique_rows * math.log1p(-1.0 / n_lines))


def one_hot_update_ms(hot_rows: int) -> float:
    """One replicated hot head's scatter-free one-hot MXU update."""
    us = ONE_HOT_BASE_US + (hot_rows - ONE_HOT_BASE_VOCAB) * ONE_HOT_US_PER_ROW
    return max(ONE_HOT_FLOOR_US, us) / 1000.0


def dense_step_ms(dense_model: str, batch_size: int) -> float:
    """Dense backbone fwd+bwd, scaled from the measured B=8192 anchors."""
    if dense_model not in DENSE_STEP_MS_AT_B8192:
        raise ValueError(f"no dense anchor for model {dense_model!r}")
    return DENSE_STEP_MS_AT_B8192[dense_model] * (batch_size / 8192.0)


# --------------------------------------------------------------------------
# HBM model (per-device bytes, undivided — the planner applies sharding)
# --------------------------------------------------------------------------

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "int8": 1}


def padded_lane_width(dim: int) -> int:
    """XLA's allocated trailing width: narrow dims (8/16) get narrow
    tiles, everything else lane-pads to a 128 multiple — a [V, 64] table
    allocates 2x its logical bytes (CLAUDE.md measured fact; same 2x for
    bf16, which is why bf16 saves exactly half, not more)."""
    if dim <= 16:
        return dim
    return 128 * math.ceil(dim / 128)


def table_hbm_bytes(
    vocab: int,
    dim: int,
    *,
    optimizer: str,
    dtype: str = "float32",
    slot_dtype: str = "float32",
    fused: bool = False,
    hot_k: int = 0,
) -> int:
    """Allocated bytes of one table + its optimizer state (whole table,
    before any sharding division).  ``hot_k`` adds the replicated dense
    head (always f32 + dense slot buffers — the head is small).

    int8 adds the per-row f32 (scale, offset) sidecar (8 B/row) and keeps
    the slot buffers at ``slot_dtype`` — so at NARROW dims the ratio vs
    f32 is bounded well under 4x (d=16 sgd: 64 B -> 16 + 8 = 24 B, 2.67x),
    while lane-padded dims approach it (d=64 sgd: 512 B -> 128 + 8 = 136 B,
    3.76x; the int8 codes lane-pad 128-wide exactly like f32).  Fused int8
    packs codes + sidecar + f32-byte state into the byte-container line
    (``line_geometry``), so slot-width padding can make it LARGER than
    plain int8 at some (dim, optimizer) — the planner prices both."""
    dsize = _DTYPE_BYTES[dtype]
    if fused:
        # int8 fat lines are byte containers: the (scale, offset) sidecar
        # and the f32-byte optimizer state ride IN-LINE, so the line
        # geometry already prices them (no separate sidecar/slot terms)
        width, rows_per_line = line_geometry(dim, optimizer, dtype)
        lane_elems = 256 if dtype == "bfloat16" else 128
        if rows_per_line > 1:
            body = math.ceil(vocab / rows_per_line) * lane_elems * dsize
        else:
            body = vocab * width * dsize
    else:
        padded = padded_lane_width(dim)
        body = vocab * padded * dsize
        body += FULL_SLOT_BUFFERS[optimizer] * vocab * padded * _DTYPE_BYTES[slot_dtype]
        if optimizer == "rowwise_adagrad":
            body += vocab * 4  # the EXACT_ROWWISE_ADAGRAD f32 accumulator
        if dtype == "int8":
            body += vocab * 2 * 4  # f32 (scale, offset) per row
    if hot_k > 0:
        k = min(hot_k, vocab)
        head = k * padded_lane_width(dim) * 4 * (1 + FULL_SLOT_BUFFERS[optimizer])
        if optimizer == "rowwise_adagrad":
            head += k * 4
        body += head
    return int(body)


def cache_hbm_bytes(
    dim: int,
    *,
    optimizer: str,
    dtype: str = "float32",
    cache_rows: int,
) -> int:
    """Replicated per-device bytes of ONE update cache
    (``ops/sparse.cache_init``): ``cache_rows`` rows at the table dtype,
    the f32 slot mirrors, the int8 (scale, offset) mirror, the rowwise
    accumulator cell, plus ~16 B/row of int32 directory bookkeeping
    (sorted ids + permutation + age/dirty).  Stacked arrays share a cache,
    so the planner charges one per plain storage GROUP."""
    c = int(cache_rows)
    if c <= 0:
        return 0
    padded = padded_lane_width(dim)
    row = padded * _DTYPE_BYTES[dtype]
    row += FULL_SLOT_BUFFERS[optimizer] * padded * 4
    if optimizer == "rowwise_adagrad":
        row += 4
    if dtype == "int8":
        row += 8
    row += 16
    return c * row


# --------------------------------------------------------------------------
# step-cost estimator
# --------------------------------------------------------------------------


def estimate_step_ms(
    loads: list[TableLoad],
    *,
    optimizer: str,
    dense_model: str,
    batch_size: int,
    n_devices: int = 1,
    cache_flush_every: int | None = None,
) -> dict:
    """Predicted per-device train-step milliseconds for a set of placed
    tables, assuming the measured-fastest formulation of each path:

      * plain tables stack per (dim, dtype, sharding) and run the
        dedup_lookup pipeline — one dedupe sort, compact row gather,
        expand, row segment-sum, then one scatter per optimizer buffer
        (the 22.4 ms Criteo formulation);
      * fused tables stack into fat-line arrays per (dim, dtype,
        sharding) — dedupe, line gather, segment-sum, in-place DMA kernel
        (the 1.40 ms TwoTower formulation).  Fused INT8 arrays update in
        ROW space instead (``ops/sparse._fat_apply_rows_int8``: byte-row
        gather + one packed scatter through the ``[L*R, W]`` view), so
        they pay row-gather + single-buffer-scatter descriptor costs plus
        the view's retiling materialization (``RESHAPE_MS_PER_GB``);
      * plain int8 tables pay one EXTRA scatter buffer (the f32
        (scale, offset) sidecar written alongside the requantized codes);
      * ``cache_flush_every`` (when not ``None``) prices every plain
        group as cache-fronted (``[embeddings] cache_rows``): per-step
        scatters move to the cache-resident arrays
        (``CACHE_SCATTER_NS_PER_SLOT_PER_BUFFER``), the deduped ids pay
        the directory route, and the big-table write-back (admission
        gather + coalesced flush scatter of the interval's
        ``flush_unique_rows``) amortizes over the interval.  Fused groups
        ignore it (the cache covers plain 2D arrays only —
        ``parallel/embedding.cached_array_names``);
      * a ``hot_k`` head removes ``hot_mass`` of the table's traffic from
        the scattered path and pays one one-hot MXU update per table
        (heads are per-table and serialize — BUDGET.md hot/cold table).

    Row-sharded groups divide descriptor counts by ``n_devices`` (balanced
    shards) and pay the a2a launch allowance; replicated and table-wise
    groups do full-count work per device / on the owner.  Returns a
    breakdown dict with ``total_ms``, ``dense_ms``, ``hot_ms`` and a
    ``per_table`` attribution (group costs split by touched-row share).
    """
    if optimizer not in SCATTER_BUFFERS:
        raise ValueError(f"unknown sparse optimizer {optimizer!r}")
    f_every = int(cache_flush_every) if cache_flush_every else 0
    cold: list[dict] = []
    hot_ms = 0.0
    per_table = {ld.name: 0.0 for ld in loads}
    for ld in loads:
        ids, uniq = float(ld.ids_per_batch), float(ld.unique_rows)
        lines = ld.unique_lines
        # interval working set for the cache write-back; absent stats fall
        # back to the no-reuse pessimum (flush == uniq per step amortized,
        # so the cache never looks like a win without an occupancy curve)
        flush = ld.flush_unique_rows
        if flush is None and f_every:
            flush = min(float(ld.vocab), uniq * f_every)
        if ld.hot_k > 0:
            k = min(ld.hot_k, ld.vocab)
            mass = 1.0 if ld.hot_k >= ld.vocab else min(1.0, max(0.0, ld.hot_mass))
            head_ms = one_hot_update_ms(k)
            hot_ms += head_ms
            per_table[ld.name] += head_ms
            ids *= 1.0 - mass
            uniq *= 1.0 - mass
            lines = None if lines is None else lines * (1.0 - mass)
            flush = None if flush is None else flush * (1.0 - mass)
        cold.append(dict(load=ld, ids=ids, uniq=uniq, lines=lines,
                         flush=flush))

    # the in-situ ramp keys on the step's total per-device touched rows
    def _div(ld: TableLoad) -> float:
        return float(n_devices) if ld.sharding == "row" else 1.0

    total_touched = sum(c["uniq"] / _div(c["load"]) for c in cold)
    m = in_situ_multiplier(total_touched)

    groups: dict[tuple, list[dict]] = {}
    for c in cold:
        ld = c["load"]
        key = (ld.fused, ld.dim, ld.dtype, ld.sharding)
        groups.setdefault(key, []).append(c)

    sparse_ms = 0.0
    a2a_ms = 0.0
    for (fused, dim, dtype, sharding), members in sorted(
            groups.items(), key=lambda kv: repr(kv[0])):
        div = float(n_devices) if sharding == "row" else 1.0
        ids = sum(c["ids"] for c in members)
        uniq = sum(c["uniq"] for c in members) / div
        if fused:
            width, rpl = line_geometry(dim, optimizer, dtype)
            lines = sum(
                c["lines"] if c["lines"] is not None else expected_lines(
                    c["uniq"], c["load"].vocab, rpl)
                for c in members) / div
            if dtype == "int8":
                # row-space int8 fat update (no DMA kernel): forward line
                # gather stays, the update pays byte-row gather + ONE
                # packed-row scatter through the [L*R, W] view — which
                # retiles, so the whole fat array materializes twice per
                # step (free only when the view is a unit-dim collapse,
                # i.e. one 128-byte-slot row per line)
                table_gb = sum(
                    table_hbm_bytes(c["load"].vocab, dim,
                                    optimizer=optimizer, dtype=dtype,
                                    fused=True)
                    for c in members) / div / float(1 << 30)
                reshape_ms = (0.0 if (rpl == 1 and width == 128)
                              else 2.0 * RESHAPE_MS_PER_GB * table_gb)
                group_ms = (
                    ids * DEDUPE_NS_PER_ID
                    + lines * LINE_GATHER_BASE_NS * m
                    + uniq * SEGSUM_NS_PER_TARGET
                    + uniq * ROW_GATHER_BASE_NS * m
                    + uniq * SCATTER_NS_PER_SLOT_PER_BUFFER
                ) / 1e6 + reshape_ms
            else:
                group_ms = (
                    ids * DEDUPE_NS_PER_ID
                    + lines * LINE_GATHER_BASE_NS * m
                    + uniq * SEGSUM_NS_PER_TARGET
                    + lines * 2 * LINE_DMA_BASE_NS_PER_DIR * m
                ) / 1e6
        else:
            # plain int8 scatters the f32 (scale, offset) sidecar alongside
            # the requantized codes: one extra buffer
            buffers = SCATTER_BUFFERS[optimizer] + (1 if dtype == "int8"
                                                    else 0)
            common = (
                ids * DEDUPE_NS_PER_ID
                + uniq * ROW_GATHER_BASE_NS * m
                + ids * EXPAND_NS_PER_ID
                + uniq * SEGSUM_NS_PER_TARGET
            )
            if f_every:
                # cache-fronted: per-step scatters hit the small cache
                # arrays (incl. the int8 qs mirror — the per-step
                # requantize keeps bit-parity with the eager path), the
                # deduped ids pay the directory route, and the big-table
                # write-back (admission row gather + coalesced flush of
                # the interval's distinct rows) amortizes over the
                # interval
                flush_rows = sum(
                    min(c["flush"], float(c["load"].vocab))
                    for c in members) / div / float(f_every)
                group_ms = (
                    common
                    + uniq * CACHE_ROUTE_NS_PER_ID
                    + uniq * CACHE_SCATTER_NS_PER_SLOT_PER_BUFFER * buffers
                    + flush_rows * (ROW_GATHER_BASE_NS * m
                                    + SCATTER_NS_PER_SLOT_PER_BUFFER
                                    * buffers)
                ) / 1e6
            else:
                group_ms = (
                    common
                    # NO in-situ ramp on the scatter: the ~54 ns/slot floor
                    # IS the at-scale in-situ figure (BUDGET.md measured the
                    # 102k-row scatter in the full step; small-scale XLA
                    # scatters are ~170 ns/row, i.e. scatters do not get
                    # WORSE at scale)
                    + uniq * SCATTER_NS_PER_SLOT_PER_BUFFER * buffers
                ) / 1e6
        sparse_ms += group_ms
        if sharding in ("row", "table") and n_devices > 1:
            a2a_ms += len(members) * A2A_US_PER_TABLE / 1000.0
        g_uniq = sum(c["uniq"] for c in members)
        for c in members:
            share = (c["uniq"] / g_uniq) if g_uniq > 0 else 1.0 / len(members)
            per_table[c["load"].name] += group_ms * share

    dense = dense_step_ms(dense_model, batch_size)
    return {
        "total_ms": dense + sparse_ms + hot_ms + a2a_ms,
        "dense_ms": dense,
        "sparse_ms": sparse_ms,
        "hot_ms": hot_ms,
        "a2a_ms": a2a_ms,
        "in_situ_multiplier": m,
        "per_table": per_table,
    }
