"""Cost-model-driven auto-sharding planner (torchrec
``EmbeddingShardingPlanner``/``EmbeddingEnumerator`` parity).

Enumerates per-table placement decisions — replicated / row-sharded /
table-wise, fused fat-line vs plain storage, f32 vs bf16 table dtype, and
hot-split size — prices every candidate with the measured v5e cost model
(``plan/costs.py``) against the table's traffic stats
(``plan/stats.py``), and greedily picks the plan minimizing predicted
per-device step time, optionally under a device HBM budget.  The result
is a versioned, deterministic ``sharding_plan.json`` the trainer consumes
as per-table spec overrides (``train/trainer.py``) and stamps into
checkpoints (the ``hot_ids_digest`` idiom).

Decision search: path choices couple through the step-level in-situ
descriptor ramp and through stacking (a table's scatter rides its
group's), so per-table independent pricing would mis-order plain vs fused
at exactly the Criteo profile the model is calibrated on.  The planner
instead runs coordinate descent over FULL-plan estimates: sweep tables in
deterministic order, re-pricing the whole step for each candidate, until
a sweep changes nothing.  Tables are few (dozens) and the estimator is
O(tables), so this is milliseconds of host work.

Deliberately conservative stances (all provenanced in docs/BUDGET.md):

  * bf16 storage is priced step-time-NEUTRAL — the fat-line bf16 ablation
    was never chip-measured (tunnel outage; BUDGET.md quantized-storage
    section records the expected ~1.7x as UNMEASURED), so dtype is chosen
    only as an HBM lever (it halves allocated bytes — that part IS
    measured) during budget demotion, never on predicted speed.
  * the update cache is considered ONLY for plans that carry plain int8
    storage.  For f32/bf16 the stance stays at the pessimistic end of
    BUDGET.md's cache_zipf expectation (break-even-to-loss: the cache
    moves scatters, it does not remove them), so pure-float plans keep
    emitting ``cache_rows: 0`` and an operator opts in by hand after
    measuring.  Plain int8 shifts the break-even structurally — the
    eager path pays an EXTRA sidecar scatter buffer plus a per-step
    requantize read-modify-write on the multi-GB table — so the
    post-pass prices the cache-fronted step at the bracket middle
    (``costs.CACHE_SCATTER_NS_PER_SLOT_PER_BUFFER``) with the honest
    flush cost (the interval working set from the stats occupancy
    curve), and emits ``cache_rows > 0`` IFF the model predicts a win
    AND the caches fit the HBM budget.  On no-reuse (uniform) traffic
    the working set equals ``flush_every x uniq`` and the cache correctly
    never wins; it takes Zipf-style reuse to tip it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from pathlib import Path
from typing import Mapping

from tdfo_tpu.plan.costs import (
    TableLoad,
    cache_hbm_bytes,
    estimate_step_ms,
    table_hbm_bytes,
)
from tdfo_tpu.plan.stats import (
    HEAD_IDS_CAP,
    HEAD_K_GRID,
    head_ids_for,
    head_mass_at,
    table_stats_digest,
    unique_lines_at,
    unique_rows_at,
    unique_rows_over,
)

__all__ = [
    "FORMAT_VERSION",
    "PLAN_FILENAME",
    "FUSED_MIN_VOCAB",
    "CACHE_FLUSH_EVERY",
    "plan_tables",
    "write_plan",
    "load_plan",
    "plan_digest",
    "format_plan",
    "apply_plan_to_specs",
]

# Plan schema version; bump on incompatible layout changes.
FORMAT_VERSION = 1

PLAN_FILENAME = "sharding_plan.json"

# Fat-line storage is only enumerated above this vocab — mirrors the
# config default ``fused_table_threshold`` (small tables ride the one-hot
# MXU tier / plain stacks; fat packing them was never measured).
FUSED_MIN_VOCAB = 16384

# Flush cadence a cache-carrying plan prices and emits — the
# ``[embeddings] flush_every`` config default, so a plan-driven cache
# behaves exactly like the hand-set knob it replaces.
CACHE_FLUSH_EVERY = 64

_SHARDINGS = ("row", "replicated", "table")
_DTYPES = ("float32", "bfloat16", "int8")


@dataclasses.dataclass(frozen=True)
class _Candidate:
    sharding: str
    fused: bool
    dtype: str
    hot_k: int  # effective head size (<= vocab); 0 = no split


def _candidates(name: str, entry: dict, optimizer: str,
                n_devices: int) -> list[_Candidate]:
    """Deterministic candidate order per table; index in this list is the
    final tie-break, so defaults (row, plain, f32, no hot) come first."""
    vocab = int(entry["vocab"])
    out = []
    shardings = _SHARDINGS if n_devices > 1 else ("row", "replicated")
    hot_ks = [0]
    for k in HEAD_K_GRID:
        k_eff = min(k, vocab)
        # the plan embeds the head's exact id set, so the stats head must
        # cover it; fully-hot tables need the whole vocab enumerated
        if k_eff not in hot_ks and len(entry["head_ids"]) >= k_eff:
            hot_ks.append(k_eff)
    for sharding in shardings:
        for fused in (False, True):
            if fused and (vocab <= FUSED_MIN_VOCAB
                          or sharding not in ("row", "replicated")):
                continue
            for dtype in _DTYPES:
                if fused and dtype != "float32" \
                        and optimizer == "rowwise_adagrad":
                    # the fat line packs the accumulator at the table
                    # dtype (bf16, PR 5) or cannot carry it at all (int8:
                    # the f32 per-row accumulator contract cannot ride a
                    # quantized line); EXACT_ROWWISE_ADAGRAD requires f32
                    # accum (refused at collection construction)
                    continue
                for hot_k in hot_ks:
                    if hot_k > 0 and (
                            fused or sharding not in ("row", "replicated")):
                        # hot heads require a plain, row/replicated base
                        # table (parallel/embedding.py hot_ids contract);
                        # int8 composes — the head stays f32, only the
                        # cold residual stores codes
                        continue
                    out.append(_Candidate(sharding, fused, dtype, hot_k))
    return out


def _loads(names, stats, decisions, *, dim, batch_size, flush_steps=None):
    loads = []
    for name in names:
        entry = stats[name]
        d = decisions[name]
        loads.append(TableLoad(
            name=name,
            vocab=int(entry["vocab"]),
            dim=dim,
            ids_per_batch=float(batch_size),
            unique_rows=unique_rows_at(entry, batch_size),
            unique_lines=unique_lines_at(entry, batch_size) if d.fused
            else None,
            sharding=d.sharding,
            fused=d.fused,
            dtype=d.dtype,
            hot_k=d.hot_k,
            hot_mass=head_mass_at(entry, d.hot_k),
            flush_unique_rows=(
                unique_rows_over(entry, batch_size, flush_steps)
                if flush_steps else None),
        ))
    return loads


def _device_loads(names, stats, decisions, *, dim, optimizer, slot_dtype,
                  n_devices):
    """Per-device HBM bytes under the current decisions.  Table-wise
    tables go to the least-loaded device (greedy, biggest-first,
    deterministic) — the assignment is recomputed from scratch so it is a
    pure function of the decisions."""
    loads = [0] * n_devices
    tablewise = []
    for name in names:
        d = decisions[name]
        b = table_hbm_bytes(
            int(stats[name]["vocab"]), dim, optimizer=optimizer,
            dtype=d.dtype, slot_dtype=slot_dtype, fused=d.fused,
            hot_k=d.hot_k)
        if d.sharding == "row":
            per = math.ceil(b / n_devices)
            for i in range(n_devices):
                loads[i] += per
        elif d.sharding == "replicated":
            for i in range(n_devices):
                loads[i] += b
        else:
            tablewise.append((b, name))
    assignment = {}
    for b, name in sorted(tablewise, key=lambda t: (-t[0], t[1])):
        dev = min(range(n_devices), key=lambda i: (loads[i], i))
        loads[dev] += b
        assignment[name] = dev
    return loads, assignment


def plan_tables(
    stats: Mapping[str, dict],
    *,
    dim: int,
    batch_size: int,
    optimizer: str,
    dense_model: str,
    n_devices: int = 1,
    hbm_gb: float = 0.0,
    slot_dtype: str = "float32",
) -> dict:
    """Choose a placement for every table in ``stats`` and return the plan
    payload (see :func:`write_plan`).  ``hbm_gb`` > 0 bounds per-device
    allocated bytes; an unsatisfiable budget raises ``ValueError``."""
    if not stats:
        raise ValueError("table stats are empty — nothing to plan")
    names = sorted(stats)
    cands = {n: _candidates(n, stats[n], optimizer, n_devices)
             for n in names}

    def total_ms(decisions, cache=False):
        flush = CACHE_FLUSH_EVERY if cache else None
        return estimate_step_ms(
            _loads(names, stats, decisions, dim=dim, batch_size=batch_size,
                   flush_steps=flush),
            optimizer=optimizer, dense_model=dense_model,
            batch_size=batch_size, n_devices=n_devices,
            cache_flush_every=flush)

    # start at the config-default placement: row-sharded plain f32 —
    # candidate 0 by construction
    decisions = {n: cands[n][0] for n in names}
    best = total_ms(decisions)["total_ms"]

    # coordinate descent over full-plan estimates (see module docstring)
    for _sweep in range(16):
        changed = False
        for name in names:
            cur = decisions[name]
            pick, pick_ms = cur, best
            for cand in cands[name]:
                if cand == cur:
                    continue
                trial = dict(decisions)
                trial[name] = cand
                ms = total_ms(trial)["total_ms"]
                if ms < pick_ms - 1e-9:
                    pick, pick_ms = cand, ms
            if pick != cur:
                decisions[name] = pick
                best = pick_ms
                changed = True
        if not changed:
            break

    # HBM budget repair: while the fullest device overflows, apply the
    # candidate swap with the best predicted-cost-per-byte-saved ratio
    # (bytes saved measured on the fullest device)
    budget = int(hbm_gb * (1 << 30))
    if budget > 0:
        for _ in range(1000):
            loads, _assign = _device_loads(
                names, stats, decisions, dim=dim, optimizer=optimizer,
                slot_dtype=slot_dtype, n_devices=n_devices)
            over = max(loads)
            if over <= budget:
                break
            pick = None
            for name in names:
                cur = decisions[name]
                for idx, cand in enumerate(cands[name]):
                    if cand == cur:
                        continue
                    trial = dict(decisions)
                    trial[name] = cand
                    t_loads, _ = _device_loads(
                        names, stats, trial, dim=dim, optimizer=optimizer,
                        slot_dtype=slot_dtype, n_devices=n_devices)
                    saved = over - max(t_loads)
                    if saved <= 0:
                        continue
                    dms = total_ms(trial)["total_ms"] - best
                    key = (dms / saved, round(dms, 9), name, idx)
                    if pick is None or key < pick[0]:
                        pick = (key, name, cand,
                                total_ms(trial)["total_ms"])
            if pick is None:
                raise ValueError(
                    f"planner cannot fit the tables under {hbm_gb} GB per "
                    f"device (fullest device needs {over / (1 << 30):.2f} "
                    "GB and no candidate swap reduces it) — raise "
                    "planner.hbm_gb or add devices"
                )
            _, name, cand, best = pick
            decisions[name] = cand
        else:
            raise ValueError("planner HBM repair did not converge")

    # update-cache post-pass (module docstring): only a plan carrying
    # plain int8 storage considers the cache — its eager path pays the
    # sidecar scatter buffer + per-step requantize on the big table, which
    # is what the cache-fronted pricing can beat on reuse-heavy traffic
    use_cache, cache_rows, cache_bytes = False, 0, 0
    if any(d.dtype == "int8" and not d.fused for d in decisions.values()):
        # size the cache to the biggest plain storage GROUP's interval
        # working set (stacked arrays share one cache; directories are
        # replicated, so no device division), next power of two with 2x
        # slack so retention never overflows mid-interval
        group_ws: dict[tuple, float] = {}
        for name in names:
            d = decisions[name]
            if d.fused:
                continue
            ws = unique_rows_over(stats[name], batch_size,
                                  CACHE_FLUSH_EVERY)
            if d.hot_k > 0:
                ws *= 1.0 - head_mass_at(stats[name], d.hot_k)
            key = (d.dtype, d.sharding)
            group_ws[key] = group_ws.get(key, 0.0) + ws
        c = 1024
        while c < 2.0 * max(group_ws.values()) and c < (1 << 21):
            c *= 2
        c_bytes = sum(
            cache_hbm_bytes(dim, optimizer=optimizer, dtype=dt,
                            cache_rows=c)
            for dt, _sh in sorted(group_ws))
        t_loads, _ = _device_loads(
            names, stats, decisions, dim=dim, optimizer=optimizer,
            slot_dtype=slot_dtype, n_devices=n_devices)
        fits = budget <= 0 or max(t_loads) + c_bytes <= budget
        cached_ms = total_ms(decisions, cache=True)["total_ms"]
        if fits and cached_ms < best - 1e-9:
            use_cache, cache_rows, cache_bytes = True, c, c_bytes
            best = cached_ms

    final = total_ms(decisions, cache=use_cache)
    loads, assignment = _device_loads(
        names, stats, decisions, dim=dim, optimizer=optimizer,
        slot_dtype=slot_dtype, n_devices=n_devices)

    # the all-defaults baseline the CLI/bench compare against: what the
    # config defaults would build — row-sharded, fat-line storage above
    # the default fused_table_threshold, f32, no hot split
    defaults = {
        n: _Candidate("row", int(stats[n]["vocab"]) > FUSED_MIN_VOCAB,
                      "float32", 0)
        for n in names
    }
    default_ms = total_ms(defaults)["total_ms"]
    default_loads, _ = _device_loads(
        names, stats, defaults, dim=dim, optimizer=optimizer,
        slot_dtype=slot_dtype, n_devices=n_devices)

    tables = {}
    for name in names:
        d = decisions[name]
        entry = stats[name]
        tables[name] = {
            "vocab": int(entry["vocab"]),
            "dim": int(dim),
            "sharding": d.sharding,
            "fused": bool(d.fused),
            "dtype": d.dtype,
            "hot_k": int(d.hot_k),
            "hot_ids": head_ids_for(entry, d.hot_k) if d.hot_k > 0 else [],
            "device": assignment.get(name),
            "predicted_ms": round(final["per_table"][name], 6),
            "hbm_bytes": table_hbm_bytes(
                int(entry["vocab"]), dim, optimizer=optimizer,
                dtype=d.dtype, slot_dtype=slot_dtype, fused=d.fused,
                hot_k=d.hot_k),
        }
    return {
        "format_version": FORMAT_VERSION,
        "batch_size": int(batch_size),
        "n_devices": int(n_devices),
        "dim": int(dim),
        "optimizer": optimizer,
        "dense_model": dense_model,
        "hbm_gb": float(hbm_gb),
        "slot_dtype": slot_dtype,
        # update-cache decision (module docstring): > 0 only when a plain
        # int8 plan predicts a cache win that fits the budget; f32/bf16
        # plans keep the measured-pessimistic 0 (operator opt-in)
        "cache_rows": int(cache_rows),
        "cache_flush_every": CACHE_FLUSH_EVERY if use_cache else 0,
        "stats_digest": table_stats_digest(stats),
        "predicted_step_ms": round(final["total_ms"], 6),
        "predicted_default_ms": round(default_ms, 6),
        "predicted_dense_ms": round(final["dense_ms"], 6),
        "max_device_hbm_bytes": max(loads) + cache_bytes,
        "default_max_device_hbm_bytes": max(default_loads),
        "tables": tables,
    }


# --------------------------------------------------------------------------
# artifact I/O (deterministic: byte-identical across reruns on same stats)
# --------------------------------------------------------------------------


def _canonical(obj):
    if isinstance(obj, float):
        return round(obj, 6)
    if isinstance(obj, dict):
        return {k: _canonical(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_canonical(v) for v in obj]
    return obj


def _dumps(plan: dict) -> str:
    return json.dumps(_canonical(plan), sort_keys=True,
                      separators=(",", ":"))


def write_plan(path: str | Path, plan: dict) -> Path:
    path = Path(path)
    if path.is_dir():
        path = path / PLAN_FILENAME
    path.write_text(_dumps(plan))
    return path


def plan_digest(plan: dict) -> str:
    """Plan fingerprint for the checkpoint ``stamps`` sidecar: sha256 over
    the canonical serialization, truncated to 16 hex chars (the
    ``hot_ids_digest`` idiom) — any placement/dtype/hot-set change flips
    it, so a restore under a different plan refuses loudly."""
    return hashlib.sha256(_dumps(plan).encode()).hexdigest()[:16]


def load_plan(path: str | Path) -> dict:
    """Read and validate a plan artifact.  Raises on a missing file, a
    format-version mismatch, or a structurally corrupt table entry."""
    path = Path(path)
    if path.is_dir():
        path = path / PLAN_FILENAME
    if not path.exists():
        raise ValueError(
            f"no sharding plan at {path} — run `python -m tdfo_tpu.launch "
            "plan --config ...` to generate one from table_stats.json"
        )
    plan = json.loads(path.read_text())
    version = plan.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError(
            f"{path} has plan format_version {version!r}, this build reads "
            f"{FORMAT_VERSION}.  Re-run the planner."
        )
    tables = plan.get("tables")
    if not isinstance(tables, dict) or not tables:
        raise ValueError(f"{path}: missing 'tables' — the plan is corrupt; "
                         "re-run the planner.")
    for name, entry in tables.items():
        missing = {"sharding", "fused", "dtype", "hot_k",
                   "hot_ids"} - set(entry)
        if missing:
            raise ValueError(f"{path}: table {name!r} is missing "
                             f"{sorted(missing)} — re-run the planner.")
        if entry["sharding"] not in _SHARDINGS:
            raise ValueError(f"{path}: table {name!r} has unknown sharding "
                             f"{entry['sharding']!r}")
        if entry["dtype"] not in _DTYPES:
            raise ValueError(f"{path}: table {name!r} has unknown dtype "
                             f"{entry['dtype']!r}")
        ids = entry["hot_ids"]
        k = int(entry["hot_k"])
        if k > 0:
            if len(ids) != k or any(b <= a for a, b in zip(ids, ids[1:])) \
                    or (ids and ids[0] < 0):
                raise ValueError(
                    f"{path}: table {name!r} hot ids must be {k} sorted, "
                    "unique, non-negative ids — the plan is corrupt; "
                    "re-run the planner."
                )
    return plan


def format_plan(plan: dict) -> str:
    """Human-readable plan summary for the ``launch.py plan`` subcommand:
    one line per table (costliest first) plus the plan-vs-defaults
    predicted step times."""
    rows = sorted(plan["tables"].items(),
                  key=lambda kv: (-kv[1]["predicted_ms"], kv[0]))
    lines = [
        f"{'table':<24} {'vocab':>10} {'sharding':>10} {'store':>6} "
        f"{'dtype':>9} {'hot_k':>6} {'dev':>4} {'HBM':>9} {'pred ms':>8}"
    ]
    for name, e in rows:
        dev = "-" if e.get("device") is None else str(e["device"])
        hbm = e.get("hbm_bytes", 0) / (1 << 20)
        lines.append(
            f"{name:<24} {e['vocab']:>10} {e['sharding']:>10} "
            f"{'fused' if e['fused'] else 'plain':>6} {e['dtype']:>9} "
            f"{e['hot_k']:>6} {dev:>4} {hbm:>8.1f}M "
            f"{e['predicted_ms']:>8.3f}"
        )
    lines.append(
        f"predicted step: plan {plan['predicted_step_ms']:.3f} ms vs "
        f"all-defaults {plan['predicted_default_ms']:.3f} ms "
        f"(dense {plan['predicted_dense_ms']:.3f} ms, B="
        f"{plan['batch_size']}, {plan['n_devices']} device(s), "
        f"digest {plan_digest(plan)})"
    )
    if "default_max_device_hbm_bytes" in plan:
        cur = plan["max_device_hbm_bytes"] / (1 << 20)
        dflt = plan["default_max_device_hbm_bytes"] / (1 << 20)
        lines.append(
            f"per-device HBM: plan {cur:.1f} MB vs all-defaults "
            f"{dflt:.1f} MB ({dflt - cur:+.1f} MB saved)"
        )
    if plan.get("cache_rows"):
        lines.append(
            f"update cache: cache_rows {plan['cache_rows']} @ flush_every "
            f"{plan['cache_flush_every']} (int8 write-combining; cache HBM "
            "counted in the per-device total)"
        )
    return "\n".join(lines)


def apply_plan_to_specs(specs, plan: dict):
    """Rewrite embedding specs to the plan's per-table decisions.  Returns
    ``(new_specs, hot_ids)`` where ``hot_ids`` is the plan-embedded
    ``{table_key: sorted int32 ids}`` mapping (or ``None`` when no table
    is hot-split).  Plan entries match a spec by table name or by any of
    its feature names (stats artifacts key by column).  A served table
    with no plan entry is an error — a plan must place every table."""
    import jax.numpy as jnp
    import numpy as np

    tables = plan["tables"]
    new_specs, hot_ids, missing = [], {}, []
    for spec in specs:
        key = None
        if spec.name in tables:
            key = spec.name
        else:
            for f in spec.features:
                if f in tables:
                    key = f
                    break
        if key is None:
            missing.append(spec.name)
            continue
        entry = tables[key]
        if int(entry.get("vocab", spec.num_embeddings)) != spec.num_embeddings:
            raise ValueError(
                f"plan table {key!r} was built for vocab {entry['vocab']} "
                f"but the model serves {spec.num_embeddings} — the plan is "
                "stale; re-run the planner on current stats."
            )
        new_specs.append(dataclasses.replace(
            spec,
            sharding=entry["sharding"],
            fused=bool(entry["fused"]),
            dtype=jnp.dtype(entry["dtype"]),
        ))
        if int(entry["hot_k"]) > 0:
            hot_ids[key] = np.asarray(entry["hot_ids"], dtype=np.int32)
    if missing:
        raise ValueError(
            f"sharding plan has no entry for tables {sorted(missing)} — "
            "regenerate the plan from this model's table_stats.json"
        )
    return new_specs, (hot_ids or None)
