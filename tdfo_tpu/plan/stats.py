"""Per-table traffic statistics artifact (``table_stats.json``).

The planner (``plan/planner.py``) prices a placement from each table's
traffic profile: how many rows a batch touches, how concentrated the
lookup mass is, which ids form the head.  The preprocessing passes already
count per-id value frequencies for the hot/cold artifact
(``data/hot_ids.py``), so they can emit this summary for free, next to
``hot_ids.json``:

  * ``vocab`` / ``total_count`` — table size and total observed lookups;
  * ``unique_per_batch`` — E[distinct rows touched by a size-B batch]
    under the observed id distribution, at a fixed batch grid
    (sum_i 1 - (1 - p_i)^B — the occupancy expectation);
  * ``head_mass`` — lookup-mass fraction absorbed by the top-K
    frequency-ranked ids, at a fixed K grid (the hot-split payoff curve);
  * ``head_ids`` — the frequency-ranked id prefix itself (capped), so a
    chosen hot split can embed its exact id set in the plan artifact.

Counts are ESTIMATES from the training scan; the PR-7 telemetry counters
record the step's true touched/unique rows on-device.  The
:func:`refine_stats_from_metrics` adapter folds a run's ``metrics.jsonl``
counter means back into the artifact (an ``observed`` block per table), so
replanning after a real run prices from measured traffic.
"""

from __future__ import annotations

import hashlib
import json
import math
from pathlib import Path
from typing import Mapping

import numpy as np

__all__ = [
    "FORMAT_VERSION",
    "BATCH_GRID",
    "HEAD_K_GRID",
    "HEAD_IDS_CAP",
    "table_stats_from_counts",
    "write_table_stats",
    "load_table_stats",
    "table_stats_digest",
    "unique_rows_at",
    "unique_rows_over",
    "unique_lines_at",
    "head_mass_at",
    "head_ids_for",
    "refine_stats_from_metrics",
]

# Artifact schema version; bump on incompatible layout changes so a loader
# never silently misreads an old file.
FORMAT_VERSION = 1

_FILENAME = "table_stats.json"

# per-batch unique-row estimates are precomputed at these batch sizes; the
# planner interpolates between them (linear in B — the curve is smooth and
# concave, interpolation error is far below the cost model's tolerance).
# The flush-scale tail points (>= 131072) price the update cache's
# per-interval working set (``unique_rows_over`` at flush_every x B
# draws); artifacts written before they existed clamp at 32768.
BATCH_GRID = (1024, 2048, 4096, 8192, 16384, 32768,
              131072, 524288, 2097152)

# head-mass curve sample points (the planner's hot-split candidate sizes)
HEAD_K_GRID = (1024, 4096, 8192, 16384)

# largest hot head the planner may choose — matches the one-hot MXU update
# range the chip measurements cover (docs/BUDGET.md hot/cold table)
HEAD_IDS_CAP = 16384

_TABLE_KEYS = {"vocab", "total_count", "unique_per_batch", "head_mass",
               "head_ids"}


def table_stats_from_counts(counts: np.ndarray) -> dict:
    """One table's stats entry from its per-id lookup counts
    (``counts[i]`` = lookups of id ``i``, the same array
    ``hot_ids_from_counts`` consumes).  Ties in the head ranking break
    toward lower ids (stable argsort on negated counts) so ``head_ids``
    prefixes equal the hot/cold artifact's sets for the same K."""
    counts = np.asarray(counts, dtype=np.float64)
    v = int(counts.shape[0])
    total = float(counts.sum())
    unique_per_batch = {}
    if total > 0:
        p = counts / total
        # E[unique rows touched] = sum_i 1 - (1 - p_i)^B, computed in log
        # space (p_i can be 1e-8 at Criteo vocabs); zero-count ids
        # contribute exactly 0, full-mass ids exactly 1.
        with np.errstate(divide="ignore"):
            log1mp = np.log1p(-np.minimum(p, 1.0))
        for b in BATCH_GRID:
            unique_per_batch[str(b)] = float(
                np.sum(-np.expm1(b * log1mp)))
    else:
        for b in BATCH_GRID:
            unique_per_batch[str(b)] = float(min(b, v))
    order = np.argsort(-counts, kind="stable")
    ranked = counts[order]
    cum = np.cumsum(ranked)
    head_mass = {}
    for k in HEAD_K_GRID:
        if total > 0:
            head_mass[str(k)] = float(cum[min(k, v) - 1] / total)
        else:
            head_mass[str(k)] = float(min(k, v) / v)
    return {
        "vocab": v,
        "total_count": total,
        "unique_per_batch": unique_per_batch,
        "head_mass": head_mass,
        "head_ids": order[: min(HEAD_IDS_CAP, v)].astype(np.int64).tolist(),
    }


def _canonical(obj):
    """Round floats so reruns on the same counts serialize byte-identically
    (the plan artifact inherits this determinism contract)."""
    if isinstance(obj, float):
        return round(obj, 6)
    if isinstance(obj, dict):
        return {k: _canonical(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_canonical(v) for v in obj]
    return obj


def _dumps(payload: dict) -> str:
    return json.dumps(_canonical(payload), sort_keys=True,
                      separators=(",", ":"))


def write_table_stats(
    data_dir: str | Path, per_table: Mapping[str, np.ndarray]
) -> Path:
    """Persist the artifact next to ``hot_ids.json`` / ``size_map.json``.
    ``per_table`` keys are the categorical COLUMN names; values are per-id
    count arrays (the same ones the hot/cold artifact is built from)."""
    data_dir = Path(data_dir)
    payload = {
        "format_version": FORMAT_VERSION,
        "tables": {
            name: table_stats_from_counts(counts)
            for name, counts in per_table.items()
        },
    }
    path = data_dir / _FILENAME
    path.write_text(_dumps(payload))
    return path


def load_table_stats(data_dir: str | Path) -> dict | None:
    """Read the artifact back as ``{column: stats entry}``; ``None`` when
    ``data_dir`` carries no artifact (the planner then raises with
    re-run-preprocessing guidance)."""
    path = Path(data_dir) / _FILENAME
    if not path.exists():
        return None
    payload = json.loads(path.read_text())
    version = payload.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError(
            f"{path} has table-stats artifact format_version {version!r}, "
            f"this build reads {FORMAT_VERSION}.  Re-run preprocessing to "
            "regenerate the artifact."
        )
    tables = payload.get("tables")
    if not isinstance(tables, dict):
        raise ValueError(f"{path}: missing 'tables' — the file is corrupt; "
                         "re-run preprocessing.")
    for name, entry in tables.items():
        missing = _TABLE_KEYS - set(entry)
        if missing:
            raise ValueError(
                f"{path}: table {name!r} is missing keys {sorted(missing)} "
                "— the file is corrupt; re-run preprocessing."
            )
        ids = np.asarray(entry["head_ids"], dtype=np.int64)
        if ids.ndim != 1 or (ids.size and (ids.min() < 0
                                           or ids.max() >= entry["vocab"])):
            raise ValueError(
                f"{path}: table {name!r} head_ids out of range — the file "
                "is corrupt; re-run preprocessing."
            )
    return tables


def table_stats_digest(tables: Mapping[str, dict]) -> str:
    """Artifact fingerprint for plan provenance: sha256 over the canonical
    serialization, truncated to 16 hex chars (the ``hot_ids_digest``
    idiom)."""
    payload = {"format_version": FORMAT_VERSION,
               "tables": {k: tables[k] for k in sorted(tables)}}
    return hashlib.sha256(_dumps(payload).encode()).hexdigest()[:16]


def _interp_grid(grid: dict[str, float], x: float) -> float:
    """Piecewise-linear read of a {str(x): y} sample dict, clamped at the
    ends (deterministic pure-float math — the plan must be reproducible)."""
    pts = sorted((int(k), float(v)) for k, v in grid.items())
    if not pts:
        raise ValueError("empty sample grid")
    if x <= pts[0][0]:
        return pts[0][1]
    for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
        if x <= x1:
            return y0 + (y1 - y0) * (x - x0) / (x1 - x0)
    return pts[-1][1]


def unique_rows_at(entry: dict, batch_size: int) -> float:
    """Expected distinct rows a size-``batch_size`` batch touches.  Prefers
    the telemetry-observed mean when the run recorded one at this batch
    size; falls back to the analytic occupancy curve."""
    obs = entry.get("observed")
    if obs and int(obs.get("batch", -1)) == int(batch_size):
        return float(obs["unique_rows"])
    u = _interp_grid(entry["unique_per_batch"], float(batch_size))
    return min(u, float(entry["vocab"]), float(batch_size))


def unique_rows_over(entry: dict, batch_size: int, steps: int) -> float:
    """Expected DISTINCT rows touched across ``steps`` consecutive
    batches — the update cache's per-flush-interval working set (what the
    coalesced write-back scatters and what ``cache_rows`` must hold).
    Reads the same occupancy curve as :func:`unique_rows_at`, at
    ``steps * batch_size`` draws.  Artifacts written before the
    flush-scale grid points existed clamp at their largest sample — an
    UNDERestimate of the working set (optimistic toward the cache);
    regenerate ``table_stats.json`` for honest flush pricing.  Never
    returns less than the single-batch estimate."""
    n = float(int(steps) * int(batch_size))
    u = _interp_grid(entry["unique_per_batch"], n)
    u = min(u, float(entry["vocab"]), n)
    return max(u, unique_rows_at(entry, batch_size))


def unique_lines_at(entry: dict, batch_size: int) -> float | None:
    """Telemetry-observed fat-line touch count at this batch size, or
    ``None`` (the estimator then uses its occupancy model)."""
    obs = entry.get("observed")
    if obs and int(obs.get("batch", -1)) == int(batch_size):
        lines = obs.get("unique_lines")
        return None if lines is None else float(lines)
    return None


def head_mass_at(entry: dict, k: int) -> float:
    """Lookup-mass fraction of the top-``k`` frequency-ranked ids."""
    if k <= 0:
        return 0.0
    if k >= entry["vocab"]:
        return 1.0
    return min(1.0, _interp_grid(entry["head_mass"], float(k)))


def head_ids_for(entry: dict, k: int) -> list[int]:
    """The top-``k`` head as a SORTED id list (the hot/cold artifact's
    representation) — raises when the stats head is shorter than ``k``."""
    ids = entry["head_ids"]
    k = min(k, entry["vocab"])
    if len(ids) < k:
        raise ValueError(
            f"stats head_ids holds {len(ids)} ids but the plan wants a "
            f"{k}-row hot head — regenerate table_stats.json"
        )
    return sorted(int(i) for i in ids[:k])


def refine_stats_from_metrics(
    tables: Mapping[str, dict],
    metrics_path: str | Path,
    *,
    batch_size: int,
) -> dict:
    """Fold a run's telemetry counters back into the stats: for every table
    whose ``emb/<name>/touched_ids`` / ``unique_rows`` (and, on fused
    tables, ``unique_lines``) counters appear in ``metrics.jsonl``
    (PR-7 ``obs/counters.py``), attach an ``observed`` block carrying the
    per-step counter MEANS at the run's batch size.  Table names must match
    the counters' array names — i.e. the run should use unstacked tables
    (``stack_tables=false``), since stacked counters aggregate per stack.
    Returns a new stats dict; tables without counters pass through
    unchanged."""
    sums: dict[str, dict[str, float]] = {}
    ns: dict[str, dict[str, int]] = {}
    with open(metrics_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            for key, val in rec.items():
                if not key.startswith("emb/"):
                    continue
                parts = key.split("/")
                if len(parts) != 3:
                    continue
                _, name, counter = parts
                if counter not in ("touched_ids", "unique_rows",
                                   "unique_lines"):
                    continue
                sums.setdefault(name, {}).setdefault(counter, 0.0)
                ns.setdefault(name, {}).setdefault(counter, 0)
                sums[name][counter] += float(val)
                ns[name][counter] += 1
    out = {}
    for name, entry in tables.items():
        entry = dict(entry)
        if name in sums and "unique_rows" in sums[name]:
            means = {c: sums[name][c] / ns[name][c] for c in sums[name]}
            obs = {
                "batch": int(batch_size),
                "touched_ids": means.get("touched_ids",
                                         float(batch_size)),
                "unique_rows": means["unique_rows"],
            }
            if "unique_lines" in means:
                obs["unique_lines"] = means["unique_lines"]
            entry["observed"] = _canonical(obs)
        out[name] = entry
    return out


def _expected_unique(vocab: int, batch: int) -> float:
    """Uniform-traffic occupancy (used by tests/bench synthetic profiles):
    ``v * (1 - (1 - 1/v)^B``)."""
    if vocab <= 0:
        return 0.0
    return vocab * -math.expm1(batch * math.log1p(-1.0 / vocab))
