"""Measured-cost auto-sharding planner: ``costs`` (the docs/BUDGET.md
per-descriptor cost table as an executable model), ``stats`` (per-table
traffic artifact + telemetry refinement), ``planner`` (placement search +
``sharding_plan.json`` artifact)."""

from tdfo_tpu.plan.costs import TableLoad, estimate_step_ms, table_hbm_bytes
from tdfo_tpu.plan.planner import (
    apply_plan_to_specs,
    format_plan,
    load_plan,
    plan_digest,
    plan_tables,
    write_plan,
)
from tdfo_tpu.plan.stats import (
    load_table_stats,
    refine_stats_from_metrics,
    table_stats_digest,
    table_stats_from_counts,
    write_table_stats,
)

__all__ = [
    "TableLoad",
    "estimate_step_ms",
    "table_hbm_bytes",
    "plan_tables",
    "write_plan",
    "load_plan",
    "plan_digest",
    "format_plan",
    "apply_plan_to_specs",
    "load_table_stats",
    "write_table_stats",
    "table_stats_from_counts",
    "table_stats_digest",
    "refine_stats_from_metrics",
]
