"""Quantized embedding storage: narrow storage dtypes + stochastic rounding.

fbgemm_gpu's ``split_table_batched_embeddings`` (the TBE stack under
``torchrec/train.py``) stores tables and optimizer slots in reduced
precision and requantizes writes with stochastic rounding; this module is
the same contract for the GSPMD/Pallas tables.  Storage is narrow
(``bfloat16``), compute stays f32: reads widen the small gathered block
AFTER the row gather (never the table), writes requantize here.

Stochastic rounding uses the classic bit trick: add uniform random low-16
bits to the f32 bit pattern, truncate the mantissa.  Two properties the
rest of the PR leans on:

  * unbiased: E[round(x)] == x for any f32 input;
  * identity on exactly-representable values: a bf16-representable f32 has
    zero low-16 mantissa bits, so adding rand <= 0xFFFF can never carry
    into the kept bits.  Untouched rows that ride through a full-block
    requantize (``jnp.where(touched, new, old)`` sweeps, fat-line blocks)
    therefore round-trip bit-exactly.

Determinism: keys derive from ``(step, table_id)`` via counter-style
``fold_in`` chains (no stateful RNG), so a training run is bit-reproducible
and kill/restart-identical under the PR-1 resume machinery — the restored
``state.step`` regenerates the exact key stream.
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

__all__ = [
    "STORAGE_DTYPES",
    "component_key",
    "quantize",
    "sr_key",
    "stochastic_round",
    "table_id",
]

# the storage dtypes the [embeddings] table_dtype/slot_dtype knobs accept
STORAGE_DTYPES = ("float32", "bfloat16")

# arbitrary fixed base; all variation comes from the (step, table) folds
_SR_BASE = 0x5EED


def table_id(name: str) -> int:
    """Stable 31-bit id of a table/array name for key folding (names are
    config-derived strings, so the id survives restarts and host count)."""
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


def sr_key(step: jax.Array | int, name: str) -> jax.Array:
    """Counter-derived threefry key for stochastic rounding at ``step`` on
    table ``name``.  Pure function of (step, table_id): bit-deterministic
    across runs and identical after a kill/resume at the same step."""
    k = jax.random.PRNGKey(_SR_BASE)
    k = jax.random.fold_in(k, step)
    return jax.random.fold_in(k, table_id(name))


def component_key(key: jax.Array | None, index: int) -> jax.Array | None:
    """Distinct subkey per written component (0=table, 1=mu/accum, 2=nu) so
    no two buffers share rounding bits.  None passes through (f32 path)."""
    return None if key is None else jax.random.fold_in(key, index)


def stochastic_round(x: jax.Array, dtype, key: jax.Array) -> jax.Array:
    """f32 -> ``dtype`` (bf16) with unbiased stochastic rounding."""
    x = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    rand = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    out = (bits + rand) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(out, jnp.float32).astype(dtype)


def quantize(x: jax.Array, dtype, key: jax.Array | None = None) -> jax.Array:
    """Cast ``x`` to the storage ``dtype``: stochastic rounding when
    narrowing with a key, round-to-nearest without one, and a PLAIN astype
    for f32 targets — the default path stays byte-identical to unquantized
    storage (the astype is an identity op XLA elides)."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float32 or key is None:
        return x.astype(dtype)
    return stochastic_round(x, dtype, key)
