"""Quantized embedding storage: narrow storage dtypes + stochastic rounding.

fbgemm_gpu's ``split_table_batched_embeddings`` (the TBE stack under
``torchrec/train.py``) stores tables and optimizer slots in reduced
precision and requantizes writes with stochastic rounding; this module is
the same contract for the GSPMD/Pallas tables.  Storage is narrow
(``bfloat16``), compute stays f32: reads widen the small gathered block
AFTER the row gather (never the table), writes requantize here.

Stochastic rounding uses the classic bit trick: add uniform random low-16
bits to the f32 bit pattern, truncate the mantissa.  Two properties the
rest of the PR leans on:

  * unbiased: E[round(x)] == x for any f32 input;
  * identity on exactly-representable values: a bf16-representable f32 has
    zero low-16 mantissa bits, so adding rand <= 0xFFFF can never carry
    into the kept bits.  Untouched rows that ride through a full-block
    requantize (``jnp.where(touched, new, old)`` sweeps, fat-line blocks)
    therefore round-trip bit-exactly.

Determinism: keys derive from ``(step, table_id)`` via counter-style
``fold_in`` chains (no stateful RNG), so a training run is bit-reproducible
and kill/restart-identical under the PR-1 resume machinery — the restored
``state.step`` regenerates the exact key stream.
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

__all__ = [
    "QSCALE_LAYOUT",
    "STORAGE_DTYPES",
    "bytes_to_f32",
    "component_key",
    "dequantize_rows",
    "f32_to_bytes",
    "quantize",
    "quantize_rows",
    "sr_key",
    "stochastic_round",
    "table_id",
]

# the storage dtypes the [embeddings] table_dtype knob accepts; slot_dtype
# stays on the first two (int8 slots would quantize second-moment state the
# optimizer math cannot survive — config.py refuses it)
STORAGE_DTYPES = ("float32", "bfloat16", "int8")

# Layout stamp for the int8 per-row sidecar: f32 (scale, offset) per row,
# col 0 = scale, col 1 = offset, the grid of quantize_rows below.  Stamped
# into checkpoint stamps (train/trainer.py) and corpus manifests
# (serve/export.py); any future re-grid bumps this string so loaders refuse
# the mismatch in BOTH directions.
QSCALE_LAYOUT = "rowwise-f32-scale-offset-v1"

# arbitrary fixed base; all variation comes from the (step, table) folds
_SR_BASE = 0x5EED


def table_id(name: str) -> int:
    """Stable 31-bit id of a table/array name for key folding (names are
    config-derived strings, so the id survives restarts and host count)."""
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


def sr_key(step: jax.Array | int, name: str) -> jax.Array:
    """Counter-derived threefry key for stochastic rounding at ``step`` on
    table ``name``.  Pure function of (step, table_id): bit-deterministic
    across runs and identical after a kill/resume at the same step."""
    k = jax.random.PRNGKey(_SR_BASE)
    k = jax.random.fold_in(k, step)
    return jax.random.fold_in(k, table_id(name))


def component_key(key: jax.Array | None, index: int) -> jax.Array | None:
    """Distinct subkey per written component (0=table, 1=mu/accum, 2=nu) so
    no two buffers share rounding bits.  None passes through (f32 path)."""
    return None if key is None else jax.random.fold_in(key, index)


def stochastic_round(x: jax.Array, dtype, key: jax.Array) -> jax.Array:
    """f32 -> ``dtype`` (bf16) with unbiased stochastic rounding."""
    x = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    rand = jax.random.bits(key, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    out = (bits + rand) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(out, jnp.float32).astype(dtype)


def quantize(x: jax.Array, dtype, key: jax.Array | None = None) -> jax.Array:
    """Cast ``x`` to the storage ``dtype``: stochastic rounding when
    narrowing with a key, round-to-nearest without one, and a PLAIN astype
    for f32 targets — the default path stays byte-identical to unquantized
    storage (the astype is an identity op XLA elides).  int8 storage never
    routes here — it needs the per-row (scale, offset) sidecar, so int8
    writers call :func:`quantize_rows` explicitly."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.int8:
        raise ValueError(
            "int8 storage carries a per-row (scale, offset) sidecar — use "
            "quantize_rows/dequantize_rows, not the scalar quantize path"
        )
    if dtype == jnp.float32 or key is None:
        return x.astype(dtype)
    return stochastic_round(x, dtype, key)


# --------------------------------------------------------------------------
# int8 rowwise quantization (fbgemm TBE rowwise scale/offset parity)
# --------------------------------------------------------------------------
#
# fbgemm's INT8 SplitTableBatchedEmbedding rows store 8-bit codes plus one
# (scale, bias) f32 pair per row appended to the line; here the pair lives
# in a separate f32 [N, 2] sidecar (column 0 = scale, column 1 = offset)
# because XLA narrow-tiles the int8 data independently of the sidecar.
#
# Grid: code q in [-128, 127] decodes as  x = q * scale + offset  with
#   scale  = (rmax - rmin) / 255
#   offset = rmin + 128 * scale            (so rmin -> -128, rmax -> 127)
# A degenerate row (rmax == rmin, including all-zero init rows) stores
# scale = 1 and codes 0, so constant rows round-trip bit-exactly through
# offset alone.
#
# Unlike bf16, int8 stochastic rounding is NOT identity on stored values:
# every write recomputes the row's grid from the NEW f32 values, so codes
# shift even for untouched lanes of a touched row.  Untouched ROWS must
# therefore never be rewritten: every int8 write path is ROW-sparse
# (per-row scatter of gathered rows), including the layout compositions —
# fat-line int8 carries codes + sidecar + f32-byte optimizer state in one
# byte line and updates it per row (``ops/sparse._fat_apply_rows_int8``),
# and the update cache requantizes per cached row at write time and
# bit-copies codes + sidecar at flush.  The one full-block sweep in the
# tree (the dense_lazy one-hot tier) stays f32/bf16-only.


def quantize_rows(
    x: jax.Array, key: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """f32 rows ``[N, D]`` -> (int8 codes ``[N, D]``, f32 ``[N, 2]``
    (scale, offset) sidecar).  With ``key``: unbiased stochastic rounding
    on the int8 grid (floor(t + uniform)); without: round-to-nearest.
    Encoding divides by the STORED f32 scale so decode uses the exact grid
    the codes were placed on."""
    x = x.astype(jnp.float32)
    rmin = jnp.min(x, axis=-1, keepdims=True)
    rmax = jnp.max(x, axis=-1, keepdims=True)
    scale = (rmax - rmin) / jnp.float32(255.0)
    # degenerate rows (constant / zero-init): any nonzero scale works, the
    # codes come out 0 and offset carries the value exactly
    scale = jnp.where(scale > 0, scale, jnp.float32(1.0))
    offset = rmin + jnp.float32(128.0) * scale
    t = (x - offset) / scale
    if key is None:
        q = jnp.round(t)
    else:
        q = jnp.floor(t + jax.random.uniform(key, x.shape, jnp.float32))
    data = jnp.clip(q, -128.0, 127.0).astype(jnp.int8)
    return data, jnp.concatenate([scale, offset], axis=-1)


def f32_to_bytes(x: jax.Array) -> jax.Array:
    """f32 ``[..., K]`` -> int8 ``[..., 4*K]`` byte view (pure bitcast, no
    rounding).  The int8 fat-line layout stores the per-row (scale, offset)
    sidecar and the exact f32 optimizer state as byte lanes of the int8
    line; this helper (and :func:`bytes_to_f32`) keeps every int8-typed
    cast in this module — ``tests/test_quality.py`` enforces the monopoly."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int8)
    return b.reshape(*x.shape[:-1], x.shape[-1] * 4)


def bytes_to_f32(b: jax.Array) -> jax.Array:
    """int8 ``[..., 4*K]`` byte view -> f32 ``[..., K]`` (inverse of
    :func:`f32_to_bytes`; exact round-trip, bits untouched)."""
    if b.shape[-1] % 4:
        raise ValueError(f"byte lane count {b.shape[-1]} is not a multiple of 4")
    k = b.shape[-1] // 4
    return jax.lax.bitcast_convert_type(
        b.reshape(*b.shape[:-1], k, 4), jnp.float32)


def dequantize_rows(data: jax.Array, qscale: jax.Array) -> jax.Array:
    """int8 codes ``[..., D]`` + f32 ``[..., 2]`` sidecar -> f32 rows.
    Works on jax arrays (traced or not) and on host numpy arrays (the
    export path dequantizes table views host-side)."""
    scale = qscale[..., 0:1]
    offset = qscale[..., 1:2]
    if isinstance(data, jax.Array) or isinstance(qscale, jax.Array):
        return data.astype(jnp.float32) * scale + offset
    import numpy as np

    return (np.asarray(data, np.float32) * np.asarray(scale, np.float32)
            + np.asarray(offset, np.float32))
