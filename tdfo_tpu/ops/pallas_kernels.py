"""Pallas TPU kernels for the framework's hot ops.

Two kernels where hand-scheduling beats XLA's default lowering; everything
else (plain gathers, ``jagged_to_dense`` — a single fused gather,
``tdfo_tpu/data/jagged.py``) is left to XLA on purpose, which already tiles
those well.

  * :func:`flash_attention` — blockwise attention with an online softmax:
    O(T) memory per query tile instead of the O(T²) logits matrix, VMEM-tiled
    for the MXU.  The single-device complement of ring attention
    (``tdfo_tpu/parallel/ring_attention.py``): ring shards T across chips,
    this kernel keeps each chip's block from materialising its local logits.
    Forward AND backward are Pallas kernels (FlashAttention-2 recompute: the
    forward saves only the per-row logsumexp; the backward rebuilds each
    probability tile from (q, k, lse) on the fly), so training at long T
    never materialises [T, T] in either direction.
  * :func:`fat_adam_rows` — the fused in-backward embedding-optimizer update
    (fbgemm ``EmbOptimType.ADAM`` parity, ``torchrec/train.py:191``) over the
    framework's *fat row* storage layout ``[V, pad(3D, 128)]`` (table | mu |
    nu interleaved per row, lane-padded).  The kernel streams the touched
    rows HBM->VMEM with per-row async DMAs, applies the whole Adam math, and
    DMA-writes the rows back IN PLACE (``input_output_aliases``) — measured
    ~2x faster than even a single XLA scatter call on v5e, and it replaces a
    gather + compute + 3 scatters.  The fat layout exists because Mosaic
    requires DMA slices lane-aligned to 128: separate [V, 64] table/mu/nu
    buffers cannot be row-DMA'd at all (a kernel attempting that fails to
    compile on hardware), while one padded fat row is a single aligned
    descriptor per row per direction.

Both take ``interpret=`` for CPU-exact testing (the suite runs them in
interpreter mode on the spoofed CPU mesh; the benchmark exercises the
compiled path on the real chip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "flash_attention",
    "fat_adam_rows",
    "fat_layout",
    "fat_components",
    "fat_assemble",
    "fat_pack",
]

_NEG_INF = float(jnp.finfo(jnp.float32).min)


# --------------------------------------------------------------------------
# flash attention
# --------------------------------------------------------------------------


def _flash_kernel(valid_ref, q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int, scale: float):
    """One (batch*head, q-tile) grid step: stream K/V tiles, online softmax.
    Also emits the per-row logsumexp (the FlashAttention-2 backward residual;
    +inf marks fully-masked rows so the backward's exp() yields 0 there)."""
    bq, dh = q_ref.shape
    t = k_ref.shape[0]
    q = q_ref[:]  # input dtype (bf16 on TPU): MXU-native, f32 accumulation

    def body(kt, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(kt * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kt * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK] f32
        valid = valid_ref[0, pl.ds(kt * block_k, block_k)] > 0  # [BK]
        s = jnp.where(valid[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        shift = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift)
        p = jnp.where(valid[None, :], p, 0.0)
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, jnp.exp(m - shift))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, dh), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))
    o_ref[:] = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0).astype(o_ref.dtype)
    if lse_ref is not None:  # training path only; inference skips the write
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
        # 8-sublane broadcast layout (like the validity mask): a [T, 1]
        # output would lane-pad 128x and OOM vmem at long T
        lse_ref[:] = jnp.broadcast_to(lse[:, 0][None, :], (8, bq))


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6)
)
def flash_attention(
    q: jax.Array,  # [B, H, T, Dh]
    k: jax.Array,
    v: jax.Array,
    key_valid: jax.Array | None = None,  # [B, T] True = attend
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    # 512-blocks measured fastest on v5e at T=4096 (fwd+bwd 6.7 ms vs 7.9 ms
    # for the [T,T]-materialising XLA formulation); blocks clip to short T
    # inference path: no logsumexp residual is computed or written
    return _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret,
                           with_lse=False)[0]


def _clip_blocks(block_q, block_k, t):
    # blocks must stay multiples of 8 (Mosaic sublane tile) even when clipped
    # to a short T
    return max(8, min(block_q, t) // 8 * 8), max(8, min(block_k, t) // 8 * 8)


def _pad_t(t, block_q, block_k):
    import math

    block = math.lcm(block_q, block_k)
    return -(-t // block) * block


def _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret,
                    with_lse: bool = True):
    b, h, t, dh = q.shape
    if key_valid is None:
        key_valid = jnp.ones((b, t), bool)
    block_q, block_k = _clip_blocks(block_q, block_k, t)
    if t % block_q or t % block_k:
        # pad T up to a multiple of BOTH blocks (lcm, so the recursive call
        # terminates): padded keys are masked out, padded query rows sliced
        pad = _pad_t(t, block_q, block_k) - t
        out_p, lse_p = _flash_fwd_impl(
            jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(key_valid, ((0, 0), (0, pad))),
            block_q, block_k, interpret, with_lse,
        )
        return out_p[:, :, :t, :], (lse_p[:, :, :, :t] if with_lse else None)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=1.0 / (dh**0.5)
    )
    # grid (b, h, q-tiles) keeps every index map affine (Mosaic rejects the
    # div/rem a flattened batch*head axis would need for the mask row).
    out = pl.pallas_call(
        kernel,
        grid=(b, h, t // block_q),
        in_specs=[
            # mask broadcast to 8 sublanes per batch row: Mosaic requires the
            # trailing block dims to tile (8, 128); kernel reads row 0
            pl.BlockSpec((None, 8, t), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)
            ),
        ] + ([
            # [B, H, 8, T] sublane-broadcast lse (tileable, no lane padding)
            pl.BlockSpec((None, None, 8, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ] if with_lse else []),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, dh), q.dtype),
        ] + ([jax.ShapeDtypeStruct((b, h, 8, t), jnp.float32)] if with_lse else []),
        interpret=interpret,
    )(
        jnp.broadcast_to(key_valid.astype(jnp.float32)[:, None, :], (b, 8, t)),
        q, k, v,
    )
    if with_lse:
        out, lse = out
        return out, lse
    return out[0], None


def _xla_attention(q, k, v, key_valid):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / (q.shape[-1] ** 0.5)
    if key_valid is not None:
        s = jnp.where(key_valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if key_valid is not None:
        # fully-masked rows: softmax over all -inf is uniform garbage; zero it
        any_valid = key_valid.any(axis=-1)[:, None, None, None]
        p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


# ---------------------------------------------------------- flash backward


def _flash_bwd_dq_kernel(valid_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                         do_ref, dq_ref, *, block_k: int, scale: float):
    """dQ for one q-tile: stream K/V tiles, recompute P from q, k and the
    saved logsumexp — no [T, T] buffer ever exists."""
    bq, dh = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:]
    do = do_ref[:]
    # lse/delta ride the same broadcast-to-8-sublanes layout as the validity
    # mask: a [T, 1] block would lane-pad 128x and blow VMEM at long T
    lse = lse_ref[0, pl.ds(qi * bq, bq)].astype(jnp.float32)[:, None]
    delta = delta_ref[0, pl.ds(qi * bq, bq)].astype(jnp.float32)[:, None]

    def body(kt, acc):
        k_blk = k_ref[pl.ds(kt * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kt * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        valid = valid_ref[0, pl.ds(kt * block_k, block_k)] > 0
        # p = softmax prob reconstructed; exp(-inf)=0 kills masked keys and
        # fully-masked rows (lse = +inf) alike
        p = jnp.exp(jnp.where(valid[None, :], s, _NEG_INF) - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    acc = jax.lax.fori_loop(0, t // block_k, body, jnp.zeros((bq, dh), jnp.float32))
    dq_ref[:] = (scale * acc).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(valid_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                          do_ref, dk_ref, dv_ref, *, block_q: int, scale: float):
    """dK/dV for one k-tile: stream q-tiles, same recompute trick."""
    bk, dh = k_ref.shape
    t = q_ref.shape[0]
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    valid = valid_ref[0, pl.ds(0, bk)] > 0  # this tile's key validity
    # valid_ref block is the k-tile slice (see in_specs): full row of length bk

    def body(qt, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(qt * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qt * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qt * block_q, block_q)].astype(jnp.float32)[:, None]
        delta = delta_ref[0, pl.ds(qt * block_q, block_q)].astype(jnp.float32)[:, None]
        s = scale * jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        p = jnp.exp(jnp.where(valid[None, :], s, _NEG_INF) - lse)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BK, Dh]
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        ds = (p * (dp - delta)).astype(q_blk.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BK, Dh]
        return dk_acc, dv_acc

    z = jnp.zeros((bk, dh), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(0, t // block_q, body, (z, z))
    dk_ref[:] = (scale * dk_acc).astype(dk_ref.dtype)
    dv_ref[:] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, key_valid, out, lse, g, block_q, block_k, interpret):
    b, h, t, dh = q.shape
    block_q, block_k = _clip_blocks(block_q, block_k, t)
    if t % block_q or t % block_k:
        pad = _pad_t(t, block_q, block_k) - t
        padt = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        dq, dk, dv = _flash_bwd_impl(
            padt(q), padt(k), padt(v),
            jnp.pad(key_valid, ((0, 0), (0, pad))),
            padt(out),
            # padded q rows: lse=+inf marks them fully masked -> zero grads
            jnp.pad(lse, ((0, 0), (0, 0), (0, 0), (0, pad)),
                    constant_values=jnp.inf),
            padt(g),
            block_q, block_k, interpret,
        )
        return dq[:, :, :t], dk[:, :, :t], dv[:, :, :t]

    # delta = rowsum(dO * O): O(T Dh) in XLA, the only non-kernel piece
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    scale = 1.0 / (dh**0.5)
    mask8 = jnp.broadcast_to(key_valid.astype(jnp.float32)[:, None, :], (b, 8, t))
    # lse already arrives in the [B, H, 8, T] sublane-broadcast layout
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, t))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, scale=scale),
        grid=(b, h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, 8, t), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(mask8, lse, delta8, q, k, v, g)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, scale=scale),
        grid=(b, h, t // block_k),
        in_specs=[
            # the k-tile's slice of the validity row
            pl.BlockSpec((None, 8, block_k), lambda bi, hi, ki: (bi, 0, ki)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(mask8, lse, delta8, q, k, v, g)
    return dq, dk, dv


def _flash_fwd(block_q, block_k, interpret, q, k, v, key_valid):
    out, lse = _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret)
    return out, (q, k, v, key_valid, out, lse)


def _flash_bwd(block_q, block_k, interpret, res, g):
    """O(T)-memory recompute backward (FlashAttention-2): two Pallas kernels
    rebuild each probability tile from (q, k, lse) on the fly — the [T, T]
    matrix the old XLA recompute materialised never exists."""
    q, k, v, key_valid = res[:4]
    out, lse = res[4], res[5]
    if key_valid is None:
        key_valid = jnp.ones((q.shape[0], q.shape[2]), bool)
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, key_valid, out, lse, g, block_q, block_k, interpret
    )
    return dq, dk, dv, None


flash_attention.defvjp(
    lambda q, k, v, key_valid, block_q, block_k, interpret: _flash_fwd(
        block_q, block_k, interpret, q, k, v, key_valid
    ),
    lambda block_q, block_k, interpret, res, g: _flash_bwd(
        block_q, block_k, interpret, res, g
    ),
)


# --------------------------------------------------------------------------
# fused row-sparse adam over fat rows
# --------------------------------------------------------------------------

_LANE = 128  # Mosaic lane tile
_SUB = 64  # component alignment: any 64-aligned interval of length <= 128
#            starting at a 0/64 in-tile offset never straddles a lane tile


def fat_layout(d: int) -> tuple[int, int]:
    """(component_stride, n_tiles) of the fat row layout for embedding dim d.

    A fat row stores [table | mu | nu] as three components of ``stride``
    lanes each (stride = d rounded up to 64, or to 128 when d > 64), shaped
    ``[V, n_tiles, 128]``.  The 3D shape is load-bearing: Mosaic tiles the
    trailing TWO dims, so per-row DMA (slicing dim 0 by 1) is always legal —
    a 2D ``[V, 3d]`` layout is rejected for widths over one lane tile
    (sublane misalignment), and separate [V, d] buffers cannot be row-DMA'd
    at all for d < 128.  The 64-alignment guarantees each component lives in
    whole-tile + half-tile pieces that static vector slices can reach.
    """
    stride = -(-d // _SUB) * _SUB
    if d > _SUB:
        stride = -(-d // _LANE) * _LANE
    lanes = -(-3 * stride // _LANE) * _LANE
    return stride, lanes // _LANE


def fat_components(x: jax.Array, d: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """[..., T, 128] fat rows -> (table, mu, nu) views, each [..., d].
    Pure jnp: used identically inside the Pallas kernel (on VMEM vectors,
    d <= 128 — tile-local static slices only) and in the XLA fallback /
    lookup paths (any d, via a flat reshape XLA folds away)."""
    stride, _ = fat_layout(d)
    if d > _LANE:  # XLA-only path: components span multiple tiles
        flat = x.reshape(*x.shape[:-2], -1)
        return tuple(flat[..., c * stride:c * stride + d] for c in range(3))
    outs = []
    for c in range(3):
        o = c * stride
        tile, off = o // _LANE, o % _LANE
        # fat_layout guarantees off + d <= 128 here (no tile straddling)
        outs.append(x[..., tile, off:off + d])
    return tuple(outs)


def fat_assemble(x: jax.Array, comps: tuple[jax.Array, ...], d: int) -> jax.Array:
    """Write updated (table, mu, nu) back into fat rows, preserving padding
    lanes from ``x``.  Returns the new [..., T, 128] array."""
    stride, t_tiles = fat_layout(d)
    if d > _LANE:  # XLA-only path (see fat_components)
        flat = x.reshape(*x.shape[:-2], -1)
        for c, comp in enumerate(comps):
            flat = jax.lax.dynamic_update_slice_in_dim(
                flat, comp, c * stride, axis=flat.ndim - 1
            )
        return flat.reshape(*x.shape)
    tiles = []
    for t in range(t_tiles):
        segs = []
        lane = 0
        while lane < _LANE:
            gl = t * _LANE + lane
            c = gl // stride
            if c < 3 and gl - c * stride < d:
                off = gl - c * stride
                take = min(d - off, _LANE - lane)
                segs.append(comps[c][..., off:off + take])
            else:
                # padding lanes up to the next component start (or tile end)
                nxt = min(
                    [(cc * stride) for cc in range(3) if cc * stride > gl]
                    + [(t + 1) * _LANE]
                )
                take = min(nxt, (t + 1) * _LANE) - gl
                segs.append(x[..., t, lane:lane + take])
            lane += take
        tiles.append(jnp.concatenate(segs, axis=-1) if len(segs) > 1 else segs[0])
    return jnp.stack(tiles, axis=-2)


def fat_pack(table: jax.Array, mu: jax.Array, nu: jax.Array) -> jax.Array:
    """[V, d] x3 -> [V, T, 128] fat rows (zero padding lanes)."""
    v, d = table.shape
    _, t_tiles = fat_layout(d)
    zero = jnp.zeros((v, t_tiles, _LANE), jnp.float32)
    return fat_assemble(
        zero, (table.astype(jnp.float32), mu.astype(jnp.float32),
               nu.astype(jnp.float32)), d
    )


def _adam_math(row, mu_r, nu_r, g_rows, corr, *, lr, b1, b2, eps, weight_decay):
    mu_n = b1 * mu_r + (1 - b1) * g_rows
    nu_n = b2 * nu_r + (1 - b2) * g_rows * g_rows
    mu_hat = mu_n / corr[0]
    nu_hat = nu_n / corr[1]
    delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * row)
    return row - delta, mu_n, nu_n


def fat_adam_rows(
    fat: jax.Array,  # [V, T, 128] f32 fat rows (fat_layout(d))
    uids: jax.Array,  # [U] unique row ids; sentinel = int32 max for padding
    g: jax.Array,  # [U, d] deduped row gradients
    step_count: jax.Array,  # scalar i32, 1-based after increment
    *,
    d: int,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    rows_per_step: int = 128,
    interpret: bool = False,
):
    """In-place fused lazy Adam on the touched rows of a fat table.

    Per grid step: ``rows_per_step`` row DMAs HBM->VMEM (all in flight
    together, the fbgemm TBE structure), the full Adam math on the component
    slices, and row DMAs straight back into the SAME buffer
    (``input_output_aliases`` — the caller's array is donated).  Sentinel
    rows read row 0 (harmless) and skip their write-back.  No XLA scatter
    anywhere — measured ~3x faster on v5e than the gather + 3-scatter XLA
    formulation it replaces; per-step HBM traffic is 2 x touched_rows x
    row_bytes.

    Requires ``uids`` duplicate-free (``dedupe_grads``): duplicate real ids
    would race on the same fat row across grid steps.  d must be <= 128
    (larger dims use the XLA fallback in ``ops.sparse``).
    """
    v_rows, t_tiles, lane = fat.shape
    assert lane == _LANE and t_tiles == fat_layout(d)[1], (fat.shape, d)
    assert d <= _LANE, "fat_adam_rows supports d <= 128; use the XLA fallback"
    u = uids.shape[0]
    sentinel = jnp.iinfo(jnp.int32).max
    # 2 buffers x rows semaphores must fit the chip's ~2KB sflag space
    # (2x256 overflows it on v5e); 128 measured fastest anyway
    rows_per_step = min(rows_per_step, 128, -(-u // 8) * 8)
    u_pad = -(-u // rows_per_step) * rows_per_step
    pad = u_pad - u
    uids_p = jnp.pad(uids.astype(jnp.int32), (0, pad), constant_values=sentinel)
    g_p = jnp.pad(g, ((0, pad), (0, 0)))
    t_f = step_count.astype(jnp.float32)
    corr = jnp.stack([1.0 - b1**t_f, 1.0 - b2**t_f])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u_pad // rows_per_step,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # [c1, c2] bias corrections
            pl.BlockSpec((rows_per_step, g.shape[1]), lambda i, ids: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # fat (HBM, manual DMA)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),  # aliased with fat
        scratch_shapes=[
            # DOUBLE-buffered row scratch: block i+1's reads overlap block
            # i's compute, block i-1's writes drain one step behind
            pltpu.VMEM((2, rows_per_step, t_tiles, _LANE), jnp.float32),
            # ONE semaphore per (buffer, row) serves reads AND writes: on a
            # given slot they strictly alternate (read.start/wait -> compute
            # -> write.start, drained before the slot's next read), and two
            # separate arrays would overflow the chip's semaphore space
            pltpu.SemaphoreType.DMA((2, rows_per_step)),
        ],
    )

    def kernel(ids_ref, corr_ref, g_ref, fat_hbm, out_hbm, scratch, sems):
        i = pl.program_id(0)
        nsteps = pl.num_programs(0)

        # helpers take a STATIC buffer parity (semaphore indices must be
        # static) and a traced block index
        def read_copy(block, p, r):
            rid = ids_ref[block * rows_per_step + r]
            # sentinel/out-of-range rows read row 0: cheap, write masked
            # off.  The >= 0 clause keeps a stray NEGATIVE id (excluded by
            # dedupe_grads, but not by the stated uids contract) in bounds.
            read = jnp.where((rid >= 0) & (rid < v_rows), rid, 0)
            return pltpu.make_async_copy(
                fat_hbm.at[pl.ds(read, 1)], scratch.at[p, pl.ds(r, 1)],
                sems.at[p, r],
            )

        def write_copy(block, p, r):
            rid = ids_ref[block * rows_per_step + r]
            return rid, pltpu.make_async_copy(
                scratch.at[p, pl.ds(r, 1)], out_hbm.at[pl.ds(rid, 1)],
                sems.at[p, r],
            )

        @pl.when(i == 0)
        def _():
            for r in range(rows_per_step):
                read_copy(0, 0, r).start()

        for p in (0, 1):  # parity of block i+1 (== parity of block i-1)
            @pl.when(((i + 1) % 2 == p) & (i >= 1))
            def _(p=p):
                # buffer p is about to be reused: block i-1's writes out of
                # it must land first
                for r in range(rows_per_step):
                    rid, cp = write_copy(i - 1, p, r)

                    @pl.when((rid >= 0) & (rid < v_rows))
                    def _(cp=cp):
                        cp.wait()

            @pl.when(((i + 1) % 2 == p) & (i + 1 < nsteps))
            def _(p=p):
                for r in range(rows_per_step):
                    read_copy(i + 1, p, r).start()

        for p in (0, 1):  # parity of block i itself
            @pl.when(i % 2 == p)
            def _(p=p):
                for r in range(rows_per_step):
                    read_copy(i, p, r).wait()
                x = scratch[p]  # [rows, T, 128]
                row, mu_r, nu_r = fat_components(x, d)
                g_rows = g_ref[...].astype(jnp.float32)
                # bias corrections precomputed outside (no runtime powf)
                new = _adam_math(row, mu_r, nu_r, g_rows, corr_ref, lr=lr,
                                 b1=b1, b2=b2, eps=eps,
                                 weight_decay=weight_decay)
                scratch[p] = fat_assemble(x, new, d)
                for r in range(rows_per_step):
                    rid, cp = write_copy(i, p, r)

                    @pl.when((rid >= 0) & (rid < v_rows))
                    def _(cp=cp):
                        cp.start()

                @pl.when(i == nsteps - 1)
                def _(p=p):
                    # no later step will drain the final block's writes
                    for r in range(rows_per_step):
                        rid, cp = write_copy(i, p, r)

                        @pl.when((rid >= 0) & (rid < v_rows))
                        def _(cp=cp):
                            cp.wait()

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(fat.shape, fat.dtype),
        input_output_aliases={3: 0},  # fat (operands: uids, corr, g, fat)
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(uids_p, corr, g_p, fat)
