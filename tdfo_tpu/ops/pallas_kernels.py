"""Pallas TPU kernels for the framework's hot ops.

Two kernels where hand-scheduling beats XLA's default lowering; everything
else (plain gathers, ``jagged_to_dense`` — a single fused gather,
``tdfo_tpu/data/jagged.py``) is left to XLA on purpose, which already tiles
those well.

  * :func:`flash_attention` — blockwise attention with an online softmax:
    O(T) memory per query tile instead of the O(T²) logits matrix, VMEM-tiled
    for the MXU.  The single-device complement of ring attention
    (``tdfo_tpu/parallel/ring_attention.py``): ring shards T across chips,
    this kernel keeps each chip's block from materialising its local logits.
    Forward is a Pallas kernel; backward recomputes with the XLA formulation
    (a dedicated backward kernel is a further optimisation).
  * :func:`sparse_adam_rows` — the fused in-backward embedding-optimizer
    update (fbgemm ``EmbOptimType.ADAM`` parity, ``torchrec/train.py:191``):
    one kernel pass fuses the three row gathers (table + both moments,
    scalar-prefetch-driven index maps, the fbgemm TBE trick) with the Adam
    math; a single XLA masked scatter lands the updates — no dense [V, D]
    sweep anywhere.

Both take ``interpret=`` for CPU-exact testing (the suite runs them in
interpreter mode on the spoofed CPU mesh; the benchmark exercises the
compiled path on the real chip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "sparse_adam_rows"]

_NEG_INF = float(jnp.finfo(jnp.float32).min)


# --------------------------------------------------------------------------
# flash attention
# --------------------------------------------------------------------------


def _flash_kernel(valid_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float):
    """One (batch*head, q-tile) grid step: stream K/V tiles, online softmax."""
    bq, dh = q_ref.shape
    t = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale

    def body(kt, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(kt * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kt * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        valid = valid_ref[0, pl.ds(kt * block_k, block_k)] > 0  # [BK]
        s = jnp.where(valid[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        shift = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift)
        p = jnp.where(valid[None, :], p, 0.0)
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, jnp.exp(m - shift))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, dh), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))
    o_ref[:] = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0).astype(o_ref.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6)
)
def flash_attention(
    q: jax.Array,  # [B, H, T, Dh]
    k: jax.Array,
    v: jax.Array,
    key_valid: jax.Array | None = None,  # [B, T] True = attend
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    return _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret)


def _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret):
    b, h, t, dh = q.shape
    if key_valid is None:
        key_valid = jnp.ones((b, t), bool)
    # blocks must stay multiples of 8 (Mosaic sublane tile) even when clipped
    # to a short T
    block_q = max(8, min(block_q, t) // 8 * 8)
    block_k = max(8, min(block_k, t) // 8 * 8)
    if t % block_q or t % block_k:
        # pad T up to a multiple of BOTH blocks (lcm, so the recursive call
        # terminates): padded keys are masked out, padded query rows sliced
        import math

        block = math.lcm(block_q, block_k)
        t_pad = -(-t // block) * block
        pad = t_pad - t
        padded = _flash_fwd_impl(
            jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(key_valid, ((0, 0), (0, pad))),
            block_q, block_k, interpret,
        )
        return padded[:, :, :t, :]
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=1.0 / (dh**0.5)
    )
    # grid (b, h, q-tiles) keeps every index map affine (Mosaic rejects the
    # div/rem a flattened batch*head axis would need for the mask row).
    out = pl.pallas_call(
        kernel,
        grid=(b, h, t // block_q),
        in_specs=[
            # mask broadcast to 8 sublanes per batch row: Mosaic requires the
            # trailing block dims to tile (8, 128); kernel reads row 0
            pl.BlockSpec((None, 8, t), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, t, dh), q.dtype),
        interpret=interpret,
    )(
        jnp.broadcast_to(key_valid.astype(jnp.float32)[:, None, :], (b, 8, t)),
        q, k, v,
    )
    return out


def _xla_attention(q, k, v, key_valid):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / (q.shape[-1] ** 0.5)
    if key_valid is not None:
        s = jnp.where(key_valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if key_valid is not None:
        # fully-masked rows: softmax over all -inf is uniform garbage; zero it
        any_valid = key_valid.any(axis=-1)[:, None, None, None]
        p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


def _flash_fwd(block_q, block_k, interpret, q, k, v, key_valid):
    out = _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret)
    return out, (q, k, v, key_valid)


def _flash_bwd(block_q, block_k, interpret, res, g):
    q, k, v, key_valid = res
    # O(T^2)-memory recompute backward via XLA (flash backward kernel TBD)
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, key_valid), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(
    lambda q, k, v, key_valid, block_q, block_k, interpret: _flash_fwd(
        block_q, block_k, interpret, q, k, v, key_valid
    ),
    lambda block_q, block_k, interpret, res, g: _flash_bwd(
        block_q, block_k, interpret, res, g
    ),
)


# --------------------------------------------------------------------------
# fused row-sparse adam
# --------------------------------------------------------------------------


def sparse_adam_rows(
    table: jax.Array,  # [V, D]
    mu: jax.Array,  # [V, D] f32
    nu: jax.Array,  # [V, D] f32
    uids: jax.Array,  # [U] unique row ids; sentinel = dtype max for padding
    g: jax.Array,  # [U, D] deduped row gradients
    step_count: jax.Array,  # scalar i32, 1-based after increment
    *,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    interpret: bool = False,
):
    """Fused Adam over the touched rows; returns (table, mu, nu).

    The kernel fuses the THREE row gathers (table, mu, nu — index maps driven
    by the scalar-prefetched id vector, the fbgemm TBE trick) with the whole
    Adam math, emitting compact [U, D] row updates; the final scatter is an
    XLA ``.at[uids].set(mode="drop")`` on donated buffers, which drops the
    padding sentinel natively.  One HBM read per touched row per buffer, one
    scatter write — never a dense [V, D] pass.

    Writes are NOT index-mapped back into the tables from inside the kernel:
    multiple grid steps may clamp to the same row (padding slots), and
    aliased same-row read-modify-writes across grid steps race with block
    pipelining.
    """
    v_rows, d = table.shape
    u = uids.shape[0]
    sentinel = jnp.iinfo(uids.dtype).max
    rows_per_step = 8  # Mosaic tile height for f32
    u_pad = -(-u // rows_per_step) * rows_per_step
    pad = u_pad - u
    uids_p = jnp.pad(uids, (0, pad), constant_values=sentinel)
    g_p = jnp.pad(g, ((0, pad), (0, 0)))
    prefetch_ids = jnp.where(
        uids_p == sentinel, 0, jnp.minimum(uids_p, v_rows - 1)
    ).astype(jnp.int32)
    t_f = step_count.astype(jnp.float32)
    corr = jnp.stack([1.0 - b1**t_f, 1.0 - b2**t_f])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u_pad // rows_per_step,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # [c1, c2] bias corrections
            pl.BlockSpec((rows_per_step, d), lambda i, ids: (i, 0)),  # g rows
            pl.BlockSpec(memory_space=pl.ANY),  # table (HBM, DMA'd)
            pl.BlockSpec(memory_space=pl.ANY),  # mu
            pl.BlockSpec(memory_space=pl.ANY),  # nu
        ],
        out_specs=[
            pl.BlockSpec((rows_per_step, d), lambda i, ids: (i, 0)),
            pl.BlockSpec((rows_per_step, d), lambda i, ids: (i, 0)),
            pl.BlockSpec((rows_per_step, d), lambda i, ids: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((3, rows_per_step, d), jnp.float32),
            pltpu.SemaphoreType.DMA((3, rows_per_step)),
        ],
    )

    def kernel(ids_ref, corr_ref, g_ref, table_hbm, mu_hbm, nu_hbm,
               out_row_ref, out_mu_ref, out_nu_ref, scratch, sems):
        i = pl.program_id(0)
        # gather this step's rows: 3 * rows_per_step small DMAs, all in flight
        # together (the fbgemm TBE gather structure)
        for r in range(rows_per_step):
            row_id = ids_ref[i * rows_per_step + r]
            for b_idx, hbm in enumerate((table_hbm, mu_hbm, nu_hbm)):
                pltpu.make_async_copy(
                    hbm.at[pl.ds(row_id, 1), :],
                    scratch.at[b_idx, pl.ds(r, 1), :],
                    sems.at[b_idx, r],
                ).start()
        for r in range(rows_per_step):
            row_id = ids_ref[i * rows_per_step + r]
            for b_idx, hbm in enumerate((table_hbm, mu_hbm, nu_hbm)):
                pltpu.make_async_copy(
                    hbm.at[pl.ds(row_id, 1), :],
                    scratch.at[b_idx, pl.ds(r, 1), :],
                    sems.at[b_idx, r],
                ).wait()
        g_rows = g_ref[:].astype(jnp.float32)
        row = scratch[0]
        mu_r = scratch[1]
        nu_r = scratch[2]
        mu_n = b1 * mu_r + (1 - b1) * g_rows
        nu_n = b2 * nu_r + (1 - b2) * g_rows * g_rows
        # Adam bias corrections precomputed outside (Mosaic has no runtime
        # powf); corr_ref = [1 - b1^t, 1 - b2^t]
        mu_hat = mu_n / corr_ref[0]
        nu_hat = nu_n / corr_ref[1]
        delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * row)
        out_row_ref[:] = (row - delta).astype(out_row_ref.dtype)
        out_mu_ref[:] = mu_n
        out_nu_ref[:] = nu_n

    new_rows, new_mu, new_nu = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((u_pad, d), table.dtype),
            jax.ShapeDtypeStruct((u_pad, d), mu.dtype),
            jax.ShapeDtypeStruct((u_pad, d), nu.dtype),
        ],
        interpret=interpret,
    )(prefetch_ids, corr, g_p, table, mu, nu)
    new_rows, new_mu, new_nu = new_rows[:u], new_mu[:u], new_nu[:u]

    # masked scatter: sentinel ids are out of bounds -> dropped
    return (
        table.at[uids].set(new_rows, mode="drop"),
        mu.at[uids].set(new_mu, mode="drop"),
        nu.at[uids].set(new_nu, mode="drop"),
    )
