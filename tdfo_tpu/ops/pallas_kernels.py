"""Pallas TPU kernels for the framework's hot ops.

Two kernels where hand-scheduling beats XLA's default lowering; everything
else (plain gathers, ``jagged_to_dense`` — a single fused gather,
``tdfo_tpu/data/jagged.py``) is left to XLA on purpose, which already tiles
those well.

  * :func:`flash_attention` — blockwise attention with an online softmax:
    O(T) memory per query tile instead of the O(T²) logits matrix, VMEM-tiled
    for the MXU.  The single-device complement of ring attention
    (``tdfo_tpu/parallel/ring_attention.py``): ring shards T across chips,
    this kernel keeps each chip's block from materialising its local logits.
    Forward AND backward are Pallas kernels (FlashAttention-2 recompute: the
    forward saves only the per-row logsumexp; the backward rebuilds each
    probability tile from (q, k, lse) on the fly), so training at long T
    never materialises [T, T] in either direction.
  * :func:`fat_line_update` — the fused in-backward embedding-optimizer
    update (fbgemm ``EmbOptimType`` parity for adam / sgd / adagrad /
    rowwise_adagrad, ``torchrec/train.py:187-195``) over the framework's
    *fat line* storage layout (:func:`line_layout`: R vocab rows of
    ``[table | optimizer state]`` packed per 128-lane line).  The kernel
    streams the touched lines HBM->VMEM with per-line async DMAs, applies
    the optimizer math on the packed lanes, and DMA-writes the lines back
    IN PLACE (``input_output_aliases``) — measured faster than even a
    single XLA scatter call on v5e, and it replaces a gather + compute +
    2-3 scatters.  The layout exists because Mosaic requires DMA slices
    lane-aligned to 128: separate narrow [V, d] table/state buffers cannot
    be row-DMA'd at all (a kernel attempting that fails to compile on
    hardware), while one packed line is a single aligned descriptor per
    direction covering up to R rows.

Both take ``interpret=`` for CPU-exact testing (the suite runs them in
interpreter mode on the spoofed CPU mesh; the benchmark exercises the
compiled path on the real chip).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tdfo_tpu.ops.quant import (
    bytes_to_f32, dequantize_rows, f32_to_bytes, quantize_rows)

# jax < 0.5 ships the same dataclass under the TPU-prefixed name
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

__all__ = [
    "flash_attention",
    "LineLayout",
    "line_layout",
    "fat_line_update",
    "fat_line_update_routed",
    "fat_view",
    "fat_gather_rows",
    "fat_pack",
    "fat_unpack",
]

_NEG_INF = float(jnp.finfo(jnp.float32).min)


# --------------------------------------------------------------------------
# flash attention
# --------------------------------------------------------------------------


def _flash_kernel(valid_ref, q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int, scale: float):
    """One (batch*head, q-tile) grid step: stream K/V tiles, online softmax.
    Also emits the per-row logsumexp (the FlashAttention-2 backward residual;
    +inf marks fully-masked rows so the backward's exp() yields 0 there)."""
    bq, dh = q_ref.shape
    t = k_ref.shape[0]
    q = q_ref[:]  # input dtype (bf16 on TPU): MXU-native, f32 accumulation

    def body(kt, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(kt * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kt * block_k, block_k), :]
        # DEFAULT precision is INTENDED on the flash dots (bf16 operands on
        # the MXU); stated explicitly because the quality gate rejects
        # precision-less dot_general in this file
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )  # [BQ, BK] f32
        valid = valid_ref[0, pl.ds(kt * block_k, block_k)] > 0  # [BK]
        s = jnp.where(valid[None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        shift = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift)
        p = jnp.where(valid[None, :], p, 0.0)
        corr = jnp.where(m <= _NEG_INF / 2, 0.0, jnp.exp(m - shift))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, dh), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))
    o_ref[:] = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0).astype(o_ref.dtype)
    if lse_ref is not None:  # training path only; inference skips the write
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf)
        # 8-sublane broadcast layout (like the validity mask): a [T, 1]
        # output would lane-pad 128x and OOM vmem at long T
        lse_ref[:] = jnp.broadcast_to(lse[:, 0][None, :], (8, bq))


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6)
)
def flash_attention(
    q: jax.Array,  # [B, H, T, Dh]
    k: jax.Array,
    v: jax.Array,
    key_valid: jax.Array | None = None,  # [B, T] True = attend
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    # 512-blocks measured fastest on v5e at T=4096 (fwd+bwd 6.7 ms vs 7.9 ms
    # for the [T,T]-materialising XLA formulation); blocks clip to short T
    # inference path: no logsumexp residual is computed or written
    return _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret,
                           with_lse=False)[0]


def _clip_blocks(block_q, block_k, t):
    # blocks must stay multiples of 8 (Mosaic sublane tile) even when clipped
    # to a short T
    return max(8, min(block_q, t) // 8 * 8), max(8, min(block_k, t) // 8 * 8)


def _pad_t(t, block_q, block_k):
    import math

    block = math.lcm(block_q, block_k)
    return -(-t // block) * block


def _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret,
                    with_lse: bool = True):
    b, h, t, dh = q.shape
    if key_valid is None:
        key_valid = jnp.ones((b, t), bool)
    block_q, block_k = _clip_blocks(block_q, block_k, t)
    if t % block_q or t % block_k:
        # pad T up to a multiple of BOTH blocks (lcm, so the recursive call
        # terminates): padded keys are masked out, padded query rows sliced
        pad = _pad_t(t, block_q, block_k) - t
        out_p, lse_p = _flash_fwd_impl(
            jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0))),
            jnp.pad(key_valid, ((0, 0), (0, pad))),
            block_q, block_k, interpret, with_lse,
        )
        return out_p[:, :, :t, :], (lse_p[:, :, :, :t] if with_lse else None)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=1.0 / (dh**0.5)
    )
    # grid (b, h, q-tiles) keeps every index map affine (Mosaic rejects the
    # div/rem a flattened batch*head axis would need for the mask row).
    out = pl.pallas_call(
        kernel,
        grid=(b, h, t // block_q),
        in_specs=[
            # mask broadcast to 8 sublanes per batch row: Mosaic requires the
            # trailing block dims to tile (8, 128); kernel reads row 0
            pl.BlockSpec((None, 8, t), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)
            ),
        ] + ([
            # [B, H, 8, T] sublane-broadcast lse (tileable, no lane padding)
            pl.BlockSpec((None, None, 8, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ] if with_lse else []),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, dh), q.dtype),
        ] + ([jax.ShapeDtypeStruct((b, h, 8, t), jnp.float32)] if with_lse else []),
        interpret=interpret,
    )(
        jnp.broadcast_to(key_valid.astype(jnp.float32)[:, None, :], (b, 8, t)),
        q, k, v,
    )
    if with_lse:
        out, lse = out
        return out, lse
    return out[0], None


def _xla_attention(q, k, v, key_valid):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / (q.shape[-1] ** 0.5)
    if key_valid is not None:
        s = jnp.where(key_valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if key_valid is not None:
        # fully-masked rows: softmax over all -inf is uniform garbage; zero it
        any_valid = key_valid.any(axis=-1)[:, None, None, None]
        p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


# ---------------------------------------------------------- flash backward


def _flash_bwd_dq_kernel(valid_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                         do_ref, dq_ref, *, block_k: int, scale: float):
    """dQ for one q-tile: stream K/V tiles, recompute P from q, k and the
    saved logsumexp — no [T, T] buffer ever exists."""
    bq, dh = q_ref.shape
    t = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:]
    do = do_ref[:]
    # lse/delta ride the same broadcast-to-8-sublanes layout as the validity
    # mask: a [T, 1] block would lane-pad 128x and blow VMEM at long T
    lse = lse_ref[0, pl.ds(qi * bq, bq)].astype(jnp.float32)[:, None]
    delta = delta_ref[0, pl.ds(qi * bq, bq)].astype(jnp.float32)[:, None]

    def body(kt, acc):
        k_blk = k_ref[pl.ds(kt * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kt * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        valid = valid_ref[0, pl.ds(kt * block_k, block_k)] > 0
        # p = softmax prob reconstructed; exp(-inf)=0 kills masked keys and
        # fully-masked rows (lse = +inf) alike
        p = jnp.exp(jnp.where(valid[None, :], s, _NEG_INF) - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )

    acc = jax.lax.fori_loop(0, t // block_k, body, jnp.zeros((bq, dh), jnp.float32))
    dq_ref[:] = (scale * acc).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(valid_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                          do_ref, dk_ref, dv_ref, *, block_q: int, scale: float):
    """dK/dV for one k-tile: stream q-tiles, same recompute trick."""
    bk, dh = k_ref.shape
    t = q_ref.shape[0]
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    valid = valid_ref[0, pl.ds(0, bk)] > 0  # this tile's key validity
    # valid_ref block is the k-tile slice (see in_specs): full row of length bk

    def body(qt, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(qt * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qt * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qt * block_q, block_q)].astype(jnp.float32)[:, None]
        delta = delta_ref[0, pl.ds(qt * block_q, block_q)].astype(jnp.float32)[:, None]
        s = scale * jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )  # [BQ, BK]
        p = jnp.exp(jnp.where(valid[None, :], s, _NEG_INF) - lse)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )  # [BK, Dh]
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )  # [BQ, BK]
        ds = (p * (dp - delta)).astype(q_blk.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )  # [BK, Dh]
        return dk_acc, dv_acc

    z = jnp.zeros((bk, dh), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(0, t // block_q, body, (z, z))
    dk_ref[:] = (scale * dk_acc).astype(dk_ref.dtype)
    dv_ref[:] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, key_valid, out, lse, g, block_q, block_k, interpret):
    b, h, t, dh = q.shape
    block_q, block_k = _clip_blocks(block_q, block_k, t)
    if t % block_q or t % block_k:
        pad = _pad_t(t, block_q, block_k) - t
        padt = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        dq, dk, dv = _flash_bwd_impl(
            padt(q), padt(k), padt(v),
            jnp.pad(key_valid, ((0, 0), (0, pad))),
            padt(out),
            # padded q rows: lse=+inf marks them fully masked -> zero grads
            jnp.pad(lse, ((0, 0), (0, 0), (0, 0), (0, pad)),
                    constant_values=jnp.inf),
            padt(g),
            block_q, block_k, interpret,
        )
        return dq[:, :, :t], dk[:, :, :t], dv[:, :, :t]

    # delta = rowsum(dO * O): O(T Dh) in XLA, the only non-kernel piece
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    scale = 1.0 / (dh**0.5)
    mask8 = jnp.broadcast_to(key_valid.astype(jnp.float32)[:, None, :], (b, 8, t))
    # lse already arrives in the [B, H, 8, T] sublane-broadcast layout
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, t))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, scale=scale),
        grid=(b, h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, 8, t), lambda bi, hi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, None, block_q, dh), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(mask8, lse, delta8, q, k, v, g)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, scale=scale),
        grid=(b, h, t // block_k),
        in_specs=[
            # the k-tile's slice of the validity row
            pl.BlockSpec((None, 8, block_k), lambda bi, hi, ki: (bi, 0, ki)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, 8, t), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, dh), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(mask8, lse, delta8, q, k, v, g)
    return dq, dk, dv


def _flash_fwd(block_q, block_k, interpret, q, k, v, key_valid):
    out, lse = _flash_fwd_impl(q, k, v, key_valid, block_q, block_k, interpret)
    return out, (q, k, v, key_valid, out, lse)


def _flash_bwd(block_q, block_k, interpret, res, g):
    """O(T)-memory recompute backward (FlashAttention-2): two Pallas kernels
    rebuild each probability tile from (q, k, lse) on the fly — the [T, T]
    matrix the old XLA recompute materialised never exists."""
    q, k, v, key_valid = res[:4]
    out, lse = res[4], res[5]
    if key_valid is None:
        key_valid = jnp.ones((q.shape[0], q.shape[2]), bool)
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, key_valid, out, lse, g, block_q, block_k, interpret
    )
    return dq, dk, dv, None


flash_attention.defvjp(
    lambda q, k, v, key_valid, block_q, block_k, interpret: _flash_fwd(
        block_q, block_k, interpret, q, k, v, key_valid
    ),
    lambda block_q, block_k, interpret, res, g: _flash_bwd(
        block_q, block_k, interpret, res, g
    ),
)


# --------------------------------------------------------------------------
# fused row-sparse optimizers over packed fat lines
# --------------------------------------------------------------------------
#
# fbgemm TBE parity for ALL EmbOptimType kinds the reference exercises
# (ADAM on GPU, SGD on CPU, torchrec/train.py:187-195; EXACT_ADAGRAD /
# EXACT_ROWWISE_ADAGRAD are fbgemm's huge-table variants): a table plus its
# per-row optimizer state live interleaved in "fat lines" — [L, T, 128] f32
# where each 128-lane line packs R vocab rows of W lanes each
# ([table(d) | state] per row, W a divisor of 128 so R = 128 // W, or a
# multiple of 128 with R = 1 for wide rows).  The 3D shape is load-bearing:
# Mosaic tiles the trailing TWO dims, so per-LINE DMA (dim-0 slices of 1)
# is always legal, while separate narrow [V, d] buffers cannot be row-DMA'd
# at all.  Because R * W == T * 128 exactly, the line array reshapes
# CONTIGUOUSLY to a [L*R, W] row view — lookups gather full W-lane rows
# (fast) and slice [:d]; no copy, and GSPMD sharding on dim 0 propagates
# through the reshape.
#
# Packing R rows per line is what keeps memory near the plain-table
# footprint: rowwise-adagrad at d=16 needs 17 lanes -> W=32, R=4, i.e.
# 128 B/row — a one-row-per-line [V, 1, 128] layout would cost 512 B/row
# (17 GB for the 33.7M-row Criteo stack, an OOM on v5e).

_LANE = 128  # Mosaic lane tile
_SLOT_WIDTHS = (8, 16, 32, 64, 128)

# optimizer-state lanes per vocab row, after the d table lanes
_STATE_LANES = {
    "sgd": lambda d: 0,
    "rowwise_adagrad": lambda d: 1,   # ONE f32 accumulator cell per row
    "adagrad": lambda d: d,           # per-element squared-grad accumulator
    "adam": lambda d: 2 * d,          # mu | nu moments
}


@dataclass(frozen=True)
class LineLayout:
    """Static description of a packed fat-line table for (d, kind, dtype).

    ``dtype == "int8"`` describes the BYTE-container line: an int8 [L, T,
    128] array whose per-row slot packs ``[d code bytes | 8 sidecar bytes
    (bitcast f32 scale, offset) | 4 bytes per f32 state lane]``.  Only the
    d table lanes are quantized — the rowwise (scale, offset) pair and the
    optimizer state ride as EXACT f32 bit patterns, so fused-int8 state math
    is bit-identical to the plain-int8 (f32 slots) reference."""

    d: int
    kind: str
    w: int      # lanes per vocab row (slot width): [table(d) | state | pad]
    r: int      # vocab rows per line (r * w == tiles * 128)
    tiles: int  # trailing [tiles, 128] shape per line
    dtype: str = "float32"

    @property
    def state_lanes(self) -> int:
        return _STATE_LANES[self.kind](self.d)

    @property
    def need(self) -> int:
        if self.dtype == "int8":
            # codes + bitcast f32 (scale, offset) + bitcast f32 state
            return self.d + 8 + 4 * self.state_lanes
        return self.d + self.state_lanes

    def n_lines(self, rows: int) -> int:
        return -(-rows // self.r)

    def padded_rows(self, rows: int) -> int:
        return self.n_lines(rows) * self.r


def line_layout(d: int, kind: str, dtype="float32") -> LineLayout:
    if kind not in _STATE_LANES:
        raise ValueError(f"unknown fused optimizer kind: {kind!r}")
    dt = jnp.dtype(dtype)
    if dt == jnp.int8:
        if kind == "rowwise_adagrad":
            raise ValueError(
                "fused int8 storage does not support rowwise_adagrad: the "
                "f32 per-row accumulator contract cannot ride a quantized "
                "line (use optimizer = adagrad/adam/sgd, or fused = false)")
        need = d + 8 + 4 * _STATE_LANES[kind](d)
        if need <= _LANE:
            w = next(s for s in _SLOT_WIDTHS if s >= need)
            return LineLayout(d, kind, w, _LANE // w, 1, "int8")
        tiles = -(-need // _LANE)
        return LineLayout(d, kind, tiles * _LANE, 1, tiles, "int8")
    need = d + _STATE_LANES[kind](d)
    if need <= _LANE:
        w = next(s for s in _SLOT_WIDTHS if s >= need)
        return LineLayout(d, kind, w, _LANE // w, 1)
    tiles = -(-need // _LANE)
    return LineLayout(d, kind, tiles * _LANE, 1, tiles)


def fat_view(fat: jax.Array, layout: LineLayout) -> jax.Array:
    """[L, T, 128] lines -> [L*R, W] per-vocab-row view (contiguous
    reshape).  HOST/CPU-side helper (unpack, XLA fallbacks, tests): on TPU
    the tiled physical layouts of the two shapes differ, so this reshape
    MATERIALISES a copy of the whole table (measured ~10 ms at the Criteo
    profile) — device paths must use :func:`fat_gather_rows` instead."""
    return fat.reshape(fat.shape[0] * layout.r, layout.w)


def fat_gather_rows(fat: jax.Array, ids: jax.Array, layout: LineLayout) -> jax.Array:
    """Gather table rows from packed lines WITHOUT reshaping the table:
    full-line gather on dim 0 of the 3D array (the fast TPU pattern — one
    512B descriptor per id), then slot-select the table lanes on the small
    gathered result with R static slices + selects.  ids may be any shape;
    output gains a trailing ``d`` axis.  Out-of-contract ids clamp to row 0
    (low) / the last line (high), matching the plain-table ``jnp.take``
    clip every other lookup path uses."""
    ids = jnp.maximum(ids, 0)
    lines = jnp.take(fat, ids // layout.r, axis=0)  # [..., T, 128]
    if layout.dtype == "int8":
        # slot-select codes AND the adjacent 8 sidecar bytes, then decode
        # on the small gathered block (the table itself stays byte-packed)
        span = layout.d + 8
        flat = lines.reshape(*lines.shape[:-2], layout.tiles * _LANE)
        out = flat[..., :span]
        if layout.r > 1:
            slot = ids % layout.r
            for s in range(1, layout.r):
                piece = flat[..., s * layout.w: s * layout.w + span]
                out = jnp.where((slot == s)[..., None], piece, out)
        codes = out[..., : layout.d]
        qs = bytes_to_f32(out[..., layout.d: span])
        return dequantize_rows(codes, qs)
    if layout.r == 1 and layout.d <= _LANE:
        # table lanes live wholly in tile 0: slice without the flattening
        # reshape (which costs a relayout of the gathered block)
        return lines[..., 0, :layout.d]
    flat = lines.reshape(*lines.shape[:-2], layout.tiles * _LANE)
    out = flat[..., : layout.d]
    if layout.r == 1:
        return out
    slot = ids % layout.r
    for s in range(1, layout.r):
        piece = flat[..., s * layout.w: s * layout.w + layout.d]
        out = jnp.where((slot == s)[..., None], piece, out)
    return out


def fat_pack(table: jax.Array, *state: jax.Array, kind: str = "adam",
             layout: LineLayout | None = None, dtype=None,
             qscale: jax.Array | None = None) -> jax.Array:
    """[V, d] table (+ per-kind optimizer state) -> [L, T, 128] fat lines.

    State arguments by kind: adam ``(mu[V,d], nu[V,d])``; adagrad
    ``(accum[V,d],)``; rowwise_adagrad ``(accum[V],)``; sgd none.  Missing
    state defaults to zeros (fresh init).  Padding rows/lanes are zero.

    ``dtype`` is the STORAGE dtype of the packed lines (default: the
    table's own dtype).  Fat lines interleave table and state lanes in one
    buffer, so the whole line shares it — a bf16 line halves the DMA bytes
    but packs the optimizer state at bf16 too, which is why fused
    rowwise_adagrad (f32-per-row accumulator contract) rejects bf16
    upstream (``parallel/embedding.py``).

    ``dtype == int8`` builds the byte-container line (:class:`LineLayout`):
    an f32 ``table`` is rowwise-quantized here (round-to-nearest, the same
    grid plain-int8 init uses); an int8 ``table`` of codes requires its
    ``qscale`` f32 [V, 2] sidecar.  State must be f32 — it rides as exact
    bit patterns, never quantized.
    """
    v, d = table.shape
    dt = jnp.dtype(dtype) if dtype is not None else table.dtype
    lay = layout or line_layout(d, kind, dt)
    want = {"sgd": 0, "rowwise_adagrad": 1, "adagrad": 1, "adam": 2}[lay.kind]
    if state and len(state) != want:
        raise ValueError(f"{lay.kind} fat_pack takes {want} state arrays")
    if dt == jnp.int8:
        if jnp.dtype(table.dtype) == jnp.int8:
            if qscale is None:
                raise ValueError(
                    "fat_pack of int8 codes needs the f32 (scale, offset) "
                    "qscale sidecar")
            codes, qs = table, qscale.astype(jnp.float32)
        else:
            codes, qs = quantize_rows(table.astype(jnp.float32))
        comps = [codes, f32_to_bytes(qs)]
        if lay.kind == "adagrad":
            acc = state[0] if state else jnp.zeros((v, d), jnp.float32)
            comps.append(f32_to_bytes(acc.astype(jnp.float32)))
        elif lay.kind == "adam":
            mu = state[0] if state else jnp.zeros((v, d), jnp.float32)
            nu = state[1] if len(state) > 1 else jnp.zeros((v, d), jnp.float32)
            comps += [f32_to_bytes(mu.astype(jnp.float32)),
                      f32_to_bytes(nu.astype(jnp.float32))]
        if lay.w > lay.need:
            comps.append(jnp.zeros((v, lay.w - lay.need), codes.dtype))
        rows = jnp.concatenate(comps, axis=1)
        pad = lay.padded_rows(v) - v
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        return rows.reshape(-1, lay.tiles, _LANE)
    comps = [table.astype(dt)]
    if lay.kind == "rowwise_adagrad":
        acc = state[0] if state else jnp.zeros((v,), dt)
        comps.append(acc.astype(dt)[:, None])
    elif lay.kind == "adagrad":
        acc = state[0] if state else jnp.zeros((v, d), dt)
        comps.append(acc.astype(dt))
    elif lay.kind == "adam":
        mu = state[0] if state else jnp.zeros((v, d), dt)
        nu = state[1] if len(state) > 1 else jnp.zeros((v, d), dt)
        comps += [mu.astype(dt), nu.astype(dt)]
    if lay.w > lay.need:
        comps.append(jnp.zeros((v, lay.w - lay.need), dt))
    rows = comps[0] if len(comps) == 1 else jnp.concatenate(comps, axis=1)
    pad = lay.padded_rows(v) - v
    rows = jnp.pad(rows, ((0, pad), (0, 0)))
    return rows.reshape(-1, lay.tiles, _LANE)


def fat_unpack(fat: jax.Array, layout: LineLayout,
               rows: int | None = None) -> tuple[jax.Array, ...]:
    """Inverse of :func:`fat_pack`: ``(table[V,d], *state)``.  int8 lines
    return ``(codes[V,d] int8, qscale[V,2] f32, *state f32)`` — the same
    (codes, sidecar) pair the plain-int8 layout stores in two arrays."""
    view = fat_view(fat, layout)
    if rows is not None:
        view = view[:rows]
    d = layout.d
    table = view[:, :d]
    if layout.dtype == "int8":
        qs = bytes_to_f32(view[:, d:d + 8])
        if layout.kind == "sgd":
            return table, qs
        if layout.kind == "adagrad":
            return table, qs, bytes_to_f32(view[:, d + 8:d + 8 + 4 * d])
        return (table, qs,
                bytes_to_f32(view[:, d + 8:d + 8 + 4 * d]),
                bytes_to_f32(view[:, d + 8 + 4 * d:d + 8 + 8 * d]))
    if layout.kind == "sgd":
        return (table,)
    if layout.kind == "rowwise_adagrad":
        return table, view[:, d]
    if layout.kind == "adagrad":
        return table, view[:, d:2 * d]
    return table, view[:, d:2 * d], view[:, 2 * d:3 * d]


def _lane_map(xs, pred, layout, rows: int):
    """Per-slot lane rearrangement as tiny constant matmuls.

    ``xs``: per-tile [rows, 128] f32 vectors.  ``pred(gi, go) -> bool`` over
    GLOBAL source/dest lane indices (works on numpy at trace time to skip
    all-zero blocks, and on Mosaic iotas to materialise the 0/1 matrix
    in-kernel — no big array constants, no unaligned lane slicing).  Returns
    per-tile outputs ``out[go] = sum_gi x[gi] * pred(gi, go)``: each output
    row depends only on the same scratch row, so sentinel-row garbage never
    crosses rows.  The [128,128] f32 dots are ~us-scale noise next to the
    row DMAs.
    """
    import numpy as np

    t_tiles = layout.tiles
    outs = []
    for s in range(t_tiles):
        acc = None
        for t in range(t_tiles):
            gi_np = np.arange(_LANE)[:, None] + t * _LANE
            go_np = np.arange(_LANE)[None, :] + s * _LANE
            if not np.asarray(pred(gi_np, go_np)).any():
                continue
            gi = jax.lax.broadcasted_iota(jnp.int32, (_LANE, _LANE), 0) + t * _LANE
            go = jax.lax.broadcasted_iota(jnp.int32, (_LANE, _LANE), 1) + s * _LANE
            b = pred(gi, go).astype(jnp.float32)
            # HIGHEST precision: the default TPU f32 dot runs bf16 passes
            # (~1e-3 relative error), which would leak into optimizer state
            contrib = jax.lax.dot_general(
                xs[t], b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            acc = contrib if acc is None else acc + contrib
        outs.append(acc if acc is not None else jnp.zeros((rows, _LANE), jnp.float32))
    return outs


def _line_math(x, gp, tl, corr, layout: LineLayout, *, lr, b1, b2, eps,
               weight_decay):
    """One optimizer step on packed lines.

    ``x``: [rows, T, 128] current lines; ``gp``: summed row grads packed at
    table lanes (zero elsewhere); ``tl``: 1.0 on every lane of touched slots;
    ``corr``: [2] adam bias corrections.  All lane bookkeeping is mask /
    matmul arithmetic (Mosaic-safe at ANY slot width); per-row semantics are
    bit-compatible with the XLA row formulations in ``ops.sparse`` (same
    order of operations; the only divergence is matmul vs reduce summation
    order in cross-lane sums).

    ``x`` may arrive at the narrow STORAGE dtype (bf16 fat lines); all math
    runs f32 — the widening below is an identity op for f32 inputs, and the
    caller requantizes the returned f32 block (:func:`_sr_writeback`).
    """
    t_tiles, w, d, kind = layout.tiles, layout.w, layout.d, layout.kind
    rows = x.shape[0]
    wd = weight_decay
    xs = [x[:, t, :].astype(jnp.float32) for t in range(t_tiles)]
    # gp/tl accept per-tile LISTS (kernel paths that build them in VMEM)
    gs = gp if isinstance(gp, list) else [gp[:, t, :].astype(jnp.float32)
                                         for t in range(t_tiles)]
    ts = tl if isinstance(tl, list) else [tl[:, t, :].astype(jnp.float32)
                                          for t in range(t_tiles)]

    if kind == "adam" and layout.r == 1 and d % 64 == 0:
        # fast path for the R=1 64-aligned layouts (e.g. the twotower d=64
        # config): component boundaries are 64-lane-aligned, so direct
        # static slices replace the lane-map matmuls (~0.3 ms off the
        # headline step), and with one row per line every valid line IS
        # touched — the write-skip on sentinel lines subsumes ``tl``.
        def take_lanes(vecs, a, b):
            out = []
            for t in range(t_tiles):
                lo, hi = max(a, t * _LANE), min(b, (t + 1) * _LANE)
                if lo < hi:
                    out.append(vecs[t][:, lo - t * _LANE:hi - t * _LANE])
            return out[0] if len(out) == 1 else jnp.concatenate(out, axis=1)

        row = take_lanes(xs, 0, d)
        mu_r = take_lanes(xs, d, 2 * d)
        nu_r = take_lanes(xs, 2 * d, 3 * d)
        g = take_lanes(gs, 0, d)
        mu_n = b1 * mu_r + (1 - b1) * g
        nu_n = b2 * nu_r + (1 - b2) * g * g
        delta = lr * ((mu_n / corr[0]) / (jnp.sqrt(nu_n / corr[1]) + eps)
                      + wd * row)
        comps = ((0, row - delta), (d, mu_n), (2 * d, nu_n))
        # assemble each 128-lane tile from the component pieces that fall in
        # it (concatenating a full 3d-wide row first trips Mosaic's
        # offset-tracking on the non-concat dim)
        tiles = []
        for t in range(t_tiles):
            segs, lane = [], t * _LANE
            while lane < (t + 1) * _LANE:
                for off, comp in comps:
                    if off <= lane < off + d:
                        take = min(off + d, (t + 1) * _LANE) - lane
                        segs.append(comp[:, lane - off:lane - off + take])
                        break
                else:  # padding lanes: preserve current contents
                    take = (t + 1) * _LANE - lane
                    segs.append(xs[t][:, lane - t * _LANE:])
                lane += take
            tiles.append(segs[0] if len(segs) == 1
                         else jnp.concatenate(segs, axis=1))
        return jnp.stack(tiles, axis=1)

    def lanes(t):  # [rows, 128] global lane index
        return jax.lax.broadcasted_iota(jnp.int32, (rows, _LANE), 1) + t * _LANE

    within = [lanes(t) % w for t in range(t_tiles)]
    is_table = [wt < d for wt in within]

    if kind == "sgd":
        new = [
            xs[t] - jnp.where(is_table[t], ts[t] * (lr * (gs[t] + wd * xs[t])), 0.0)
            for t in range(t_tiles)
        ]
        return jnp.stack(new, axis=1)

    if kind in ("rowwise_adagrad", "adagrad"):
        geff = [
            jnp.where(is_table[t], (gs[t] + wd * xs[t]) * ts[t], 0.0)
            for t in range(t_tiles)
        ]
        sq = [g * g for g in geff]
        if kind == "rowwise_adagrad":
            is_state = [wt == d for wt in within]
            accg = _lane_map(
                sq,
                lambda gi, go: ((gi // w) == (go // w)) & ((gi % w) < d)
                & ((go % w) == d),
                layout, rows,
            )
            accg = [a * (1.0 / d) for a in accg]  # sum -> mean, scale after
        else:
            is_state = [(wt >= d) & (wt < 2 * d) for wt in within]
            accg = _lane_map(
                sq, lambda gi, go: (go == gi + d) & ((gi % w) < d), layout, rows
            )
        acc_new = [xs[t] + accg[t] for t in range(t_tiles)]
        acc_masked = [jnp.where(is_state[t], acc_new[t], 0.0) for t in range(t_tiles)]
        if kind == "rowwise_adagrad":
            denom = _lane_map(
                acc_masked,
                lambda gi, go: ((gi // w) == (go // w)) & ((gi % w) == d)
                & ((go % w) < d),
                layout, rows,
            )
        else:
            denom = _lane_map(
                acc_masked,
                lambda gi, go: (go == gi - d) & ((gi % w) >= d) & ((gi % w) < 2 * d),
                layout, rows,
            )
        new = [
            xs[t]
            + jnp.where(is_state[t], accg[t], 0.0)
            - lr * geff[t] / (jnp.sqrt(denom[t]) + eps)
            for t in range(t_tiles)
        ]
        return jnp.stack(new, axis=1)

    # adam (AdamW: decoupled weight decay on touched rows)
    is_mu = [(wt >= d) & (wt < 2 * d) for wt in within]
    is_nu = [(wt >= 2 * d) & (wt < 3 * d) for wt in within]
    g_t = [jnp.where(is_table[t], gs[t], 0.0) for t in range(t_tiles)]
    gm = _lane_map(g_t, lambda gi, go: (go == gi + d) & ((gi % w) < d), layout, rows)
    gn = _lane_map([g * g for g in g_t],
                   lambda gi, go: (go == gi + 2 * d) & ((gi % w) < d), layout, rows)
    mu_n = [b1 * xs[t] + (1 - b1) * gm[t] for t in range(t_tiles)]
    nu_n = [b2 * xs[t] + (1 - b2) * gn[t] for t in range(t_tiles)]
    mu_b = _lane_map(
        [jnp.where(is_mu[t], mu_n[t], 0.0) for t in range(t_tiles)],
        lambda gi, go: (go == gi - d) & ((gi % w) >= d) & ((gi % w) < 2 * d),
        layout, rows,
    )
    nu_b = _lane_map(
        [jnp.where(is_nu[t], nu_n[t], 0.0) for t in range(t_tiles)],
        lambda gi, go: (go == gi - 2 * d) & ((gi % w) >= 2 * d) & ((gi % w) < 3 * d),
        layout, rows,
    )
    new = []
    for t in range(t_tiles):
        mu_hat = mu_b[t] / corr[0]
        nu_hat = nu_b[t] / corr[1]
        delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + wd * xs[t])
        upd = (
            jnp.where(is_mu[t], mu_n[t] - xs[t], 0.0)
            + jnp.where(is_nu[t], nu_n[t] - xs[t], 0.0)
            - jnp.where(is_table[t], delta, 0.0)
        )
        new.append(xs[t] + ts[t] * upd)
    return jnp.stack(new, axis=1)


def _sr_writeback(new, seed_ref, block, dtype):
    """Requantize a computed [rows, T, 128] f32 block to the line STORAGE
    dtype at the scratch writeback.

    f32 storage returns ``new`` untouched (the f32 kernel is bit-identical
    to before the dtype layer existed).  Narrow storage without a seed is
    round-to-nearest.  With a seed it applies the same unbiased
    stochastic-rounding bit trick as ``ops/quant.py`` — add uniform low-16
    bits to the f32 pattern, truncate — but the uniform bits come from a
    counter-based murmur3-finalizer hash of (seed, element position, grid
    block) in plain lax ops: ``pltpu.prng_seed`` has no interpret-mode
    lowering in this jax, and a hash of static positions is deterministic
    by construction (same inputs + seed -> same bits, kill/resume-exact).
    Exactly-representable values round-trip bit-exactly (the low-16 add
    cannot carry), so sentinel/untouched lines in the block are preserved
    even before their write-skip.
    """
    if jnp.dtype(dtype) == jnp.float32:
        return new
    if seed_ref is None:
        return new.astype(dtype)
    seed = seed_ref[0].astype(jnp.uint32)
    rows, t_tiles = new.shape[0], new.shape[1]
    out = []
    for t in range(t_tiles):
        x = new[:, t, :]
        # global element index within the block: row-major over [rows, T*128]
        idx = (jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANE), 0)
               * jnp.uint32(t_tiles * _LANE)
               + jnp.uint32(t * _LANE)
               + jax.lax.broadcasted_iota(jnp.uint32, (rows, _LANE), 1))
        h = (idx * jnp.uint32(0x9E3779B1) + seed
             + block.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
        v = (u + (h & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
        out.append(jax.lax.bitcast_convert_type(v, jnp.float32))
    return jnp.stack(out, axis=1).astype(dtype)


def fat_line_update(
    fat: jax.Array,      # [L, T, 128] fat lines (line_layout), f32 or bf16
    ulines: jax.Array,   # [U] unique LINE ids; sentinel = int32 max
    gp: jax.Array,       # [U, T, 128] packed summed grads (table lanes) —
    #                      or, with R == 1, ROW-form [U, d] (streams d lanes
    #                      per line instead of T*128; the kernel pads)
    tl: jax.Array,       # [U, T, 128] touched mask (1.0 on touched slots);
    #                      None with R == 1 (one row per line: every valid
    #                      line is touched, the write-skip subsumes it)
    corr: jax.Array,     # [2] adam bias corrections (zeros for other kinds)
    *,
    layout: LineLayout,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    lines_per_step: int = 128,
    sr_seed: jax.Array | None = None,
    interpret: bool = False,
):
    """In-place fused optimizer step on the touched lines of a fat table.

    Per grid step: ``lines_per_step`` line DMAs HBM->VMEM (all in flight
    together, the fbgemm TBE structure), the optimizer math on the packed
    lanes, and line DMAs straight back into the SAME buffer
    (``input_output_aliases`` — the caller's array is donated).  Sentinel
    lines deliberately issue an UNCONDITIONAL read of line 0 (a per-line
    when-region on the start+wait costs scalar-core time on every block,
    which outweighs skipping the rare tail reads) and skip only their
    write-back, so over-provisioned capacity (slots past the distinct-line
    count) costs one redundant read DMA per slot and no writes.  No XLA
    scatter anywhere — scatters serialise at ~170 ns/row on v5e while the
    double-buffered DMA stream amortises to ~17-35 ns/line.

    Requires ``ulines`` duplicate-free: duplicate line ids would race on the
    same fat line across grid steps.  (fbgemm fused TBE contract,
    ``torchrec/train.py:191-195``.)

    bf16 fat lines compute in f32 and requantize at the scratch writeback
    (:func:`_sr_writeback`; ``sr_seed`` — a scalar int32 — enables
    stochastic rounding, fbgemm quantized-TBE parity).  The seed rides a
    conditional SMEM operand: the f32 call graph — operand list, alias
    indices, kernel signature — is byte-identical to the pre-dtype-layer
    kernel, so default configs cannot regress.
    """
    quant = jnp.dtype(fat.dtype) != jnp.float32
    use_sr = bool(quant) and sr_seed is not None
    n_lines, t_tiles, lane = fat.shape
    assert lane == _LANE and t_tiles == layout.tiles, (fat.shape, layout)
    row_form = gp.ndim == 2
    assert not row_form or (layout.r == 1 and tl is None), (gp.shape, layout)
    u = ulines.shape[0]
    sentinel = jnp.iinfo(jnp.int32).max
    # 2 buffers x lines semaphores must fit the chip's ~2KB sflag space
    # (2x256 overflows it on v5e); 128 measured fastest anyway
    lines_per_step = min(lines_per_step, 128, -(-u // 8) * 8)
    u_pad = -(-u // lines_per_step) * lines_per_step
    pad = u_pad - u
    ulines_p = jnp.pad(ulines.astype(jnp.int32), (0, pad), constant_values=sentinel)
    if row_form:
        gp_p = jnp.pad(gp, ((0, pad), (0, 0)))
        gp_spec = pl.BlockSpec((lines_per_step, gp.shape[1]),
                               lambda i, ids: (i, 0))
        tl_ops, tl_specs = (), ()
    else:
        gp_p = jnp.pad(gp, ((0, pad), (0, 0), (0, 0)))
        gp_spec = pl.BlockSpec((lines_per_step, t_tiles, _LANE),
                               lambda i, ids: (i, 0, 0))
        tl_ops = (jnp.pad(tl, ((0, pad), (0, 0), (0, 0))),)
        tl_specs = (pl.BlockSpec((lines_per_step, t_tiles, _LANE),
                                 lambda i, ids: (i, 0, 0)),)

    # SR seed as a conditional SMEM scalar: present ONLY for narrow storage
    # with a seed, so the f32 operand layout (and alias index) is unchanged
    seed_ops = ((jnp.asarray(sr_seed, jnp.int32).reshape(1),)
                if use_sr else ())
    seed_specs = ((pl.BlockSpec(memory_space=pltpu.SMEM),) if use_sr else ())

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u_pad // lines_per_step,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # [c1, c2] bias corrections
            *seed_specs,
            gp_spec,
            *tl_specs,
            pl.BlockSpec(memory_space=pl.ANY),  # fat (HBM, manual DMA)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),  # aliased with fat
        scratch_shapes=[
            # DOUBLE-buffered line scratch: block i+1's reads overlap block
            # i's compute, block i-1's writes drain one step behind.
            # STORAGE dtype: bf16 lines halve both the scratch footprint and
            # the per-line DMA bytes (compute widens to f32 in _line_math)
            pltpu.VMEM((2, lines_per_step, t_tiles, _LANE), fat.dtype),
            # ONE semaphore per (buffer, line) serves reads AND writes: on a
            # given slot they strictly alternate (read.start/wait -> compute
            # -> write.start, drained before the slot's next read), and two
            # separate arrays would overflow the chip's semaphore space
            pltpu.SemaphoreType.DMA((2, lines_per_step)),
        ],
    )

    def kernel(ids_ref, corr_ref, *args):
        seed_ref = args[0] if use_sr else None
        g_ref, *rest = args[1:] if use_sr else args
        t_ref = None if row_form else rest[0]
        fat_hbm, out_hbm, scratch, sems = rest[-4:]
        i = pl.program_id(0)
        nsteps = pl.num_programs(0)

        # helpers take a STATIC buffer parity (semaphore indices must be
        # static) and a traced block index.  Sentinel/out-of-range lines
        # read line 0 (start AND wait unconditional — they must stay
        # balanced) and skip only their write-back.
        def line_id(block, r):
            rid = ids_ref[block * lines_per_step + r]
            return rid, (rid >= 0) & (rid < n_lines)

        def read_copy(block, p, r):
            rid, ok = line_id(block, r)
            # sentinel/out-of-range lines read line 0 UNconditionally: a
            # per-line when-region on the start+wait costs scalar-core time
            # on EVERY block, which outweighs skipping the rare tail reads
            read = jnp.where(ok, rid, 0)
            return ok, pltpu.make_async_copy(
                fat_hbm.at[pl.ds(read, 1)], scratch.at[p, pl.ds(r, 1)],
                sems.at[p, r],
            )

        def write_copy(block, p, r):
            rid, ok = line_id(block, r)
            return ok, pltpu.make_async_copy(
                scratch.at[p, pl.ds(r, 1)], out_hbm.at[pl.ds(rid, 1)],
                sems.at[p, r],
            )

        def start_reads(block, p):
            for r in range(lines_per_step):
                read_copy(block, p, r)[1].start()

        @pl.when(i == 0)
        def _():
            start_reads(0, 0)

        for p in (0, 1):  # parity of block i+1 (== parity of block i-1)
            @pl.when(((i + 1) % 2 == p) & (i >= 1))
            def _(p=p):
                # buffer p is about to be reused: block i-1's writes out of
                # it must land first
                for r in range(lines_per_step):
                    ok, cp = write_copy(i - 1, p, r)

                    @pl.when(ok)
                    def _(cp=cp):
                        cp.wait()

            @pl.when(((i + 1) % 2 == p) & (i + 1 < nsteps))
            def _(p=p):
                start_reads(i + 1, p)

        for p in (0, 1):  # parity of block i itself
            @pl.when(i % 2 == p)
            def _(p=p):
                for r in range(lines_per_step):
                    read_copy(i, p, r)[1].wait()
                x = scratch[p]  # [lines, T, 128]
                if row_form:
                    # expand the d-lane rows to packed tiles in VMEM (zeros
                    # at state/pad lanes); touched == valid, write-skipped
                    g2 = g_ref[...].astype(jnp.float32)
                    d = layout.d
                    gs = []
                    for t in range(t_tiles):
                        lo, hi = t * _LANE, (t + 1) * _LANE
                        pieces = []
                        if lo < d:
                            pieces.append(g2[:, lo:min(d, hi)])
                        if hi > d:
                            pieces.append(jnp.zeros(
                                (lines_per_step, hi - max(d, lo)),
                                jnp.float32))
                        gs.append(pieces[0] if len(pieces) == 1
                                  else jnp.concatenate(pieces, axis=1))
                    tl_in = [jnp.ones((lines_per_step, _LANE), jnp.float32)
                             for _ in range(t_tiles)]
                else:
                    gg = g_ref[...].astype(jnp.float32)
                    tt = t_ref[...].astype(jnp.float32)
                    gs = [gg[:, t, :] for t in range(t_tiles)]
                    tl_in = [tt[:, t, :] for t in range(t_tiles)]
                new = _line_math(
                    x, gs, tl_in, corr_ref, layout, lr=lr,
                    b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                )
                scratch[p] = _sr_writeback(new, seed_ref, i, fat.dtype)
                for r in range(lines_per_step):
                    ok, cp = write_copy(i, p, r)

                    @pl.when(ok)
                    def _(cp=cp):
                        cp.start()

                @pl.when(i == nsteps - 1)
                def _(p=p):
                    # no later step will drain the final block's writes
                    for r in range(lines_per_step):
                        ok, cp = write_copy(i, p, r)

                        @pl.when(ok)
                        def _(cp=cp):
                            cp.wait()

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(fat.shape, fat.dtype),
        # fat (operands: ids, corr, [seed,] gp, [tl,] fat)
        input_output_aliases={(3 if row_form else 4) + len(seed_ops): 0},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(ulines_p, corr, *seed_ops, gp_p, *tl_ops, fat)


def routed_lines_per_step(layout: LineLayout) -> int:
    """Lines per grid step for the routed kernel: caps the window at
    RPB = lines_per_step x R <= 512 rows so the R x 2 routing masks
    ([lines_per_step, RPB] f32 each) stay ~2 MB of scoped VMEM regardless
    of R (R=16 at 128 lines/step measured a 38 MB stack OOM), and at most
    128 lines so the 2 x lines semaphore array fits the chip's ~2 KB sflag
    space (2 x 512 measured over it)."""
    return min(128, max(8, 512 // layout.r))


def fat_line_update_routed(
    fat: jax.Array,      # [L, T, 128] f32 fat lines (line_layout)
    lines: jax.Array,    # [C, T, 128] f32: CURRENT contents of the touched
    #                      lines in ulines order — the forward pass already
    #                      gathered them, so this kernel issues NO read DMAs
    #                      (half the scattered descriptors; sentinel slots
    #                      may carry any garbage, their writes are skipped)
    ulines: jax.Array,   # [C] unique LINE ids, C % lps == 0; sentinel = i32max
    sdiv: jax.Array,     # [C/lps] per-block window index: row_start(i) // RPB
    tsi: jax.Array,      # [C/lps, 8, 2*RPB] i32 (8-sublane broadcast — a
    #                      (1, 2RPB) block is not Mosaic-tileable):
    #                      per-window-row block-local slot index
    #                      (line_in_block * R + slot), or any value outside
    #                      [0, RPB) for rows of other blocks
    g_u: jax.Array,      # [>= (max(sdiv)+2)*RPB, 128] row-level summed
    #                      grads in SORTED-unique order
    #                      (dedupe_rows_and_lines), lane-padded to 128 (the
    #                      HBM operand is (1,128)-tiled, so window DMAs of
    #                      narrower slices are not tile-aligned)
    corr: jax.Array,     # [2] adam bias corrections (zeros otherwise)
    *,
    layout: LineLayout,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    sr_seed: jax.Array | None = None,
    interpret: bool = False,
):
    """:func:`fat_line_update` with IN-KERNEL operand routing.

    Instead of streaming pre-packed [C, T, 128] grad/touched lanes (whose
    construction needs a segment-sum into the C x R slot space — measured
    ~2.5x the row-level segment-sum at the Criteo profile — plus two packed
    materialisations), this variant consumes the ROW-level ``g_u`` directly:
    each block's rows live in a CONTIGUOUS range of the sorted-unique order,
    covered by two RPB-aligned windows that the Pallas pipeline streams as
    regular blocked inputs (index maps read ``sdiv`` from scalar prefetch).
    The kernel scatters window rows into packed lanes with R tiny 0/1
    iota-compare matmuls per window — each output row depends on one window
    row exactly, so the routing is bit-exact — and derives the touched mask
    from the same matrices for free.  The current line contents arrive as
    the regular blocked ``lines`` input (reusing the forward's gather), so
    the only scattered DMAs are the write-backs.

    bf16 storage: same contract as :func:`fat_line_update` — f32 compute,
    :func:`_sr_writeback` requantize, conditional SMEM ``sr_seed`` operand
    keeping the f32 call graph byte-identical.  ``lines`` arrives at the
    table's storage dtype (it is the forward's gather of ``fat``).
    """
    quant = jnp.dtype(fat.dtype) != jnp.float32
    use_sr = bool(quant) and sr_seed is not None
    n_lines, t_tiles, lane = fat.shape
    d, r, w = layout.d, layout.r, layout.w
    assert lane == _LANE and t_tiles == layout.tiles, (fat.shape, layout)
    c = ulines.shape[0]
    lines_per_step = routed_lines_per_step(layout)
    assert c % lines_per_step == 0, (c, lines_per_step)
    nblocks = c // lines_per_step
    rpb = lines_per_step * r
    assert lines.shape == (c, t_tiles, _LANE), lines.shape

    seed_ops = ((jnp.asarray(sr_seed, jnp.int32).reshape(1),)
                if use_sr else ())
    seed_specs = ((pl.BlockSpec(memory_space=pltpu.SMEM),) if use_sr else ())

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # ulines, sdiv
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # corr
            *seed_specs,
            pl.BlockSpec((None, 8, 2 * rpb), lambda i, ids, sd: (i, 0, 0)),
            pl.BlockSpec((lines_per_step, t_tiles, _LANE),
                         lambda i, ids, sd: (i, 0, 0)),  # current lines
            # g_u windows are at DYNAMIC (sdiv-dependent) offsets: as a
            # blocked input the pipeline stalls on every block's fetch
            # (measured ~3x the whole kernel); manual double-buffered DMA
            # below overlaps the next window with this block's compute
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),  # fat (HBM, write DMAs only)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),  # aliased with fat
        scratch_shapes=[
            # storage dtype (halved write-back DMA bytes for bf16 lines)
            pltpu.VMEM((2, lines_per_step, t_tiles, _LANE), fat.dtype),
            pltpu.VMEM((2, 2 * rpb, _LANE), jnp.float32),  # g windows
            pltpu.SemaphoreType.DMA((2, lines_per_step)),
            pltpu.SemaphoreType.DMA((2,)),  # one bulk window copy per block
        ],
    )
    assert g_u.shape[1] == _LANE, g_u.shape

    def kernel(ids_ref, sdiv_ref, corr_ref, *args):
        seed_ref = args[0] if use_sr else None
        (tsi_ref, lines_ref, g_hbm, fat_hbm, out_hbm,
         scratch, gwin, sems, gsems) = args[1:] if use_sr else args
        i = pl.program_id(0)
        nsteps = pl.num_programs(0)

        def win_copy(block, p):
            start = sdiv_ref[block] * rpb
            return pltpu.make_async_copy(
                g_hbm.at[pl.ds(start, 2 * rpb)], gwin.at[p], gsems.at[p],
            )

        def line_id(block, q):
            rid = ids_ref[block * lines_per_step + q]
            return rid, (rid >= 0) & (rid < n_lines)

        def write_copy(block, p, q):
            rid, ok = line_id(block, q)
            return ok, pltpu.make_async_copy(
                scratch.at[p, pl.ds(q, 1)], out_hbm.at[pl.ds(rid, 1)],
                sems.at[p, q],
            )

        @pl.when(i == 0)
        def _():
            win_copy(0, 0).start()

        for p in (0, 1):
            # scratch buffer p is about to be recomputed: block i-2's
            # writes out of it must land first
            @pl.when((i % 2 == p) & (i >= 2))
            def _(p=p):
                for q in range(lines_per_step):
                    ok, cp = write_copy(i - 2, p, q)

                    @pl.when(ok)
                    def _(cp=cp):
                        cp.wait()

            @pl.when(((i + 1) % 2 == p) & (i + 1 < nsteps))
            def _(p=p):
                win_copy(i + 1, p).start()

        for p in (0, 1):
            @pl.when(i % 2 == p)
            def _(p=p):
                win_copy(i, p).wait()
                glo = gwin[p, pl.ds(0, rpb)].astype(jnp.float32)
                ghi = gwin[p, pl.ds(rpb, rpb)].astype(jnp.float32)
                x = lines_ref[...].astype(jnp.float32)  # [lines, T, 128]
                tsi_lo = tsi_ref[0, pl.ds(0, rpb)]
                tsi_hi = tsi_ref[0, pl.ds(rpb, rpb)]  # sublane 0 of the block
                lrow = jax.lax.broadcasted_iota(
                    jnp.int32, (lines_per_step, rpb), 0)
                slotg, occ = [], []
                for s in range(r):
                    tgt = lrow * r + s
                    m_lo = (tsi_lo[None, :] == tgt).astype(jnp.float32)
                    m_hi = (tsi_hi[None, :] == tgt).astype(jnp.float32)
                    # each output row matches <= 1 window row, so the sums
                    # add zeros to the single routed value: bit-exact
                    dot = lambda m, g: jax.lax.dot_general(
                        m, g, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                    slotg.append((dot(m_lo, glo) + dot(m_hi, ghi))[:, :d])
                    occ.append(
                        jnp.sum(m_lo, axis=1, keepdims=True)
                        + jnp.sum(m_hi, axis=1, keepdims=True)
                    )
                ones_w = jnp.ones((1, w), jnp.float32)
                if t_tiles == 1:
                    pieces_g, pieces_t = [], []
                    for s in range(r):
                        pg = slotg[s]
                        if w > d:
                            pg = jnp.concatenate(
                                [pg, jnp.zeros((lines_per_step, w - d),
                                               jnp.float32)], axis=1)
                        pieces_g.append(pg)
                        pieces_t.append(occ[s] * ones_w)
                    gp = jnp.concatenate(pieces_g, axis=1)[:, None, :]
                    tl = jnp.concatenate(pieces_t, axis=1)[:, None, :]
                else:  # r == 1: one slot spanning T tiles
                    padded = jnp.concatenate(
                        [slotg[0],
                         jnp.zeros((lines_per_step, w - d), jnp.float32)],
                        axis=1)
                    gp = jnp.stack(
                        [padded[:, t * _LANE:(t + 1) * _LANE]
                         for t in range(t_tiles)], axis=1)
                    tlw = occ[0] * jnp.ones((1, _LANE), jnp.float32)
                    tl = jnp.stack([tlw] * t_tiles, axis=1)
                new = _line_math(
                    x, gp, tl, corr_ref, layout, lr=lr, b1=b1, b2=b2,
                    eps=eps, weight_decay=weight_decay,
                )
                scratch[p] = _sr_writeback(new, seed_ref, i, fat.dtype)
                for q in range(lines_per_step):
                    ok, cp = write_copy(i, p, q)

                    @pl.when(ok)
                    def _(cp=cp):
                        cp.start()

        # the final TWO blocks' writes have no later block to drain them.
        # A one-block grid has no off-parity block at all: statically skip
        # parity 1 there — its would-be block index is -1, and merely
        # CONSTRUCTING write_copy(-1, ...) loads ids_ref at a negative SMEM
        # index before any @pl.when guard could suppress it.  For nblocks
        # >= 2, i == nsteps - 1 >= 1 so both parities index real blocks.
        @pl.when(i == nsteps - 1)
        def _():
            for p2 in ((0,) if nblocks == 1 else (0, 1)):
                blk = jnp.where(i % 2 == p2, i, i - 1)
                for q in range(lines_per_step):
                    ok, cp = write_copy(blk, p2, q)

                    @pl.when(ok)
                    def _(cp=cp):
                        cp.wait()

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(fat.shape, fat.dtype),
        # operands: ulines, sdiv, corr, [seed,] tsi, lines, g_u, fat
        input_output_aliases={6 + len(seed_ops): 0},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(ulines, sdiv, corr, *seed_ops, tsi, lines,
      g_u.astype(jnp.float32), fat)
