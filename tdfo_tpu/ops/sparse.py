"""Row-sparse gradient aggregation + optimizer updates.

TPU-native replacement for fbgemm's fused in-backward embedding optimizers
(``EmbOptimType.ADAM/SGD/EXACT_ADAGRAD`` used at ``torchrec/train.py:191-195``
inside ``DistributedModelParallel``).  fbgemm updates only the rows touched by
the batch during the backward pass; the equivalent here is:

  1. the train step computes gradients w.r.t. the *gathered rows* (an
     activation), never materialising a dense [V, D] gradient;
  2. :func:`dedupe_grads` merges duplicate ids with a segment-sum;
  3. a sparse update (:func:`sparse_sgd` / :func:`sparse_adam` /
     :func:`sparse_adagrad` / :func:`sparse_rowwise_adagrad`) gathers the
     touched optimizer-state rows,
     updates them, and scatters back — O(B*D) work and memory traffic per
     step instead of O(V*D), which is what makes >=1B-row tables feasible
     (SURVEY.md §7 hard part #2).

All functions are jit-friendly (static unique-capacity), donation-safe, and
shard-transparent: under GSPMD a row-sharded table turns the gather/scatter
into the appropriate ICI collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "dedupe_grads",
    "dedupe_ids",
    "fat_adam_apply_unique",
    "sparse_sgd",
    "sparse_adam",
    "sparse_adagrad",
    "sparse_rowwise_adagrad",
    "dense_lazy_adam",
    "fat_adam_update",
    "SparseOptimizer",
    "sparse_optimizer",
]


def dedupe_grads(
    ids: jax.Array, grads: jax.Array, *, capacity: int | None = None,
    vocab: int | None = None, max_distinct: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Merge duplicate row ids: ``(ids[B], grads[B,D]) -> (uids[U], g[U,D], valid[U])``.

    ``capacity`` is the static unique bound (defaults to ``B``).  It MUST be
    >= the true distinct-id count: slots are assigned by rank, so distinct
    ids ranked at or past ``capacity`` have their uids write and their
    segment contributions silently dropped (``mode="drop"`` scatter,
    out-of-range segment ids) — gradient mass would vanish without error.
    An undersized capacity is therefore a TRACE-TIME error unless a static
    bound proves it safe: pass ``vocab`` (the table's row count — distinct
    ids can never exceed it) to license ``capacity >= vocab`` with
    ``vocab < B``, or ``max_distinct`` — a CALLER-PROVEN static bound on
    distinct real ids (e.g. a stacked table's per-member
    ``sum(min(batch_f, vocab_f))``, which the train step derives from the
    collection specs).  Undersized capacity slots are not free: scatter
    cost scales with the SLOT count, so a tight bound directly cuts the
    update cost (measured ~60-125 ns/slot on v5e).  The default
    ``capacity=B`` is always safe.

    Negative (padding) ids are remapped to an out-of-bounds sentinel, which
    sorts to the TOP rank: its slot (if within capacity) keeps the sentinel
    id, gets a False ``valid`` mask and a zeroed grad row, and downstream
    scatters drop it — it can never collide with a real row update.  The
    sentinel is the id dtype's max, which must not be a real row id (tables
    are < 2^31 rows for int32 ids).
    """
    b = ids.shape[0]
    capacity = capacity or b
    if (capacity < b and (vocab is None or capacity < vocab)
            and (max_distinct is None or capacity < max_distinct)):
        raise ValueError(
            f"dedupe_grads: capacity {capacity} < batch {b} is only safe when "
            f"a static bound proves distinct ids fit (vocab or max_distinct "
            f"<= capacity); got vocab={vocab}, max_distinct={max_distinct}.  "
            "Undersizing silently DROPS the largest-id updates, so it is "
            "rejected at trace time."
        )
    uids, seg, valid = _dedupe_ids_impl(ids, capacity)
    g = jax.ops.segment_sum(grads, seg, num_segments=capacity)
    g = jnp.where(valid[:, None], g, 0.0)
    return uids, g, valid


def dedupe_ids(
    ids: jax.Array, *, capacity: int | None = None,
    vocab: int | None = None, max_distinct: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The id half of :func:`dedupe_grads`: ``ids[B] -> (uids[C], seg[B],
    valid[C])`` with ``ids == uids[seg]`` for non-negative ids.

    The deduplicated-lookup path uses this ONCE per step per table array:
    the forward gathers ``table[uids]`` (a compact, cache-resident block)
    and expands by ``seg``; the backward segment-sums the embedding grads by
    the SAME ``seg`` — one sort serves both directions instead of a dedupe
    in the update plus a full-width gather in the forward.  Capacity
    licensing matches :func:`dedupe_grads`.
    """
    b = ids.shape[0]
    capacity = capacity or b
    if (capacity < b and (vocab is None or capacity < vocab)
            and (max_distinct is None or capacity < max_distinct)):
        raise ValueError(
            f"dedupe_ids: capacity {capacity} < batch {b} needs a static "
            f"bound (vocab or max_distinct <= capacity); got vocab={vocab}, "
            f"max_distinct={max_distinct}"
        )
    return _dedupe_ids_impl(ids, capacity)


def _dedupe_ids_impl(ids, capacity):
    # Single-sort formulation (measured 3.2x the jnp.unique + sort-method
    # searchsorted pipeline on v5e: 0.24 ms vs 0.78 ms at B=16384): one
    # payload sort ranks the ids, a cumsum over the first-occurrence mask
    # assigns each sorted position its unique slot, and a second pair-sort
    # carries the slot back to the original position.  ``seg`` equals what
    # searchsorted(unique(clean), clean) would produce, so the segment_sum
    # is bit-identical to the textbook pipeline.  Unstable sorts are safe:
    # equal ids share a slot regardless of their relative order.
    b = ids.shape[0]
    oob = jnp.asarray(jnp.iinfo(ids.dtype).max, ids.dtype)
    clean = jnp.where(ids >= 0, ids, oob)
    iota = jnp.arange(b, dtype=jnp.int32)
    sorted_ids, order = jax.lax.sort((clean, iota), num_keys=1, is_stable=False)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]]
    )
    uidx = (jnp.cumsum(first) - 1).astype(jnp.int32)  # slot per sorted pos
    _, seg = jax.lax.sort((order, uidx), num_keys=1, is_stable=False)
    # slot s holds the id ranked s; slots past the distinct count keep the
    # sentinel (and, when capacity < distinct — licensed by a static bound
    # only — the overflow writes/segments are dropped, never misdirected)
    uids = jnp.full((capacity,), oob, ids.dtype).at[uidx].set(
        sorted_ids, mode="drop"
    )
    valid = uids < oob
    return uids, seg, valid


def _masked_scatter_rows(table: jax.Array, uids: jax.Array, new_rows: jax.Array,
                         valid: jax.Array) -> jax.Array:
    """Write new_rows into table[uids]; padding slots carry an out-of-bounds
    id and are dropped by the scatter."""
    del valid  # encoded in uids: invalid slots are out of bounds
    return table.at[uids].set(new_rows, mode="drop")


def sparse_sgd(table, uids, g, valid, *, lr: float, weight_decay: float = 0.0):
    """fbgemm EXACT_SGD parity: touched rows only, wd applied to touched rows."""
    rows = table[uids]
    g = g + weight_decay * rows
    return _masked_scatter_rows(table, uids, rows - lr * g.astype(rows.dtype), valid)


def sparse_adam(table, mu, nu, count, uids, g, valid, *, lr, b1=0.9, b2=0.999,
                eps=1e-8, weight_decay=0.0):
    """Row-sparse AdamW: moments exist per-row; bias correction uses a global
    step count (matches fbgemm ADAM; per-row counts differ negligibly and a
    global count is what optax uses for the dense path).

    ``weight_decay`` is decoupled (AdamW) and only touches gathered rows —
    fbgemm semantics, NOT optax's full-table decay.
    Returns (table, mu, nu, count).
    """
    rows = table[uids]
    mu_r, nu_r = mu[uids], nu[uids]
    g = g.astype(mu_r.dtype)
    new_count = count + 1
    t = new_count.astype(jnp.float32)
    mu_n = b1 * mu_r + (1 - b1) * g
    nu_n = b2 * nu_r + (1 - b2) * g * g
    mu_hat = mu_n / (1 - b1**t)
    nu_hat = nu_n / (1 - b2**t)
    delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * rows)
    return (
        _masked_scatter_rows(table, uids, rows - delta.astype(rows.dtype), valid),
        _masked_scatter_rows(mu, uids, mu_n, valid),
        _masked_scatter_rows(nu, uids, nu_n, valid),
        new_count,
    )


def sparse_rowwise_adagrad(table, accum, uids, g, valid, *, lr, eps=1e-10,
                           weight_decay=0.0):
    """fbgemm EXACT_ROWWISE_ADAGRAD parity: ONE f32 accumulator PER ROW
    (mean of squared grads), not per element — optimizer state is V x 4
    bytes instead of V x D x 8, which is what lets a v5e hold a 4x10^8-row
    dim-8 table WITH adaptive-optimizer semantics (fbgemm's default choice
    for huge tables; ``torchrec/train.py:191`` uses ADAM but fbgemm's TBE
    rowwise variant is the >=1B-row configuration).
    """
    rows = table[uids]
    acc_r = accum[uids]  # [U]
    g = g.astype(jnp.float32) + weight_decay * rows
    acc_n = acc_r + jnp.mean(g * g, axis=-1)
    delta = lr * g / (jnp.sqrt(acc_n)[:, None] + eps)
    return (
        _masked_scatter_rows(table, uids, rows - delta.astype(rows.dtype), valid),
        _masked_scatter_rows(accum, uids, acc_n, valid),
    )


def sparse_adagrad(table, accum, uids, g, valid, *, lr, eps=1e-10, weight_decay=0.0):
    """fbgemm EXACT_ADAGRAD parity (row-wise accumulator of squared grads)."""
    rows = table[uids]
    acc_r = accum[uids]
    g = g.astype(acc_r.dtype) + weight_decay * rows
    acc_n = acc_r + g * g
    delta = lr * g / (jnp.sqrt(acc_n) + eps)
    return (
        _masked_scatter_rows(table, uids, rows - delta.astype(rows.dtype), valid),
        _masked_scatter_rows(accum, uids, acc_n, valid),
    )


def dense_lazy_adam(table, mu, nu, count, ids, grads, *, lr, b1=0.9, b2=0.999,
                    eps=1e-8, weight_decay=0.0):
    """Small-vocab tier: lazy Adam via one-hot MXU matmuls + a dense masked
    sweep.  Per-row gradient sums and touched-row counts come from a single
    ``one_hot.T @ grads`` contraction (XLA fuses the one-hot generation into
    the matmul — nothing [B, V]-sized is materialised), then table/mu/nu get
    a full [V, D] read-modify-write.  For V up to ~16k this is dramatically
    faster on TPU than any gather/scatter formulation (XLA scatter serialises
    per row: ~1.4 ms for 8k rows on v5e vs ~100 us here), and there is no
    sort, no dedupe, no scatter at all.  Negative (padding) ids one-hot to
    zero rows, so they contribute nothing and count as untouched.

    Semantics are identical to :func:`sparse_adam` (lazy moments: untouched
    rows do not decay; decoupled weight decay on touched rows; global-step
    bias correction).  Returns (table, mu, nu, count).
    """
    v = table.shape[0]
    ids = ids.reshape(-1)
    grads = grads.reshape(-1, grads.shape[-1]).astype(jnp.float32)
    oh = jax.nn.one_hot(ids, v, dtype=jnp.float32)  # [B, V], fused into dots
    gsum = jax.lax.dot_general(
        oh, grads, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [V, D]
    touched = (jnp.sum(oh, axis=0) > 0)[:, None]  # [V, 1]
    new_count = count + 1
    t = new_count.astype(jnp.float32)
    mu_n = b1 * mu + (1 - b1) * gsum
    nu_n = b2 * nu + (1 - b2) * gsum * gsum
    mu_hat = mu_n / (1 - b1**t)
    nu_hat = nu_n / (1 - b2**t)
    delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps)
                  + weight_decay * table.astype(jnp.float32))
    return (
        jnp.where(touched, table - delta.astype(table.dtype), table),
        jnp.where(touched, mu_n, mu),
        jnp.where(touched, nu_n, nu),
        new_count,
    )


def fat_adam_update(fat, count, ids, grads, *, embedding_dim, lr, b1=0.9,
                    b2=0.999, eps=1e-8, weight_decay=0.0,
                    capacity: int | None = None,
                    max_distinct: int | None = None):
    """Big-table tier: fused lazy Adam over fat rows ``[V, T, 128]``
    (``pallas_kernels.fat_layout``: table | mu | nu packed per row).

    On TPU with d <= 128 this runs the in-place DMA kernel
    (:func:`~tdfo_tpu.ops.pallas_kernels.fat_adam_rows`); elsewhere an XLA
    formulation with ONE full-row gather and ONE full-row scatter — fat rows
    exist precisely so the whole read-modify-write is a single descriptor
    per row instead of 3 gathers + 3 scatters over separate table/mu/nu
    buffers.  Returns (fat, count).
    """
    uids, g, valid = dedupe_grads(
        ids.reshape(-1), grads.reshape(-1, grads.shape[-1]), capacity=capacity,
        vocab=fat.shape[0], max_distinct=max_distinct,
    )
    return fat_adam_apply_unique(
        fat, count, uids, g, embedding_dim=embedding_dim, lr=lr, b1=b1,
        b2=b2, eps=eps, weight_decay=weight_decay,
    )


def fat_adam_apply_unique(fat, count, uids, g, *, embedding_dim, lr, b1=0.9,
                          b2=0.999, eps=1e-8, weight_decay=0.0):
    """:func:`fat_adam_update` on PRE-deduplicated ``(uids, g)`` — the
    dedup-lookup path computes them once per step and shares them with the
    forward's compact gather."""
    from tdfo_tpu.ops.pallas_kernels import (
        fat_adam_rows,
        fat_assemble,
        fat_components,
    )

    d = embedding_dim
    new_count = count + 1
    if jax.default_backend() == "tpu" and d <= 128:
        fat = fat_adam_rows(
            fat, uids, g, new_count, d=d, lr=lr, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay,
        )
        return fat, new_count
    # XLA fallback (CPU tests, d > 128): numerically identical
    rows = jnp.take(fat, jnp.minimum(uids, fat.shape[0] - 1), axis=0)  # [U, T, 128]
    row, mu_r, nu_r = fat_components(rows, d)
    t = new_count.astype(jnp.float32)
    corr = jnp.stack([1.0 - b1**t, 1.0 - b2**t])
    mu_n = b1 * mu_r + (1 - b1) * g.astype(jnp.float32)
    nu_n = b2 * nu_r + (1 - b2) * g.astype(jnp.float32) ** 2
    delta = lr * ((mu_n / corr[0]) / (jnp.sqrt(nu_n / corr[1]) + eps)
                  + weight_decay * row)
    new_rows = fat_assemble(rows, (row - delta, mu_n, nu_n), d)
    # sentinel uids are out of bounds -> dropped by the scatter
    return fat.at[uids].set(new_rows, mode="drop"), new_count


@dataclass(frozen=True)
class SparseOptimizer:
    """Uniform wrapper: init(table)->slots, update(table, slots, ids, grads)->(table, slots).

    The KeyedOptimizerWrapper/CombinedOptimizer equivalent for the sparse half
    (``torchrec/train.py:248-254``): dense params keep optax; each embedding
    table gets one of these.  Adam dispatches across three tiers picked for
    TPU cost structure (measured on v5e — XLA scatter serialises per row, so
    scatter-free formulations win):

      * fat storage (``table.ndim == 3``): in-place DMA kernel / single
        row-granular gather+scatter — O(touched rows) traffic on tables of
        any size (the >=1B-row path);
      * plain storage, small vocab (<= ``small_vocab_threshold``): one-hot
        MXU matmul + dense masked sweep, no sort/gather/scatter at all;
      * plain storage, large vocab: dedupe + row gather/scatter (the
        portable XLA formulation).
    """

    kind: str  # "sgd" | "adam" | "adagrad" | "rowwise_adagrad"
    lr: float
    weight_decay: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    small_vocab_threshold: int = 16384

    def init(self, table: jax.Array) -> Any:
        if table.ndim == 3:  # fat rows carry their own moments
            if self.kind != "adam":
                raise ValueError("fat (fused) tables require the adam optimizer")
            return (jnp.zeros((), jnp.int32),)
        if self.kind == "sgd":
            return ()
        if self.kind == "adagrad":
            return (jnp.zeros_like(table, dtype=jnp.float32),)
        if self.kind == "rowwise_adagrad":
            # ONE f32 cell per row: the state layout that scales to 1e9 rows
            return (jnp.zeros((table.shape[0],), jnp.float32),)
        if self.kind == "adam":
            return (
                jnp.zeros_like(table, dtype=jnp.float32),
                jnp.zeros_like(table, dtype=jnp.float32),
                jnp.zeros((), jnp.int32),
            )
        raise ValueError(f"unknown sparse optimizer kind: {self.kind!r}")

    def update_unique(self, table, slots, uids, g, valid, *,
                      embedding_dim: int | None = None):
        """Tier dispatch on PRE-deduplicated ``(uids, g, valid)`` — the
        dedup-lookup step path (one shared sort per array per step).  The
        small-vocab one-hot tier needs raw ids and is bypassed here;
        ``sparse_adam`` has identical semantics."""
        if table.ndim == 3:
            if embedding_dim is None:
                raise ValueError("fat-table update needs embedding_dim")
            (count,) = slots
            table, count = fat_adam_apply_unique(
                table, count, uids, g, embedding_dim=embedding_dim,
                lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
                weight_decay=self.weight_decay,
            )
            return table, (count,)
        if self.kind == "sgd":
            return sparse_sgd(table, uids, g, valid, lr=self.lr,
                              weight_decay=self.weight_decay), slots
        if self.kind == "adagrad":
            (accum,) = slots
            table, accum = sparse_adagrad(
                table, accum, uids, g, valid, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay)
            return table, (accum,)
        if self.kind == "rowwise_adagrad":
            (accum,) = slots
            table, accum = sparse_rowwise_adagrad(
                table, accum, uids, g, valid, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay)
            return table, (accum,)
        if self.kind == "adam":
            mu, nu, count = slots
            table, mu, nu, count = sparse_adam(
                table, mu, nu, count, uids, g, valid, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
            )
            return table, (mu, nu, count)
        raise ValueError(self.kind)

    def update(self, table, slots, ids, grads, *, embedding_dim: int | None = None,
               capacity: int | None = None, max_distinct: int | None = None):
        if table.ndim == 3:
            if embedding_dim is None:
                raise ValueError("fat-table update needs embedding_dim")
            (count,) = slots
            table, count = fat_adam_update(
                table, count, ids, grads, embedding_dim=embedding_dim,
                lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
                weight_decay=self.weight_decay, capacity=capacity,
                max_distinct=max_distinct,
            )
            return table, (count,)
        if self.kind == "adam" and table.shape[0] <= self.small_vocab_threshold:
            mu, nu, count = slots
            table, mu, nu, count = dense_lazy_adam(
                table, mu, nu, count, ids, grads, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
            )
            return table, (mu, nu, count)
        uids, g, valid = dedupe_grads(ids.reshape(-1), grads.reshape(-1, grads.shape[-1]),
                                      capacity=capacity, vocab=table.shape[0],
                                      max_distinct=max_distinct)
        if self.kind == "sgd":
            return sparse_sgd(table, uids, g, valid, lr=self.lr,
                              weight_decay=self.weight_decay), slots
        if self.kind == "adagrad":
            (accum,) = slots
            table, accum = sparse_adagrad(table, accum, uids, g, valid, lr=self.lr,
                                          eps=self.eps, weight_decay=self.weight_decay)
            return table, (accum,)
        if self.kind == "rowwise_adagrad":
            (accum,) = slots
            table, accum = sparse_rowwise_adagrad(
                table, accum, uids, g, valid, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay)
            return table, (accum,)
        if self.kind == "adam":
            mu, nu, count = slots
            table, mu, nu, count = sparse_adam(
                table, mu, nu, count, uids, g, valid, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
            )
            return table, (mu, nu, count)
        raise ValueError(self.kind)


def sparse_optimizer(kind: str, lr: float, weight_decay: float = 0.0, **kw) -> SparseOptimizer:
    return SparseOptimizer(kind=kind, lr=lr, weight_decay=weight_decay, **kw)
