"""Row-sparse gradient aggregation + optimizer updates.

TPU-native replacement for fbgemm's fused in-backward embedding optimizers
(``EmbOptimType.ADAM/SGD/EXACT_ADAGRAD`` used at ``torchrec/train.py:191-195``
inside ``DistributedModelParallel``).  fbgemm updates only the rows touched by
the batch during the backward pass; the equivalent here is:

  1. the train step computes gradients w.r.t. the *gathered rows* (an
     activation), never materialising a dense [V, D] gradient;
  2. :func:`dedupe_grads` merges duplicate ids with a segment-sum;
  3. a sparse update (:func:`sparse_sgd` / :func:`sparse_adam` /
     :func:`sparse_adagrad` / :func:`sparse_rowwise_adagrad`) gathers the
     touched optimizer-state rows,
     updates them, and scatters back — O(B*D) work and memory traffic per
     step instead of O(V*D), which is what makes >=1B-row tables feasible
     (SURVEY.md §7 hard part #2).

All functions are jit-friendly (static unique-capacity), donation-safe, and
shard-transparent: under GSPMD a row-sharded table turns the gather/scatter
into the appropriate ICI collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tdfo_tpu.obs import counters
from tdfo_tpu.ops.quant import (
    component_key,
    dequantize_rows,
    quantize,
    quantize_rows,
)

__all__ = [
    "dedupe_grads",
    "dedupe_ids",
    "fat_apply_unique",
    "sparse_sgd",
    "sparse_adam",
    "sparse_adagrad",
    "sparse_rowwise_adagrad",
    "dense_lazy_adam",
    "dense_lazy_sgd",
    "dense_lazy_adagrad",
    "dense_lazy_rowwise_adagrad",
    "fat_update",
    "cache_route",
    "cache_lookup_rows",
    "cache_overlay_rows",
    "SparseOptimizer",
    "sparse_optimizer",
]


def dedupe_grads(
    ids: jax.Array, grads: jax.Array, *, capacity: int | None = None,
    vocab: int | None = None, max_distinct: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Merge duplicate row ids: ``(ids[B], grads[B,D]) -> (uids[U], g[U,D], valid[U])``.

    ``capacity`` is the static unique bound (defaults to ``B``).  It MUST be
    >= the true distinct-id count: slots are assigned by rank, so distinct
    ids ranked at or past ``capacity`` have their uids write and their
    segment contributions silently dropped (``mode="drop"`` scatter,
    out-of-range segment ids) — gradient mass would vanish without error.
    An undersized capacity is therefore a TRACE-TIME error unless a static
    bound proves it safe: pass ``vocab`` (the table's row count — distinct
    ids can never exceed it) to license ``capacity >= vocab`` with
    ``vocab < B``, or ``max_distinct`` — a CALLER-PROVEN static bound on
    distinct real ids (e.g. a stacked table's per-member
    ``sum(min(batch_f, vocab_f))``, which the train step derives from the
    collection specs).  Undersized capacity slots are not free: scatter
    cost scales with the SLOT count, so a tight bound directly cuts the
    update cost (measured ~60-125 ns/slot on v5e).  The default
    ``capacity=B`` is always safe.

    Negative (padding) ids are remapped to an out-of-bounds sentinel, which
    sorts to the TOP rank: its slot (if within capacity) keeps the sentinel
    id, gets a False ``valid`` mask and a zeroed grad row, and downstream
    scatters drop it — it can never collide with a real row update.  The
    sentinel is the id dtype's max, which must not be a real row id (tables
    are < 2^31 rows for int32 ids).
    """
    b = ids.shape[0]
    capacity = capacity or b
    if (capacity < b and (vocab is None or capacity < vocab)
            and (max_distinct is None or capacity < max_distinct)):
        raise ValueError(
            f"dedupe_grads: capacity {capacity} < batch {b} is only safe when "
            f"a static bound proves distinct ids fit (vocab or max_distinct "
            f"<= capacity); got vocab={vocab}, max_distinct={max_distinct}.  "
            "Undersizing silently DROPS the largest-id updates, so it is "
            "rejected at trace time."
        )
    uids, seg, valid = _dedupe_ids_impl(ids, capacity)
    # widen BEFORE the segment-sum: bf16-stored tables hand back bf16
    # embedding grads, and duplicate-id accumulation must happen in f32
    # (identity for f32 inputs)
    g = jax.ops.segment_sum(grads.astype(jnp.float32), seg,
                            num_segments=capacity)
    g = jnp.where(valid[:, None], g, 0.0)
    return uids, g, valid


def dedupe_ids(
    ids: jax.Array, *, capacity: int | None = None,
    vocab: int | None = None, max_distinct: int | None = None,
    rows_per_line: int = 1,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The id half of :func:`dedupe_grads`: ``ids[B] -> (uids[C], seg[B],
    valid[C])`` with ``ids == uids[seg]`` for non-negative ids.

    The deduplicated-lookup path uses this ONCE per step per table array:
    the forward gathers ``table[uids]`` (a compact, cache-resident block)
    and expands by ``seg``; the backward segment-sums the embedding grads by
    the SAME ``seg`` — one sort serves both directions instead of a dedupe
    in the update plus a full-width gather in the forward.  Capacity
    licensing matches :func:`dedupe_grads`.

    ``rows_per_line`` > 1 (fat-line tables, ``pallas_kernels.line_layout``):
    dedupe by LINE id instead of row id, AT NO EXTRA COST — the same single
    sort yields the line grouping.  Returns ``(ulines[C], seg[B],
    valid[C])`` where ``seg`` indexes the ``C x R`` line-slot space
    (``seg = line_slot * R + row % R``): the forward gathers whole lines
    and expands slot rows by ``seg``; the update segment-sums grads by the
    SAME ``seg`` into exactly the kernel's packed operand layout.  Negative
    ids map to slot 0 of the sentinel line (gathers row 0 after clamping —
    identical to the default lookup's clip — and the kernel drops the
    sentinel line's update).  ``capacity``/``vocab``/``max_distinct`` then
    bound distinct LINES.
    """
    b = ids.shape[0]
    capacity = capacity or b
    r = rows_per_line
    vocab_bound = None if vocab is None else -(-vocab // r)
    if (capacity < b and (vocab_bound is None or capacity < vocab_bound)
            and (max_distinct is None or capacity < max_distinct)):
        raise ValueError(
            f"dedupe_ids: capacity {capacity} < batch {b} needs a static "
            f"bound (vocab or max_distinct <= capacity); got vocab={vocab}, "
            f"max_distinct={max_distinct}, rows_per_line={r}"
        )
    return _dedupe_ids_impl(ids, capacity, r)


def _dedupe_ids_impl(ids, capacity, r: int = 1):
    # Single-sort formulation (measured 3.2x the jnp.unique + sort-method
    # searchsorted pipeline on v5e: 0.24 ms vs 0.78 ms at B=16384): one
    # payload sort ranks the ids, a cumsum over the first-occurrence mask
    # assigns each sorted position its unique slot, and a second pair-sort
    # carries the slot back to the original position.  ``seg`` equals what
    # searchsorted(unique(clean), clean) would produce, so the segment_sum
    # is bit-identical to the textbook pipeline.  Unstable sorts are safe:
    # equal ids share a slot regardless of their relative order.  With
    # r > 1 the grouping key is the LINE id (ids are sorted, so line ids
    # are too) and ``seg`` carries the line-slot index — the whole fat-line
    # operand transform rides the same two sorts.
    b = ids.shape[0]
    oob = jnp.asarray(jnp.iinfo(ids.dtype).max, ids.dtype)
    clean = jnp.where(ids >= 0, ids, oob)
    iota = jnp.arange(b, dtype=jnp.int32)
    sorted_ids, order = jax.lax.sort((clean, iota), num_keys=1, is_stable=False)
    ok = sorted_ids < oob
    key = jnp.where(ok, sorted_ids // r, oob) if r > 1 else sorted_ids
    slot = jnp.where(ok, sorted_ids % r, 0) if r > 1 else None
    first = jnp.concatenate([jnp.ones((1,), bool), key[1:] != key[:-1]])
    uidx = (jnp.cumsum(first) - 1).astype(jnp.int32)  # group slot per sorted pos
    segidx = uidx if r == 1 else uidx * r + slot
    _, seg = jax.lax.sort((order, segidx), num_keys=1, is_stable=False)
    # slot s holds the key ranked s; slots past the distinct count keep the
    # sentinel (and, when capacity < distinct — licensed by a static bound
    # only — the overflow writes/segments are dropped, never misdirected)
    uids = jnp.full((capacity,), oob, ids.dtype).at[uidx].set(key, mode="drop")
    valid = uids < oob
    return uids, seg, valid


def _masked_scatter_rows(table: jax.Array, uids: jax.Array, new_rows: jax.Array,
                         valid: jax.Array) -> jax.Array:
    """Write new_rows into table[uids]; padding slots carry an out-of-bounds
    id and are dropped by the scatter."""
    del valid  # encoded in uids: invalid slots are out of bounds
    return table.at[uids].set(new_rows, mode="drop")


def _gather_rows_f32(table, uids, qscale):
    """Touched-row gather, widened to f32 AFTER the gather.  int8 tables
    (``qscale`` is the f32 [V, 2] (scale, offset) sidecar) gather the
    matching sidecar rows and decode through the STORED grid."""
    if qscale is None:
        return table[uids].astype(jnp.float32)
    return dequantize_rows(table[uids], qscale[uids])


def _requantize_scatter(table, qscale, uids, new_rows, valid, key):
    """Write updated f32 rows back at the table's storage dtype.  Plain
    path: :func:`quantize` + one scatter (returns ``(table, None)``).  int8
    path: the row grid is recomputed from the NEW values
    (:func:`quantize_rows` — fbgemm rowwise requantize semantics) and both
    the codes and the sidecar scatter."""
    if qscale is None:
        return _masked_scatter_rows(
            table, uids, quantize(new_rows, table.dtype, key), valid), None
    data, qs = quantize_rows(new_rows, key)
    return (_masked_scatter_rows(table, uids, data, valid),
            _masked_scatter_rows(qscale, uids, qs, valid))


def sparse_sgd(table, uids, g, valid, *, lr: float, weight_decay: float = 0.0,
               sr_key=None, qscale=None):
    """fbgemm EXACT_SGD parity: touched rows only, wd applied to touched rows.

    Storage dtype discipline (all ``sparse_*``/``dense_lazy_*`` functions):
    gathered rows widen to f32, ALL math runs f32, and only the final write
    requantizes (:func:`tdfo_tpu.ops.quant.quantize` — stochastic rounding
    when ``sr_key`` is given and the table stores narrow; a plain identity
    astype for f32 tables, keeping the default path byte-identical).  int8
    tables pass their (scale, offset) sidecar as ``qscale`` and get
    ``(table, qscale)`` back."""
    rows = _gather_rows_f32(table, uids, qscale)
    g = g.astype(jnp.float32) + weight_decay * rows
    table, qscale = _requantize_scatter(table, qscale, uids, rows - lr * g,
                                        valid, sr_key)
    return table if qscale is None else (table, qscale)


def sparse_adam(table, mu, nu, count, uids, g, valid, *, lr, b1=0.9, b2=0.999,
                eps=1e-8, weight_decay=0.0, sr_key=None, qscale=None):
    """Row-sparse AdamW: moments exist per-row; bias correction uses a global
    step count (matches fbgemm ADAM; per-row counts differ negligibly and a
    global count is what optax uses for the dense path).

    ``weight_decay`` is decoupled (AdamW) and only touches gathered rows —
    fbgemm semantics, NOT optax's full-table decay.
    Returns (table, mu, nu, count), + qscale when given (int8 tables; the
    moment slots stay at ``slot_dtype`` — only the table rides int8).
    """
    rows = _gather_rows_f32(table, uids, qscale)
    mu_r = mu[uids].astype(jnp.float32)
    nu_r = nu[uids].astype(jnp.float32)
    g = g.astype(jnp.float32)
    new_count = count + 1
    t = new_count.astype(jnp.float32)
    mu_n = b1 * mu_r + (1 - b1) * g
    nu_n = b2 * nu_r + (1 - b2) * g * g
    mu_hat = mu_n / (1 - b1**t)
    nu_hat = nu_n / (1 - b2**t)
    delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * rows)
    table, qscale = _requantize_scatter(
        table, qscale, uids, rows - delta, valid, component_key(sr_key, 0))
    out = (
        table,
        _masked_scatter_rows(
            mu, uids, quantize(mu_n, mu.dtype, component_key(sr_key, 1)),
            valid),
        _masked_scatter_rows(
            nu, uids, quantize(nu_n, nu.dtype, component_key(sr_key, 2)),
            valid),
        new_count,
    )
    return out if qscale is None else out + (qscale,)


def sparse_rowwise_adagrad(table, accum, uids, g, valid, *, lr, eps=1e-10,
                           weight_decay=0.0, sr_key=None, qscale=None):
    """fbgemm EXACT_ROWWISE_ADAGRAD parity: ONE f32 accumulator PER ROW
    (mean of squared grads), not per element — optimizer state is V x 4
    bytes instead of V x D x 8, which is what lets a v5e hold a 4x10^8-row
    dim-8 table WITH adaptive-optimizer semantics (fbgemm's default choice
    for huge tables; ``torchrec/train.py:191`` uses ADAM but fbgemm's TBE
    rowwise variant is the >=1B-row configuration).
    """
    rows = _gather_rows_f32(table, uids, qscale)
    acc_r = accum[uids]  # [U] — ALWAYS f32 (the fbgemm parity contract)
    g = g.astype(jnp.float32) + weight_decay * rows
    acc_n = acc_r + jnp.mean(g * g, axis=-1)
    delta = lr * g / (jnp.sqrt(acc_n)[:, None] + eps)
    table, qscale = _requantize_scatter(
        table, qscale, uids, rows - delta, valid, component_key(sr_key, 0))
    out = (table, _masked_scatter_rows(accum, uids, acc_n, valid))
    return out if qscale is None else out + (qscale,)


def sparse_adagrad(table, accum, uids, g, valid, *, lr, eps=1e-10,
                   weight_decay=0.0, sr_key=None, qscale=None):
    """fbgemm EXACT_ADAGRAD parity (row-wise accumulator of squared grads)."""
    rows = _gather_rows_f32(table, uids, qscale)
    acc_r = accum[uids].astype(jnp.float32)
    g = g.astype(jnp.float32) + weight_decay * rows
    acc_n = acc_r + g * g
    delta = lr * g / (jnp.sqrt(acc_n) + eps)
    table, qscale = _requantize_scatter(
        table, qscale, uids, rows - delta, valid, component_key(sr_key, 0))
    out = (
        table,
        _masked_scatter_rows(
            accum, uids,
            quantize(acc_n, accum.dtype, component_key(sr_key, 1)), valid),
    )
    return out if qscale is None else out + (qscale,)


def dense_lazy_adam(table, mu, nu, count, ids, grads, *, lr, b1=0.9, b2=0.999,
                    eps=1e-8, weight_decay=0.0, sr_key=None):
    """Small-vocab tier: lazy Adam via one-hot MXU matmuls + a dense masked
    sweep.  Per-row gradient sums and touched-row counts come from a single
    ``one_hot.T @ grads`` contraction (XLA fuses the one-hot generation into
    the matmul — nothing [B, V]-sized is materialised), then table/mu/nu get
    a full [V, D] read-modify-write.  For V up to ~16k this is dramatically
    faster on TPU than any gather/scatter formulation (XLA scatter serialises
    per row: ~1.4 ms for 8k rows on v5e vs ~100 us here), and there is no
    sort, no dedupe, no scatter at all.  Negative (padding) ids one-hot to
    zero rows, so they contribute nothing and count as untouched.

    Semantics are identical to :func:`sparse_adam` (lazy moments: untouched
    rows do not decay; decoupled weight decay on touched rows; global-step
    bias correction).  Returns (table, mu, nu, count).
    """
    gsum, touched = _one_hot_gsum(table, ids, grads)
    new_count = count + 1
    t = new_count.astype(jnp.float32)
    tf = table.astype(jnp.float32)
    mu_n = b1 * mu.astype(jnp.float32) + (1 - b1) * gsum
    nu_n = b2 * nu.astype(jnp.float32) + (1 - b2) * gsum * gsum
    mu_hat = mu_n / (1 - b1**t)
    nu_hat = nu_n / (1 - b2**t)
    delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * tf)
    return (
        jnp.where(touched,
                  quantize(tf - delta, table.dtype, component_key(sr_key, 0)),
                  table),
        jnp.where(touched,
                  quantize(mu_n, mu.dtype, component_key(sr_key, 1)), mu),
        jnp.where(touched,
                  quantize(nu_n, nu.dtype, component_key(sr_key, 2)), nu),
        new_count,
    )


def _one_hot_gsum(table, ids, grads):
    """Shared front half of the dense lazy tier: per-row summed grads and the
    touched mask via ONE ``one_hot.T @ grads`` contraction (XLA fuses the
    one-hot away — nothing [B, V] materialises; ~100-350 us on v5e for
    vocabs 5k-16k vs ~170 ns PER ROW for a scatter).  Negative (padding)
    ids one-hot to zero rows: zero grad mass, untouched.  Returns
    ``(gsum[V, D] f32, touched[V, 1] bool)``."""
    v = table.shape[0]
    ids = ids.reshape(-1)
    grads = grads.reshape(-1, grads.shape[-1]).astype(jnp.float32)
    oh = jax.nn.one_hot(ids, v, dtype=jnp.float32)  # [B, V], fused into dots
    gsum = jax.lax.dot_general(
        oh, grads, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [V, D]
    touched = (jnp.sum(oh, axis=0) > 0)[:, None]  # [V, 1]
    return gsum, touched


def dense_lazy_sgd(table, ids, grads, *, lr, weight_decay=0.0, sr_key=None):
    """Scatter-free SGD for SMALL tables (hot-head arrays, vocab <= ~16k):
    duplicate ids merge in the one-hot contraction, then the whole [V, D]
    table takes one masked read-modify-write.  Row semantics are identical
    to :func:`sparse_sgd` (weight decay folded into the summed grad of
    touched rows only).  Returns the new table."""
    gsum, touched = _one_hot_gsum(table, ids, grads)
    g = gsum + weight_decay * table.astype(jnp.float32)
    new = table.astype(jnp.float32) - lr * g
    return jnp.where(touched, quantize(new, table.dtype, sr_key), table)


def dense_lazy_adagrad(table, accum, ids, grads, *, lr, eps=1e-10,
                       weight_decay=0.0, sr_key=None):
    """Scatter-free EXACT_ADAGRAD (per-element accumulator) for small
    tables; row semantics identical to :func:`sparse_adagrad`.  Returns
    ``(table, accum)``."""
    gsum, touched = _one_hot_gsum(table, ids, grads)
    g = gsum + weight_decay * table.astype(jnp.float32)
    acc_n = accum.astype(jnp.float32) + g * g
    delta = lr * g / (jnp.sqrt(acc_n) + eps)
    return (
        jnp.where(touched,
                  quantize(table.astype(jnp.float32) - delta, table.dtype,
                           component_key(sr_key, 0)), table),
        jnp.where(touched,
                  quantize(acc_n, accum.dtype, component_key(sr_key, 1)),
                  accum),
    )


def dense_lazy_rowwise_adagrad(table, accum, ids, grads, *, lr, eps=1e-10,
                               weight_decay=0.0, sr_key=None):
    """Scatter-free EXACT_ROWWISE_ADAGRAD (ONE f32 accumulator per row) for
    small tables; row semantics identical to
    :func:`sparse_rowwise_adagrad`.  Returns ``(table, accum)``."""
    gsum, touched = _one_hot_gsum(table, ids, grads)
    g = gsum + weight_decay * table.astype(jnp.float32)
    acc_n = accum + jnp.mean(g * g, axis=-1)  # [V] — accum is always f32
    delta = lr * g / (jnp.sqrt(acc_n)[:, None] + eps)
    return (
        jnp.where(touched,
                  quantize(table.astype(jnp.float32) - delta, table.dtype,
                           component_key(sr_key, 0)), table),
        jnp.where(touched[:, 0], acc_n, accum),
    )


# --- device-resident update cache (software MANAGED_CACHING) ---------------
#
# fbgemm's cached TBE (``EmbeddingLocation.MANAGED_CACHING`` + ``lxu_cache``)
# rebuilt for a chip whose scatter costs ~60-110 ns/slot regardless of hints
# (docs/BUDGET.md): the step's touched rows live in a small dense cache —
# sorted-id directory, [C, d] value array, optimizer-slot mirrors, dirty mask,
# frequency/recency counters — all plain arrays carried in the train state.
# Misses are ADMITTED (a gather-only copy of the authoritative big-table row),
# hits and fresh admissions update IN the cache with the exact per-row
# ``sparse_*`` math, and dirty rows write back to the big table verbatim in
# ONE coalesced scatter at flush time.  Because the cached row is the
# authoritative value and flush copies bits, any (train -> flush) prefix
# reproduces the eager tables bit-for-bit; the per-slot scatter cost is paid
# once per flush interval instead of once per step.
#
# The directory is two [C] arrays: ``ids`` sorted ascending (int32-max
# sentinels = free entries, grouped at the top by the sort) and ``slot``, the
# physical row each directory entry owns (a permutation of [0, C) — value
# rows never move, only the id/slot pairs re-sort on admission/eviction).
# Membership is one ``searchsorted(method="sort")`` per step (~0.14 ms at 8k
# on v5e), branch-free.

_CACHE_OOB = 2**31 - 1  # int32 max: free-directory-entry / invalid sentinel


def cache_route(cache, ids):
    """Route ``ids`` (any shape, array-row space, negatives = padding)
    through the cache directory.  Returns ``(phys, hit)``: the physical
    cache row per id (``C`` — one past the end, gather-clamp/scatter-drop —
    where ``hit`` is False)."""
    cids = cache["ids"]
    c = cids.shape[0]
    pos = jnp.searchsorted(cids, ids, method="sort").astype(jnp.int32)
    posc = jnp.minimum(pos, c - 1)
    hit = (cids[posc] == ids) & (ids >= 0) & (ids < _CACHE_OOB)
    phys = jnp.where(hit, cache["slot"][posc], c)
    return phys, hit


def _replicated_shard_map(f, mesh):
    """Run ``f`` in manual-SPMD mode with every operand fully replicated.

    The cache's directory math (searchsorted routing, admission sorts, [C]
    scatters) is replicated state by contract, but under GSPMD the sharding
    PROPAGATION — not the committed input shardings, and not even explicit
    boundary ``with_sharding_constraint`` pins — decides the layout of every
    interior op, and it is free to partition the sort/scatter chain over the
    batch axis.  Observed: inside the fused train-step program the cache
    update's scatters are silently DROPPED when that happens (admission
    survives, ``dirty``/``freq``/row writes vanish).  A fully-replicated
    ``shard_map`` takes the partitioner out of the loop: every device runs
    the identical cache-sized computation on full copies."""
    from tdfo_tpu.core.mesh import shard_map

    from jax.sharding import PartitionSpec as P

    return shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                     check_vma=False)


def cache_lookup_rows(cache, ids, *, mesh=None):
    """Route ``ids`` and gather their cached rows: ``(rows[..., d],
    hit[...])``.  int8 caches (a ``qs`` (scale, offset) mirror present)
    return the rows DEQUANTIZED through the cached per-row grid — callers
    always see f32 values, same as the big-table lookup path.  Pass the
    device ``mesh`` from inside multi-device jitted programs so the route
    runs replicated (see :func:`_replicated_shard_map`); the gathered rows
    come back replicated and mix freely with sharded activations."""
    def f(cids, cslot, crows, q, *qs):
        phys, hit = cache_route({"ids": cids, "slot": cslot}, q)
        clamp = jnp.minimum(phys, crows.shape[0] - 1)
        cur = jnp.take(crows, clamp, axis=0)
        if qs:
            cur = dequantize_rows(cur, jnp.take(qs[0], clamp, axis=0))
        return cur, hit
    if mesh is not None:
        f = _replicated_shard_map(f, mesh)
    qs_ops = (cache["qs"],) if "qs" in cache else ()
    return f(cache["ids"], cache["slot"], cache["rows"], ids, *qs_ops)


def cache_overlay_rows(cache, ids, rows, *, mesh=None):
    """Serve cached rows into a gathered block: where ``ids`` hit the
    directory, replace ``rows`` (``[..., d]``, gathered from the possibly
    stale big table) with the authoritative cache value.  Gather-only —
    this is what keeps the forward bit-identical to the eager path between
    flushes."""
    cur, hit = cache_lookup_rows(cache, ids, mesh=mesh)
    return jnp.where(hit[..., None], cur.astype(rows.dtype), rows)


def _cache_mirror_keys(kind):
    """Optimizer-slot mirror keys carried per cached row."""
    return {"sgd": (), "adagrad": ("acc",), "rowwise_adagrad": ("acc",),
            "adam": ("mu", "nu")}[kind]


def _cache_slot_mirror(key, kind, c, d, slot_dtype):
    """Empty [C]-leading mirror of the big-table slot component ``key``."""
    if kind == "rowwise_adagrad":
        # ONE f32 accumulator per row (the fbgemm parity contract)
        return jnp.zeros((c,), jnp.float32)
    return jnp.zeros((c, d), jnp.dtype(slot_dtype))


def _cache_gather_slot(key, slots, kind, src):
    big = {"acc": 0, "mu": 0, "nu": 1}[key] if kind != "rowwise_adagrad" else 0
    return jnp.take(slots[big], src, axis=0)


def _cache_admit(cache, urows, uslot, uids, valid, kind, step, uqs=None):
    """Admit every missing valid ``uid``: assign free physical slots, copy
    the authoritative rows + slot mirrors from the PRE-GATHERED per-uid
    blocks (``urows[U, d]`` / ``uslot`` — the big arrays never enter: their
    gathers happen outside, where GSPMD partitions plain gathers
    correctly), and re-sort the directory.  int8 caches also bit-copy the
    per-row (scale, offset) pairs (``uqs``, gathered from the table's
    sidecar) into the ``qs`` mirror — admission copies bits, it never
    re-grids.  Distinct ids past the free capacity are counted into the
    ``over`` counter — their updates would be silently lost, so callers
    must treat a non-zero counter as a hard error."""
    c = cache["ids"].shape[0]
    cids, cslot = cache["ids"], cache["slot"]
    _, hit = cache_route(cache, uids)
    miss = valid & ~hit
    oob = jnp.asarray(_CACHE_OOB, jnp.int32)
    # pair-sort carries each missing id's position in ``uids`` along, so
    # the pre-gathered row/mirror blocks index by ``upos`` (order-free: no
    # sortedness assumption on ``uids``)
    smid, upos = jax.lax.sort(
        (jnp.where(miss, uids, oob),
         jnp.arange(uids.shape[0], dtype=jnp.int32)),
        num_keys=1, is_stable=False)
    n_miss = jnp.sum(miss).astype(jnp.int32)
    n_used = jnp.sum(cids < oob).astype(jnp.int32)
    k = jnp.arange(smid.shape[0], dtype=jnp.int32)
    dirpos = n_used + k
    admit = (k < n_miss) & (dirpos < c)
    over = jnp.sum((k < n_miss) & (dirpos >= c)).astype(jnp.int32)
    # the k-th new id takes the k-th free directory entry (free entries are
    # the sentinel-id tail of the sorted directory) and inherits its
    # physical slot; one pair-sort restores directory order
    phys = cslot[jnp.minimum(dirpos, c - 1)]
    new_ids = cids.at[jnp.where(admit, dirpos, c)].set(smid, mode="drop")
    sids, sslot = jax.lax.sort((new_ids, cslot), num_keys=1, is_stable=False)
    tgt = jnp.where(admit, phys, c)
    cache = dict(cache)
    cache["ids"], cache["slot"] = sids, sslot
    cache["rows"] = cache["rows"].at[tgt].set(
        jnp.take(urows, upos, axis=0), mode="drop")
    if uqs is not None:
        cache["qs"] = cache["qs"].at[tgt].set(
            jnp.take(uqs, upos, axis=0), mode="drop")
    for key in _cache_mirror_keys(kind):
        cache[key] = cache[key].at[tgt].set(
            jnp.take(uslot[key], upos, axis=0), mode="drop")
    cache["dirty"] = cache["dirty"].at[tgt].set(False, mode="drop")
    cache["freq"] = cache["freq"].at[tgt].set(0, mode="drop")
    cache["last"] = cache["last"].at[tgt].set(step, mode="drop")
    cache["over"] = cache["over"] + over
    return cache


def _lines_from_unique(uids, g, valid, layout):
    """Row-level uniques -> line-level kernel operands.

    ``uids`` arrive SORTED ascending with sentinels (int32 max) grouped at
    the top (the :func:`dedupe_grads` contract), so their line ids are also
    sorted — a first-occurrence mask + cumsum assigns line slots WITHOUT a
    second sort.  Returns ``(ulines[C], g_slots[C, R, d], touched[C, R])``
    where C is the row capacity (an upper bound on distinct lines; surplus
    slots carry the sentinel and the kernel skips their DMAs entirely).
    """
    r = layout.r
    cap = uids.shape[0]
    oob = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    uids = uids.astype(jnp.int32)
    line = jnp.where(valid, uids // r, oob)
    slot = jnp.where(valid, uids % r, 0)
    first = jnp.concatenate([jnp.ones((1,), bool), line[1:] != line[:-1]])
    lidx = (jnp.cumsum(first) - 1).astype(jnp.int32)
    ulines = jnp.full((cap,), oob, jnp.int32).at[lidx].set(line, mode="drop")
    # all sentinel rows share one line id -> one slot, which stays oob
    seg2 = jnp.where(valid, lidx * r + slot, cap * r)  # invalid -> dropped
    g_slots = jax.ops.segment_sum(
        g.astype(jnp.float32), seg2, num_segments=cap * r
    ).reshape(cap, r, -1)
    touched = (jax.ops.segment_sum(
        valid.astype(jnp.float32), seg2, num_segments=cap * r
    ) > 0).astype(jnp.float32).reshape(cap, r)
    return ulines, g_slots, touched


def _pack_lanes(g_slots, touched, layout):
    """[C, R, d] grads + [C, R] touched -> [C, T, 128] packed-lane operands
    (grads at table lanes, zeros elsewhere; touched broadcast slot-wide)."""
    cap, r, d = g_slots.shape
    gp = g_slots
    if layout.w > d:
        gp = jnp.concatenate(
            [gp, jnp.zeros((cap, r, layout.w - d), jnp.float32)], axis=-1
        )
    gp = gp.reshape(cap, layout.tiles, 128)
    tl = jnp.broadcast_to(
        touched[:, :, None], (cap, r, layout.w)
    ).reshape(cap, layout.tiles, 128)
    return gp, tl


def _fat_apply_lines_xla(fat, ulines, g_slots, touched, *, layout, lr, b1,
                         b2, eps, weight_decay, new_count=None, sr_key=None):
    """Portable line-level formulation: gather every slot row of the
    touched lines through the [L*R, W] view, apply the per-row optimizer
    math (bit-identical to the plain-table ``sparse_*`` functions) gated by
    ``touched``, scatter back.  CPU/test path; the TPU path is the in-place
    DMA kernel."""
    from tdfo_tpu.ops.pallas_kernels import fat_view

    d, r = layout.d, layout.r
    n_lines = fat.shape[0]
    view = fat_view(fat, layout)
    # sentinel lines (int32 max) redirect past the view: gather clamps
    # (values unused — touched is 0 there), scatter drops
    base = jnp.where(ulines < n_lines, ulines, n_lines).astype(jnp.int32)
    idx = (base[:, None] * r + jnp.arange(r, dtype=jnp.int32)[None, :]).reshape(-1)
    rows_full = jnp.take(view, jnp.minimum(idx, view.shape[0] - 1), axis=0)
    rows_full = rows_full.astype(jnp.float32)  # widen AFTER the gather
    table = rows_full[:, :d]
    g = g_slots.astype(jnp.float32)
    kind = layout.kind
    if kind == "sgd":
        g2 = g + weight_decay * table
        parts = {0: table - lr * g2}
    elif kind == "rowwise_adagrad":
        acc = rows_full[:, d]
        g2 = g + weight_decay * table
        acc_n = acc + jnp.mean(g2 * g2, axis=-1)
        delta = lr * g2 / (jnp.sqrt(acc_n)[:, None] + eps)
        parts = {0: table - delta, d: acc_n[:, None]}
    elif kind == "adagrad":
        acc = rows_full[:, d:2 * d]
        g2 = g + weight_decay * table
        acc_n = acc + g2 * g2
        delta = lr * g2 / (jnp.sqrt(acc_n) + eps)
        parts = {0: table - delta, d: acc_n}
    else:  # adam
        mu, nu = rows_full[:, d:2 * d], rows_full[:, 2 * d:3 * d]
        t = new_count.astype(jnp.float32)
        mu_n = b1 * mu + (1 - b1) * g
        nu_n = b2 * nu + (1 - b2) * g * g
        mu_hat = mu_n / (1 - b1**t)
        nu_hat = nu_n / (1 - b2**t)
        delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * table)
        parts = {0: table - delta, d: mu_n, 2 * d: nu_n}
    new_rows = rows_full
    for off, comp in parts.items():
        new_rows = jax.lax.dynamic_update_slice_in_dim(new_rows, comp, off, axis=1)
    new_rows = jnp.where(touched.reshape(-1)[:, None] > 0, new_rows, rows_full)
    # whole-block requantize: untouched rows are exactly representable, so
    # stochastic rounding is an identity on them (ops/quant.py bit trick)
    new_rows = quantize(new_rows, fat.dtype, sr_key)
    return view.at[idx].set(new_rows, mode="drop").reshape(fat.shape)


def dedupe_rows_and_lines(ids, *, capacity_rows: int, capacity_lines: int,
                          rows_per_line: int):
    """Row- AND line-level dedupe from ONE sort pass (the fat-line routed
    path): ``ids[B] -> (seg_row[B], ulines[CL], row_lidx[CR], row_slot[CR])``.

    ``seg_row`` maps each batch position to its distinct-row slot (the
    forward expand / backward row segment-sum key — the CHEAP segment
    space); ``ulines`` are the distinct line ids (sorted, int32-max
    sentinels at the top); ``row_lidx``/``row_slot`` give each distinct
    row's line slot and within-line slot (``capacity_lines`` fills unused
    row slots so they route past every real line).  Negative ids group
    under the sentinel line with slot 0, so they gather row 0 (default-path
    clip parity) and their update drops with the sentinel line.
    """
    b = ids.shape[0]
    r = rows_per_line
    oob = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    ids = ids.astype(jnp.int32)
    clean = jnp.where(ids >= 0, ids, oob)
    iota = jnp.arange(b, dtype=jnp.int32)
    sorted_ids, order = jax.lax.sort((clean, iota), num_keys=1, is_stable=False)
    ok = sorted_ids < oob
    first_r = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]])
    uidx = (jnp.cumsum(first_r) - 1).astype(jnp.int32)
    line = jnp.where(ok, sorted_ids // r, oob)
    slot = jnp.where(ok, sorted_ids % r, 0)
    first_l = jnp.concatenate([jnp.ones((1,), bool), line[1:] != line[:-1]])
    lidx = (jnp.cumsum(first_l) - 1).astype(jnp.int32)
    _, seg_row = jax.lax.sort((order, uidx), num_keys=1, is_stable=False)
    ulines = jnp.full((capacity_lines,), oob, jnp.int32).at[lidx].set(
        line, mode="drop")
    row_lidx = jnp.full((capacity_rows,), capacity_lines, jnp.int32).at[
        uidx].set(lidx, mode="drop")
    row_slot = jnp.zeros((capacity_rows,), jnp.int32).at[uidx].set(
        slot, mode="drop")
    return seg_row, ulines, row_lidx, row_slot


def _fat_apply_rows_int8(fat, uids, g, *, layout, lr, b1=0.9, b2=0.999,
                         eps=1e-8, weight_decay=0.0, new_count=None,
                         sr_key=None):
    """ROW-space optimizer step on int8 byte-container fat lines.

    The line-space XLA formulation cannot serve int8: ``quantize_rows``'
    stochastic draw covers the whole operand block, so bit-parity with the
    plain-int8 reference requires calling it on the SAME ``[U, d]``
    uids-ordered block with the SAME key — which is exactly what this
    function does.  Gather the touched byte rows through the ``[L*R, W]``
    view, decode (codes x sidecar -> f32 rows, state bytes -> exact f32),
    run the ``sparse_*``-identical math, requantize the new rows
    (:func:`quantize_rows`, fbgemm rowwise requantize semantics — raw key
    for sgd, ``component_key(key, 0)`` otherwise, mirroring
    :func:`_requantize_scatter` callers), re-encode, scatter the rows back.
    Sentinel uids (int32 max) clamp on the gather and drop on the scatter.
    The flattening view reshape materialises on TPU (docs/BUDGET.md prices
    it); the in-place DMA kernel does not cover int8 lines yet."""
    from tdfo_tpu.ops.pallas_kernels import fat_view
    from tdfo_tpu.ops.quant import bytes_to_f32, f32_to_bytes

    d = layout.d
    view = fat_view(fat, layout)
    safe = jnp.minimum(jnp.maximum(uids, 0), view.shape[0] - 1)
    rows_b = jnp.take(view, safe, axis=0)  # [U, W] bytes
    codes = rows_b[:, :d]
    qs = bytes_to_f32(rows_b[:, d:d + 8])
    rows = dequantize_rows(codes, qs)
    g = g.astype(jnp.float32)
    kind = layout.kind
    if kind == "sgd":
        g2 = g + weight_decay * rows
        new_rows = rows - lr * g2
        key_t = sr_key  # sparse_sgd passes the raw step key
        state_new = ()
    elif kind == "adagrad":
        acc = bytes_to_f32(rows_b[:, d + 8:d + 8 + 4 * d])
        g2 = g + weight_decay * rows
        acc_n = acc + g2 * g2
        delta = lr * g2 / (jnp.sqrt(acc_n) + eps)
        new_rows = rows - delta
        key_t = component_key(sr_key, 0)
        state_new = (acc_n,)
    elif kind == "adam":
        mu = bytes_to_f32(rows_b[:, d + 8:d + 8 + 4 * d])
        nu = bytes_to_f32(rows_b[:, d + 8 + 4 * d:d + 8 + 8 * d])
        t = new_count.astype(jnp.float32)
        mu_n = b1 * mu + (1 - b1) * g
        nu_n = b2 * nu + (1 - b2) * g * g
        mu_hat = mu_n / (1 - b1**t)
        nu_hat = nu_n / (1 - b2**t)
        delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * rows)
        new_rows = rows - delta
        key_t = component_key(sr_key, 0)
        state_new = (mu_n, nu_n)
    else:  # rowwise_adagrad never builds an int8 layout (line_layout refuses)
        raise ValueError(kind)
    new_codes, new_qs = quantize_rows(new_rows, key_t)
    comps = [new_codes, f32_to_bytes(new_qs)]
    comps += [f32_to_bytes(s) for s in state_new]
    if layout.w > layout.need:
        comps.append(rows_b[:, layout.need:])  # preserve the zero pad bytes
    new_b = jnp.concatenate(comps, axis=1)
    return view.at[uids].set(new_b, mode="drop").reshape(fat.shape)


def _fat_apply_int8(fat, slots, uids, g, *, layout, lr, b1, b2, eps,
                    weight_decay, sr_key=None):
    """Slot bookkeeping around :func:`_fat_apply_rows_int8` (adam's global
    bias-correction count is the only out-of-line state).  Returns
    ``(fat, slots)``."""
    if layout.kind == "adam":
        (count,) = slots
        new_count = count + 1
        new_slots = (new_count,)
    else:
        new_count = None
        new_slots = slots
    fat = _fat_apply_rows_int8(
        fat, uids, g, layout=layout, lr=lr, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, new_count=new_count, sr_key=sr_key)
    return fat, new_slots


def _kernel_seed(sr_key, dtype):
    """Scalar int32 stochastic-rounding seed for the fat-line kernels
    (None = no SR: f32 storage, or no key -> round-to-nearest)."""
    if sr_key is None or jnp.dtype(dtype) == jnp.float32:
        return None
    return jax.random.randint(sr_key, (), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)


def fat_apply_routed(fat, slots, ulines, g_u, row_lidx, row_slot, lines, *,
                     embedding_dim, kind, lr, b1=0.9, b2=0.999, eps=1e-8,
                     weight_decay=0.0, interpret: bool = False, sr_key=None):
    """Fused fat-line step on ROW-level summed grads + routing info from
    :func:`dedupe_rows_and_lines` — the fastest update path: the expensive
    C x R slot-space segment-sum never exists; the kernel routes window
    rows into packed lanes itself, and ``lines`` (the forward's gather of
    the touched lines, [C, T, 128] in ulines order) spares it every read
    DMA.  Returns ``(fat, slots)``."""
    from tdfo_tpu.ops.pallas_kernels import (
        fat_line_update_routed,
        line_layout,
    )

    layout = line_layout(embedding_dim, kind, fat.dtype)
    r = layout.r
    cl = ulines.shape[0]
    cr = g_u.shape[0]
    if layout.dtype == "int8":
        # reconstruct the sorted distinct ROW ids from the routing arrays
        # (uids order == the plain path's dedupe rank order, which is what
        # makes the requantize draw bit-identical); slots past the real
        # lines keep the int32-max sentinel so their writes drop
        oob = jnp.iinfo(jnp.int32).max
        uids = jnp.where(
            row_lidx < cl,
            jnp.take(ulines, jnp.minimum(row_lidx, cl - 1)) * r + row_slot,
            oob)
        return _fat_apply_int8(
            fat, slots, uids, g_u, layout=layout, lr=lr, b1=b1, b2=b2,
            eps=eps, weight_decay=weight_decay, sr_key=sr_key)
    if kind == "adam":
        (count,) = slots
        new_count = count + 1
        t = new_count.astype(jnp.float32)
        corr = jnp.stack([1.0 - b1**t, 1.0 - b2**t])
        new_slots = (new_count,)
    else:
        new_count = None
        corr = jnp.zeros((2,), jnp.float32)
        new_slots = slots
    if layout.d <= 128 and (jax.default_backend() == "tpu" or interpret):
        from tdfo_tpu.ops.pallas_kernels import routed_lines_per_step

        oob = jnp.iinfo(jnp.int32).max
        lines_per_step = routed_lines_per_step(layout)
        cl_pad = -(-cl // lines_per_step) * lines_per_step
        nblocks = cl_pad // lines_per_step
        rpb = lines_per_step * r
        ulines_p = jnp.pad(ulines, (0, cl_pad - cl), constant_values=oob)
        lines_p = jnp.pad(lines.astype(jnp.float32),
                          ((0, cl_pad - cl), (0, 0), (0, 0)))
        # row ranges per block: row_lidx is non-decreasing (sorted uniques)
        block_start = jnp.searchsorted(
            row_lidx, jnp.arange(nblocks, dtype=jnp.int32) * lines_per_step,
            method="sort",
        ).astype(jnp.int32)
        sdiv = block_start // rpb
        rows_pad = (cr // rpb + 2) * rpb
        # lane-pad to 128: the kernel's window DMA source is (1,128)-tiled
        g_pad = jnp.pad(g_u.astype(jnp.float32),
                        ((0, rows_pad - cr), (0, 128 - g_u.shape[1])))
        slotidx = jnp.pad(
            jnp.minimum(row_lidx, cl) * r + row_slot,
            (0, rows_pad - cr), constant_values=jnp.int32(cl) * r,
        )
        gk = sdiv[:, None] * rpb + jnp.arange(2 * rpb, dtype=jnp.int32)[None, :]
        tsi = (jnp.take(slotidx, jnp.minimum(gk, rows_pad - 1), axis=0)
               - (jnp.arange(nblocks, dtype=jnp.int32) * rpb)[:, None])
        # 8-sublane broadcast: a (1, 2RPB) block is not Mosaic-tileable
        tsi = jnp.broadcast_to(tsi[:, None, :], (nblocks, 8, 2 * rpb))
        fat = fat_line_update_routed(
            fat, lines_p, ulines_p, sdiv, tsi, g_pad, corr, layout=layout,
            lr=lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
            interpret=interpret, sr_seed=_kernel_seed(sr_key, fat.dtype),
        )
        return fat, new_slots
    # XLA fallback: construct the line-slot operands by (cheap on CPU)
    # scatter, then share the verified line-level formulation
    slotidx = jnp.minimum(row_lidx, cl).astype(jnp.int32) * r + row_slot
    slotidx = jnp.where(row_lidx < cl, slotidx, cl * r)  # padding -> dropped
    g_slots = jnp.zeros((cl * r, g_u.shape[1]), jnp.float32).at[slotidx].set(
        g_u.astype(jnp.float32), mode="drop")
    touched = jnp.zeros((cl * r,), jnp.float32).at[slotidx].set(
        1.0, mode="drop")
    fat = _fat_apply_lines_xla(
        fat, ulines, g_slots, touched, layout=layout, lr=lr, b1=b1, b2=b2,
        eps=eps, weight_decay=weight_decay, new_count=new_count,
        sr_key=sr_key,
    )
    return fat, new_slots


def _fat_apply_lines(fat, slots, ulines, g_slots, touched, *, layout, lr,
                     b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                     interpret: bool = False, sr_key=None):
    """Shared line-level dispatch: kernel on TPU (or interpret), XLA
    formulation elsewhere.  ``g_slots``: [C*R, d] summed grads in line-slot
    order; ``touched``: [C*R] occupancy (any dtype, > 0 = touched).
    Returns ``(fat, slots)``."""
    from tdfo_tpu.ops.pallas_kernels import fat_line_update

    kind = layout.kind
    if kind == "adam":
        (count,) = slots
        new_count = count + 1
        t = new_count.astype(jnp.float32)
        corr = jnp.stack([1.0 - b1**t, 1.0 - b2**t])
        new_slots = (new_count,)
    else:
        new_count = None
        corr = jnp.zeros((2,), jnp.float32)
        new_slots = slots
    c = ulines.shape[0]
    g_slots = g_slots.reshape(c, layout.r, -1)
    if touched is None:
        # R == 1 licence: one row per line, so every valid line is touched
        # (kernel write-skip / fallback line-drop subsume the mask)
        assert layout.r == 1, "touched=None requires rows_per_line == 1"
        touched_f = (ulines < fat.shape[0]).astype(jnp.float32)[:, None]
    else:
        touched_f = (touched.reshape(c, layout.r) > 0).astype(jnp.float32)
    # d > 128 lines span 4+ tiles — rare configs with no on-chip coverage;
    # keep them on the proven XLA formulation (the pre-existing guard)
    if layout.d <= 128 and (jax.default_backend() == "tpu" or interpret):
        sr_seed = _kernel_seed(sr_key, fat.dtype)
        if layout.r == 1:
            # row-form operands: stream d lanes per line, no touched mask
            fat = fat_line_update(
                fat, ulines, g_slots.reshape(c, -1).astype(jnp.float32),
                None, corr, layout=layout, lr=lr, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay, interpret=interpret,
                sr_seed=sr_seed,
            )
        else:
            gp, tl = _pack_lanes(g_slots.astype(jnp.float32), touched_f,
                                 layout)
            fat = fat_line_update(
                fat, ulines, gp, tl, corr, layout=layout, lr=lr, b1=b1,
                b2=b2, eps=eps, weight_decay=weight_decay,
                interpret=interpret, sr_seed=sr_seed,
            )
    else:
        fat = _fat_apply_lines_xla(
            fat, ulines, g_slots.reshape(c * layout.r, -1), touched_f,
            layout=layout, lr=lr, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, new_count=new_count, sr_key=sr_key,
        )
    return fat, new_slots


def fat_apply_unique(fat, slots, uids, g, valid=None, *, embedding_dim, kind,
                     lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                     interpret: bool = False, sr_key=None):
    """Fused fat-line optimizer step on PRE-deduplicated row-level
    ``(uids, g)``.  ``uids`` must be sorted ascending with int32-max
    sentinels at the top (the :func:`dedupe_grads` layout) — the line
    grouping then needs no extra sort.  Returns ``(fat, slots)``.

    Prefer the routed path (``dedupe_rows_and_lines`` +
    ``SparseOptimizer.update_routed``) in hot steps: it skips the
    row->line scatters entirely.
    """
    from tdfo_tpu.ops.pallas_kernels import line_layout

    layout = line_layout(embedding_dim, kind, fat.dtype)
    if layout.dtype == "int8":
        return _fat_apply_int8(
            fat, slots, uids, g, layout=layout, lr=lr, b1=b1, b2=b2,
            eps=eps, weight_decay=weight_decay, sr_key=sr_key)
    if valid is None:
        valid = uids < jnp.iinfo(jnp.int32).max
    ulines, g_slots, touched = _lines_from_unique(uids, g, valid, layout)
    return _fat_apply_lines(
        fat, slots, ulines, g_slots.reshape(-1, g_slots.shape[-1]),
        touched.reshape(-1), layout=layout, lr=lr, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, interpret=interpret, sr_key=sr_key,
    )


def fat_update(fat, slots, ids, grads, *, embedding_dim, kind, lr, b1=0.9,
               b2=0.999, eps=1e-8, weight_decay=0.0,
               capacity: int | None = None, max_distinct: int | None = None,
               interpret: bool = False, sr_key=None):
    """Big-table tier: fused in-backward optimizer over packed fat lines
    (``pallas_kernels.line_layout``) — fbgemm TBE parity for every
    ``EmbOptimType`` kind the framework exposes (adam / sgd / adagrad /
    rowwise_adagrad; ``torchrec/train.py:187-195``).

    One line-aware dedupe sort + one segment-sum produce the kernel
    operands directly (no row-level intermediate).  ``capacity`` /
    ``max_distinct`` bound distinct LINES here (a row bound is always a
    valid line bound); int8 byte-container lines dedupe in ROW space
    instead (the row-sparse requantize contract), so there they bound
    distinct rows.  Returns ``(fat, slots)``."""
    from tdfo_tpu.ops.pallas_kernels import line_layout

    layout = line_layout(embedding_dim, kind, fat.dtype)
    r = layout.r
    ids = ids.reshape(-1)
    grads = grads.reshape(-1, grads.shape[-1])
    if layout.dtype == "int8":
        uids, g, _valid = dedupe_grads(
            ids, grads, capacity=capacity, vocab=fat.shape[0] * r,
            max_distinct=max_distinct)
        return _fat_apply_int8(
            fat, slots, uids, g, layout=layout, lr=lr, b1=b1, b2=b2,
            eps=eps, weight_decay=weight_decay, sr_key=sr_key)
    ulines, seg, valid = dedupe_ids(
        ids, capacity=capacity, vocab=fat.shape[0] * r,
        max_distinct=max_distinct, rows_per_line=r,
    )
    c = ulines.shape[0]
    g_slots = jax.ops.segment_sum(
        grads.astype(jnp.float32), seg, num_segments=c * r
    )
    touched = None if r == 1 else jax.ops.segment_sum(
        (ids >= 0).astype(jnp.float32), seg, num_segments=c * r
    )
    return _fat_apply_lines(
        fat, slots, ulines, g_slots, touched, layout=layout, lr=lr, b1=b1,
        b2=b2, eps=eps, weight_decay=weight_decay, interpret=interpret,
        sr_key=sr_key,
    )


@dataclass(frozen=True)
class SparseOptimizer:
    """Uniform wrapper: init(table)->slots, update(table, slots, ids, grads)->(table, slots).

    The KeyedOptimizerWrapper/CombinedOptimizer equivalent for the sparse half
    (``torchrec/train.py:248-254``): dense params keep optax; each embedding
    table gets one of these.  Updates dispatch across three tiers picked for
    TPU cost structure (measured on v5e — XLA scatter serialises per row, so
    scatter-free formulations win):

      * fat-line storage (``table.ndim == 3``, ANY kind): in-place DMA
        kernel on packed lines — O(touched rows) traffic on tables of any
        size (the >=1B-row path, fbgemm fused-TBE parity);
      * plain storage, small vocab (<= ``small_vocab_threshold``, adam):
        one-hot MXU matmul + dense masked sweep, no sort/gather/scatter;
      * plain storage, large vocab: dedupe + row gather/scatter (the
        portable XLA formulation).
    """

    kind: str  # "sgd" | "adam" | "adagrad" | "rowwise_adagrad"
    lr: float
    weight_decay: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    small_vocab_threshold: int = 16384
    # STORAGE dtype of the adam/adagrad slot buffers of plain tables
    # ("float32" | "bfloat16"; fbgemm mixed-precision TBE parity).  Fat-line
    # tables pack state at the TABLE dtype; rowwise_adagrad's per-row
    # accumulator stays f32 regardless (the parity contract — config
    # rejects the bf16 combination).  Writes requantize via the same
    # ``sr_key`` stream as the tables.
    slot_dtype: str = "float32"

    def init(self, table: jax.Array) -> Any:
        if table.ndim == 3:  # fat lines carry their own optimizer state
            # adam keeps the global step count for bias correction; the
            # other kinds are fully self-contained in the packed rows
            return (jnp.zeros((), jnp.int32),) if self.kind == "adam" else ()
        sd = jnp.dtype(self.slot_dtype)
        if self.kind == "sgd":
            return ()
        if self.kind == "adagrad":
            return (jnp.zeros_like(table, dtype=sd),)
        if self.kind == "rowwise_adagrad":
            # ONE f32 cell per row: the state layout that scales to 1e9 rows
            # (always f32 — slot_dtype does not apply to this kind)
            return (jnp.zeros((table.shape[0],), jnp.float32),)
        if self.kind == "adam":
            return (
                jnp.zeros_like(table, dtype=sd),
                jnp.zeros_like(table, dtype=sd),
                jnp.zeros((), jnp.int32),
            )
        raise ValueError(f"unknown sparse optimizer kind: {self.kind!r}")

    def update_routed(self, table, slots, ulines, g_u, row_lidx, row_slot,
                      lines, *, embedding_dim: int, sr_key=None):
        """Fat-line fastest path: row-level summed grads + routing arrays
        from :func:`dedupe_rows_and_lines` (the dedup-lookup step shares
        ONE sort between the forward's line gather — whose result ``lines``
        the kernel reuses instead of re-reading — the row expand, and this
        update; the slot-space segment-sum never exists)."""
        if table.ndim != 3:
            raise ValueError("update_routed is the fat-line path")
        return fat_apply_routed(
            table, slots, ulines, g_u, row_lidx, row_slot, lines,
            embedding_dim=embedding_dim, kind=self.kind, lr=self.lr,
            b1=self.b1, b2=self.b2, eps=self.eps,
            weight_decay=self.weight_decay, sr_key=sr_key,
        )

    def update_unique(self, table, slots, uids, g, valid, *,
                      embedding_dim: int | None = None, sr_key=None,
                      qscale=None):
        """Tier dispatch on PRE-deduplicated ``(uids, g, valid)`` — the
        dedup-lookup step path (one shared sort per array per step).  The
        small-vocab one-hot tier needs raw ids and is bypassed here;
        ``sparse_adam`` has identical semantics.  PLAIN 2D int8 tables pass
        their (scale, offset) sidecar as ``qscale`` and get ``(table,
        slots, qscale)`` back; int8 FAT-LINE tables carry the sidecar
        in-line (byte-container layout) and never take a ``qscale``."""
        if table.ndim == 3:
            if qscale is not None:
                raise ValueError(
                    "fat-line int8 tables carry their (scale, offset) "
                    "sidecar in-line — qscale is only for plain 2D int8 "
                    "tables")
            if embedding_dim is None:
                raise ValueError("fat-table update needs embedding_dim")
            return fat_apply_unique(
                table, slots, uids, g, valid, embedding_dim=embedding_dim,
                kind=self.kind, lr=self.lr, b1=self.b1, b2=self.b2,
                eps=self.eps, weight_decay=self.weight_decay, sr_key=sr_key,
            )
        if self.kind == "sgd":
            out = sparse_sgd(table, uids, g, valid, lr=self.lr,
                             weight_decay=self.weight_decay,
                             sr_key=sr_key, qscale=qscale)
            if qscale is None:
                return out, slots
            table, qscale = out
            return table, slots, qscale
        if self.kind == "adagrad":
            (accum,) = slots
            out = sparse_adagrad(
                table, accum, uids, g, valid, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay, sr_key=sr_key, qscale=qscale)
            if qscale is None:
                table, accum = out
                return table, (accum,)
            table, accum, qscale = out
            return table, (accum,), qscale
        if self.kind == "rowwise_adagrad":
            (accum,) = slots
            out = sparse_rowwise_adagrad(
                table, accum, uids, g, valid, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay, sr_key=sr_key, qscale=qscale)
            if qscale is None:
                table, accum = out
                return table, (accum,)
            table, accum, qscale = out
            return table, (accum,), qscale
        if self.kind == "adam":
            mu, nu, count = slots
            out = sparse_adam(
                table, mu, nu, count, uids, g, valid, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
                sr_key=sr_key, qscale=qscale,
            )
            if qscale is None:
                table, mu, nu, count = out
                return table, (mu, nu, count)
            table, mu, nu, count, qscale = out
            return table, (mu, nu, count), qscale
        raise ValueError(self.kind)

    def dense_update(self, table, slots, ids, grads, *, sr_key=None):
        """Scatter-free tier for SMALL plain tables regardless of kind — the
        hot-head arrays of the frequency-partitioned embedding mode
        (``parallel/embedding.py`` hot/cold): duplicate ids merge inside a
        one-hot MXU contraction and the whole [V, D] table takes one masked
        read-modify-write, so the power-law head never pays a sort, dedupe,
        gather or scatter.  Negative ids contribute nothing.  Row semantics
        are identical to the ``sparse_*`` functions (lazy state: untouched
        rows do not decay).  Returns ``(table, slots)``."""
        if table.ndim != 3 and self.kind == "sgd":
            return dense_lazy_sgd(
                table, ids, grads, lr=self.lr,
                weight_decay=self.weight_decay, sr_key=sr_key), ()
        if table.ndim != 3 and self.kind == "adagrad":
            (accum,) = slots
            table, accum = dense_lazy_adagrad(
                table, accum, ids, grads, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay, sr_key=sr_key)
            return table, (accum,)
        if table.ndim != 3 and self.kind == "rowwise_adagrad":
            (accum,) = slots
            table, accum = dense_lazy_rowwise_adagrad(
                table, accum, ids, grads, lr=self.lr, eps=self.eps,
                weight_decay=self.weight_decay, sr_key=sr_key)
            return table, (accum,)
        if table.ndim != 3 and self.kind == "adam":
            mu, nu, count = slots
            table, mu, nu, count = dense_lazy_adam(
                table, mu, nu, count, ids, grads, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
                sr_key=sr_key,
            )
            return table, (mu, nu, count)
        raise ValueError(
            f"dense_update needs a plain 2D table (kind {self.kind!r}, "
            f"ndim {table.ndim})")

    def cache_init(self, table, cache_rows: int):
        """Empty update-cache pytree for a plain 2D ``table``: sorted-id
        directory (+ its physical-slot permutation), value rows at the
        table's storage dtype, per-kind optimizer-slot mirrors, dirty mask,
        frequency/recency counters, and the admission-overflow counter.
        int8 tables add a ``qs`` f32 [C, 2] (scale, offset) mirror: cached
        rows store CODES at storage dtype plus their per-row grid, so flush
        stays a bit-copy."""
        if table.ndim != 2:
            raise ValueError(
                "the update cache covers plain 2D tables only (fat-line "
                "arrays keep their in-place DMA path)")
        c = int(cache_rows)
        d = table.shape[1]
        cache = {
            "ids": jnp.full((c,), _CACHE_OOB, jnp.int32),
            "slot": jnp.arange(c, dtype=jnp.int32),
            "rows": jnp.zeros((c, d), table.dtype),
            "dirty": jnp.zeros((c,), bool),
            "freq": jnp.zeros((c,), jnp.int32),
            "last": jnp.zeros((c,), jnp.int32),
            "over": jnp.zeros((), jnp.int32),
        }
        if jnp.dtype(table.dtype) == jnp.int8:
            cache["qs"] = jnp.zeros((c, 2), jnp.float32)
        for key in _cache_mirror_keys(self.kind):
            cache[key] = _cache_slot_mirror(key, self.kind, c, d,
                                            self.slot_dtype)
        return cache

    def cache_update_unique(self, cache, table, slots, uids, g, valid, *,
                            step, sr_key=None, mesh=None, qscale=None):
        """Cached step on PRE-deduplicated ``(uids, g, valid)``: admit
        misses (gather-only), then apply the EXACT per-row ``sparse_*``
        math to the cached rows/mirrors and scatter into the [C] cache —
        the big table and its slot row arrays are read, never written.
        ``step`` feeds the recency counter.  Returns ``(cache, slots)``
        (``slots`` changes only for adam's global step count).  int8
        tables pass their (scale, offset) sidecar as ``qscale``: admission
        bit-copies codes + grid, the math dequantizes through the cached
        grid, and every write requantizes the NEW rows via
        :func:`quantize_rows` with the same key discipline as
        :func:`_requantize_scatter` callers — so the cached trajectory is
        bit-identical to the eager plain-int8 one.  Pass the device
        ``mesh`` when calling from inside a multi-device jitted program:
        the cache math then runs in a fully-replicated ``shard_map`` (see
        :func:`_replicated_shard_map`) while the big table/slot gathers
        stay outside on the sharded arrays."""
        if counters.enabled():
            # pre-admission route: how many of this step's unique rows the
            # cache already held.  Gather-only on replicated cache arrays,
            # and traced ONLY under an active collector (byte-identity).
            _, hit = cache_route(cache, jnp.where(valid, uids, -1))
            counters.emit("cache_hit_rows", (hit & valid).sum())
            counters.emit("cache_miss_rows", (valid & ~hit).sum())
        # the ONLY touches of the big arrays: plain per-uid row gathers,
        # which GSPMD partitions correctly on sharded tables
        gid = jnp.minimum(jnp.where(valid, uids, 0), table.shape[0] - 1)
        urows = jnp.take(table, gid, axis=0)
        uqs = None if qscale is None else jnp.take(qscale, gid, axis=0)
        uslot = {key: _cache_gather_slot(key, slots, self.kind, gid)
                 for key in _cache_mirror_keys(self.kind)}
        count = slots[2] if self.kind == "adam" else None
        math = self._cache_math
        if mesh is not None:
            math = _replicated_shard_map(math, mesh)
        cache, new_count = math(cache, uids, g, valid, urows, uslot, step,
                                count, sr_key, uqs)
        if self.kind == "adam":
            return cache, (slots[0], slots[1], new_count)
        return cache, slots

    def _cache_math(self, cache, uids, g, valid, urows, uslot, step, count,
                    sr_key, uqs=None):
        """Admission + per-kind cached update on cache-sized operands only
        (big-table rows and slot mirrors arrive pre-gathered as
        ``urows``/``uslot``) — the body ``cache_update_unique`` optionally
        wraps in a replicated shard_map."""
        cache = _cache_admit(cache, urows, uslot, uids, valid, self.kind,
                             step, uqs)
        c = cache["ids"].shape[0]
        cs, _ = cache_route(cache, uids)
        csc = jnp.minimum(cs, c - 1)
        int8 = "qs" in cache
        if int8:
            cur = dequantize_rows(
                jnp.take(cache["rows"], csc, axis=0),
                jnp.take(cache["qs"], csc, axis=0))
        else:
            cur = jnp.take(cache["rows"], csc, axis=0).astype(jnp.float32)
        g = g.astype(jnp.float32)
        lr, wd, eps = self.lr, self.weight_decay, self.eps
        new_count = count
        cache = dict(cache)

        def put_rows(new, key):
            # storage write: the int8 path re-grids the NEW rows through
            # quantize_rows (write-time requantize — the flush stays a bit
            # copy) with the same [U, d] block shape and key the plain
            # path's _requantize_scatter uses, so codes match bit-for-bit
            if int8:
                data, nqs = quantize_rows(new, key)
                cache["rows"] = cache["rows"].at[cs].set(data, mode="drop")
                cache["qs"] = cache["qs"].at[cs].set(nqs, mode="drop")
            else:
                cache["rows"] = cache["rows"].at[cs].set(
                    quantize(new, cache["rows"].dtype, key), mode="drop")

        if self.kind == "sgd":
            g2 = g + wd * cur
            put_rows(cur - lr * g2, sr_key)
        elif self.kind == "adagrad":
            acc_r = jnp.take(cache["acc"], csc, axis=0).astype(jnp.float32)
            g2 = g + wd * cur
            acc_n = acc_r + g2 * g2
            delta = lr * g2 / (jnp.sqrt(acc_n) + eps)
            put_rows(cur - delta, component_key(sr_key, 0))
            cache["acc"] = cache["acc"].at[cs].set(
                quantize(acc_n, cache["acc"].dtype,
                         component_key(sr_key, 1)), mode="drop")
        elif self.kind == "rowwise_adagrad":
            acc_r = jnp.take(cache["acc"], csc)  # [U] — always f32
            g2 = g + wd * cur
            acc_n = acc_r + jnp.mean(g2 * g2, axis=-1)
            delta = lr * g2 / (jnp.sqrt(acc_n)[:, None] + eps)
            put_rows(cur - delta, component_key(sr_key, 0))
            cache["acc"] = cache["acc"].at[cs].set(acc_n, mode="drop")
        elif self.kind == "adam":
            mu_r = jnp.take(cache["mu"], csc, axis=0).astype(jnp.float32)
            nu_r = jnp.take(cache["nu"], csc, axis=0).astype(jnp.float32)
            new_count = count + 1
            t = new_count.astype(jnp.float32)
            mu_n = self.b1 * mu_r + (1 - self.b1) * g
            nu_n = self.b2 * nu_r + (1 - self.b2) * g * g
            mu_hat = mu_n / (1 - self.b1**t)
            nu_hat = nu_n / (1 - self.b2**t)
            delta = lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + wd * cur)
            put_rows(cur - delta, component_key(sr_key, 0))
            cache["mu"] = cache["mu"].at[cs].set(
                quantize(mu_n, cache["mu"].dtype, component_key(sr_key, 1)),
                mode="drop")
            cache["nu"] = cache["nu"].at[cs].set(
                quantize(nu_n, cache["nu"].dtype, component_key(sr_key, 2)),
                mode="drop")
        else:
            raise ValueError(self.kind)
        cache["dirty"] = cache["dirty"].at[cs].set(True, mode="drop")
        cache["freq"] = cache["freq"].at[cs].add(1, mode="drop")
        cache["last"] = cache["last"].at[cs].set(step, mode="drop")
        return cache, new_count

    def cache_update(self, cache, table, slots, ids, grads, *, step,
                     capacity: int | None = None,
                     max_distinct: int | None = None, sr_key=None,
                     mesh=None, qscale=None):
        """Cached analogue of :meth:`update` for plain 2D tables: the SAME
        ``dedupe_grads`` call (bit-identical summed grads), then
        :meth:`cache_update_unique`.  Returns ``(cache, slots)``."""
        uids, g, valid = dedupe_grads(
            ids.reshape(-1), grads.reshape(-1, grads.shape[-1]),
            capacity=capacity, vocab=table.shape[0],
            max_distinct=max_distinct)
        counters.emit("unique_rows", lambda: valid.sum())
        return self.cache_update_unique(cache, table, slots, uids, g, valid,
                                        step=step, sr_key=sr_key, mesh=mesh,
                                        qscale=qscale)

    def cache_flush(self, cache, table, slots, qscale=None):
        """Write every dirty cached row (+ slot mirrors) back to the big
        table in ONE coalesced scatter — a verbatim bit-copy, so the
        flushed table equals the eager-path table exactly — then evict down
        to the hottest ``C // 2`` entries by (frequency, recency, id) and
        age the retained frequency counters.  Returns ``(cache, table,
        slots, overflow)`` where ``overflow`` is the interval's admission
        overflow count (MUST be zero; updates past capacity were lost).

        int8 tables pass (and get back) their ``qscale`` sidecar — the
        return becomes ``(cache, table, slots, qscale, overflow)``.  The
        flush stays a BIT-COPY (codes + one extra (scale, offset) scatter):
        requantization already happened per-row at write time in
        :meth:`cache_update_unique`, which keeps a kill/resume inside a
        flush interval trivially exact — no flush-time stochastic draw
        exists to replay."""
        c = cache["ids"].shape[0]
        cids, cslot = cache["ids"], cache["slot"]
        oob = jnp.asarray(_CACHE_OOB, jnp.int32)
        dirty_dir = jnp.take(cache["dirty"], cslot) & (cids < oob)
        counters.emit("cache_flushed_rows", lambda: dirty_dir.sum())
        counters.emit("cache_resident_rows", lambda: (cids < oob).sum())
        tgt = jnp.where(dirty_dir, cids, table.shape[0])
        table = table.at[tgt].set(
            jnp.take(cache["rows"], cslot, axis=0), mode="drop")
        if qscale is not None:
            qscale = qscale.at[tgt].set(
                jnp.take(cache["qs"], cslot, axis=0), mode="drop")
        new_slots = list(slots)
        for key in _cache_mirror_keys(self.kind):
            big = ({"acc": 0, "mu": 0, "nu": 1}[key]
                   if self.kind != "rowwise_adagrad" else 0)
            new_slots[big] = new_slots[big].at[tgt].set(
                jnp.take(cache[key], cslot, axis=0), mode="drop")
        # retention: hottest-first rank by (freq desc, recency desc, id) —
        # deterministic; evicted entries are clean post-writeback so
        # eviction just frees their directory entry + physical slot
        keep_k = c // 2
        used = cids < oob
        imax = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
        nf = jnp.where(used, -jnp.take(cache["freq"], cslot), imax)
        nl = jnp.where(used, -jnp.take(cache["last"], cslot), imax)
        _, _, s_ids, s_slot = jax.lax.sort((nf, nl, cids, cslot),
                                           num_keys=3, is_stable=False)
        keep = jnp.arange(c, dtype=jnp.int32) < keep_k
        new_ids, new_slot = jax.lax.sort(
            (jnp.where(keep, s_ids, oob), s_slot), num_keys=1,
            is_stable=False)
        retained = jnp.zeros((c,), bool).at[
            jnp.where(keep & (s_ids < oob), s_slot, c)].set(
                True, mode="drop")
        cache = dict(cache)
        cache["ids"], cache["slot"] = new_ids, new_slot
        cache["dirty"] = jnp.zeros_like(cache["dirty"])
        cache["freq"] = jnp.where(retained, cache["freq"] // 2, 0)
        cache["last"] = jnp.where(retained, cache["last"], 0)
        over = cache["over"]
        cache["over"] = jnp.zeros((), jnp.int32)
        if qscale is not None:
            return cache, table, tuple(new_slots), qscale, over
        return cache, table, tuple(new_slots), over

    def update(self, table, slots, ids, grads, *, embedding_dim: int | None = None,
               capacity: int | None = None, max_distinct: int | None = None,
               sr_key=None, qscale=None):
        if table.ndim == 3:
            if qscale is not None:
                raise ValueError(
                    "fat-line int8 tables carry their (scale, offset) "
                    "sidecar in-line — qscale is only for plain 2D int8 "
                    "tables")
            if embedding_dim is None:
                raise ValueError("fat-table update needs embedding_dim")
            return fat_update(
                table, slots, ids, grads, embedding_dim=embedding_dim,
                kind=self.kind, lr=self.lr, b1=self.b1, b2=self.b2,
                eps=self.eps, weight_decay=self.weight_decay,
                capacity=capacity, max_distinct=max_distinct, sr_key=sr_key,
            )
        if (self.kind == "adam" and qscale is None
                and table.shape[0] <= self.small_vocab_threshold):
            # the one-hot tier's full-block requantize would re-grid every
            # untouched int8 row (quantize_rows is not an identity the way
            # the bf16 bit trick is), so int8 tables stay on the row
            # gather/scatter path below whatever their vocab
            mu, nu, count = slots
            table, mu, nu, count = dense_lazy_adam(
                table, mu, nu, count, ids, grads, lr=self.lr, b1=self.b1,
                b2=self.b2, eps=self.eps, weight_decay=self.weight_decay,
                sr_key=sr_key,
            )
            return table, (mu, nu, count)
        uids, g, valid = dedupe_grads(ids.reshape(-1), grads.reshape(-1, grads.shape[-1]),
                                      capacity=capacity, vocab=table.shape[0],
                                      max_distinct=max_distinct)
        return self.update_unique(table, slots, uids, g, valid,
                                  embedding_dim=embedding_dim, sr_key=sr_key,
                                  qscale=qscale)


def sparse_optimizer(kind: str, lr: float, weight_decay: float = 0.0, **kw) -> SparseOptimizer:
    return SparseOptimizer(kind=kind, lr=lr, weight_decay=weight_decay, **kw)
