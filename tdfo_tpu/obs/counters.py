"""Trace-time counter registry for in-graph step diagnostics.

The train/sparse steps are built from layered helpers (`ops/sparse.py`
cache math, `parallel/embedding.py` exchanges, the step bodies themselves)
that would each need a threaded-through accumulator argument to report
diagnostics.  Instead, emission sites call :func:`emit` unconditionally and
a *collector* — a plain dict pushed onto a module-level stack while the
step function is being TRACED — decides whether anything happens:

- no collector active (the default, ``telemetry.counters=false``): ``emit``
  returns immediately without evaluating its value thunk, so the traced
  jaxpr is byte-identical to a build with no telemetry code at all
  (pinned by ``tests/test_telemetry.py``);
- a collector active: the thunk runs under the ambient trace and the
  resulting tracer is recorded; the step wrapper returns the dict as an
  extra pytree output, so counter values ride the SAME device buffers and
  host fetches as the pending losses — no extra syncs.

Two scoping rules keep tracers from leaking across trace boundaries:
``core/mesh.py`` wraps every `shard_map` body in :func:`suppress` (a tracer
born inside manual-SPMD cannot escape via a side dict — sites that need
per-shard counters declare them as real shard_map outputs and emit from
the caller), and multi-step `lax.scan` bodies open their OWN collector
inside the body, stacking counters as scan outputs (`train/step.py`,
`train/trainer.py`).
"""

from __future__ import annotations

import contextlib
from typing import Callable, Union

import jax.numpy as jnp

# Stack of active collectors.  ``None`` entries mark suppressed regions
# (shard_map bodies): emission is disabled but the stack depth still
# records that a trace boundary was crossed.
_STACK: list = []
_PREFIX: list = []


def enabled() -> bool:
    """True when the innermost region has a live collector."""
    return bool(_STACK) and _STACK[-1] is not None


def emit(name: str, value: Union[Callable, object]) -> None:
    """Record ``value`` under ``name`` in the active collector, if any.

    ``value`` may be a zero-arg thunk — it is ONLY called when a collector
    is active, so emission sites add zero equations to the counters-off
    jaxpr (the byte-identity contract).  Values are coerced to f32 scalars
    so every counter pytree leaf has one dtype/shape (cross-step stacking
    under scan, single fetch at log time).
    """
    if not enabled():
        return
    if callable(value):
        value = value()
    _STACK[-1]["".join(_PREFIX) + name] = jnp.asarray(value, jnp.float32)


@contextlib.contextmanager
def collect():
    """Open a collector; yields the dict that ``emit`` fills during the
    enclosed trace."""
    out: dict = {}
    _STACK.append(out)
    try:
        yield out
    finally:
        _STACK.pop()


@contextlib.contextmanager
def suppress():
    """Disable emission for the enclosed region (shard_map bodies)."""
    if not _STACK:
        # Nothing to suppress — keep the common counters-off path free of
        # stack churn.
        yield
        return
    _STACK.append(None)
    try:
        yield
    finally:
        _STACK.pop()


@contextlib.contextmanager
def scope(prefix: str):
    """Prefix counter names emitted in the enclosed region
    (``emb/<table>/touched`` style namespacing)."""
    _PREFIX.append(prefix)
    try:
        yield
    finally:
        _PREFIX.pop()
