"""Compile/retrace + device-memory event recorder (``events.jsonl``).

jax announces every backend compilation and trace through
``jax._src.dispatch.log_elapsed_time`` ("Finished XLA compilation of
{fun_name} in {t} sec", DEBUG unless ``jax_log_compiles``).  Rather than
flipping the global log-compiles flag (stderr spam), :func:`configure`
attaches one DEBUG-level handler to that logger — propagation is disabled
while recording so the DEBUG flood never reaches jax's own stderr handler,
with anything at the logger's original threshold forwarded on — and parses
the records:
every compilation lands in ``events.jsonl`` with its name, duration, and
per-name count, and compilations AFTER :func:`mark_warmup` are flagged
``after_warmup`` with a loud warning (a retrace in steady state means a
shape/dtype leak — the serve frontend's bounded-jit-cache invariant).

Process-global, configured once per run like ``utils/faults.configure``.
``memory_snapshot`` samples ``device.memory_stats()`` live/peak bytes
(None on spoofed CPU devices — gated) and keeps a run-peak watermark for
the final summary.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

_JAX_LOGGER_NAME = "jax._src.dispatch"
_COMPILE_RE = re.compile(
    r"Finished XLA compilation of (.+?) in ([0-9.eE+-]+) sec")
_TRACE_RE = re.compile(
    r"Finished tracing \+ transforming (.+?) (?:for pmap )?in "
    r"([0-9.eE+-]+) sec")

_LOCK = threading.Lock()
_ACTIVE: Optional["_Recorder"] = None


class _Recorder:
    def __init__(self, path, rotate_bytes: int = 0):
        self.path = os.fspath(path)
        self.rotate_bytes = int(rotate_bytes)
        self.after_warmup = False
        self.counts: dict = {}   # (kind, name) -> occurrences
        self.peak_bytes: dict = {}  # device label -> max bytes_in_use seen
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # truncate: one recorder per run, the file is the run's event log
        open(self.path, "w").close()

    def record(self, kind: str, **fields) -> None:
        from tdfo_tpu.utils.logrotate import maybe_rotate_path

        rec = {"time": time.time(), "kind": kind, **fields}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if self.rotate_bytes:
            # between complete appends, the closed-file shape: a kill at
            # any point leaves only whole lines in either generation
            maybe_rotate_path(self.path, self.rotate_bytes)


class _JaxCompileHandler(logging.Handler):
    """Parses dispatch's compile/trace announcements into event records."""

    def emit(self, record: logging.LogRecord) -> None:
        rec = _ACTIVE
        if rec is not None:
            try:
                msg = record.getMessage()
            except Exception:
                msg = None
            if msg is not None:
                self._parse(rec, msg)
        # Propagation is off while we hold the logger at DEBUG (jax mounts
        # a level-NOTSET stderr handler on the "jax" logger, so the DEBUG
        # flood we enable would spam the console).  Records that cleared
        # the logger's ORIGINAL threshold — real warnings, or compile
        # announcements promoted to WARNING by jax_log_compiles — still
        # flow to the parent chain here.
        if _FWD_LEVEL is not None and record.levelno >= _FWD_LEVEL:
            parent = logging.getLogger(_JAX_LOGGER_NAME).parent
            if parent is not None:
                parent.handle(record)

    @staticmethod
    def _parse(rec: "_Recorder", msg: str) -> None:
        for kind, rx in (("compile", _COMPILE_RE), ("trace", _TRACE_RE)):
            m = rx.search(msg)
            if not m:
                continue
            name, dur = m.group(1), float(m.group(2))
            with _LOCK:
                n = rec.counts[(kind, name)] = rec.counts.get(
                    (kind, name), 0) + 1
                late = rec.after_warmup
                rec.record(kind, name=name, duration_s=dur, count=n,
                           after_warmup=late)
            if late and kind == "compile":
                logger.warning(
                    "UNEXPECTED RETRACE: %s compiled after warmup "
                    "(occurrence %d, %.3fs) — a shape/dtype leak is "
                    "invalidating the jit cache", name, n, dur)
            return


_HANDLER: Optional[_JaxCompileHandler] = None
_SAVED_LEVEL: Optional[int] = None
_SAVED_PROPAGATE: Optional[bool] = None
_FWD_LEVEL: Optional[int] = None


def configure(path=None, *, rotate_bytes: int = 0) -> None:
    """Start recording to ``path`` (``events.jsonl``); ``None`` stops.
    ``rotate_bytes > 0`` caps the sink via ``[telemetry] log_rotate_bytes``
    (one ``.1`` overflow generation, the MetricLogger discipline)."""
    global _ACTIVE, _HANDLER, _SAVED_LEVEL, _SAVED_PROPAGATE, _FWD_LEVEL
    jl = logging.getLogger(_JAX_LOGGER_NAME)
    with _LOCK:
        if path is None:
            _ACTIVE = None
            if _HANDLER is not None:
                jl.removeHandler(_HANDLER)
                _HANDLER = None
            if _SAVED_LEVEL is not None:
                jl.setLevel(_SAVED_LEVEL)
                _SAVED_LEVEL = None
            if _SAVED_PROPAGATE is not None:
                jl.propagate = _SAVED_PROPAGATE
                _SAVED_PROPAGATE = None
            _FWD_LEVEL = None
            return
        _ACTIVE = _Recorder(path, rotate_bytes=rotate_bytes)
        if _HANDLER is None:
            _HANDLER = _JaxCompileHandler(level=logging.DEBUG)
            _SAVED_LEVEL = jl.level
            _SAVED_PROPAGATE = jl.propagate
            _FWD_LEVEL = jl.getEffectiveLevel()
            # dispatch logs at DEBUG unless jax_log_compiles; the logger
            # must pass DEBUG for the records to exist at all.  Propagation
            # goes off so the flood stays out of jax's stderr handler; the
            # handler forwards anything at the original threshold.
            jl.setLevel(logging.DEBUG)
            jl.propagate = False
            jl.addHandler(_HANDLER)


def active() -> bool:
    return _ACTIVE is not None


def mark_warmup() -> None:
    """Declare warmup over: later compilations are unexpected retraces."""
    rec = _ACTIVE
    if rec is not None:
        rec.after_warmup = True
        rec.record("warmup_done")


def record(kind: str, **fields) -> None:
    """Append an arbitrary event (checkpoint, epoch boundary, stall...)."""
    rec = _ACTIVE
    if rec is not None:
        with _LOCK:
            rec.record(kind, **fields)


def compile_count(name_substr: Optional[str] = None) -> int:
    """Total compilations recorded (optionally filtered by name substring)."""
    rec = _ACTIVE
    if rec is None:
        return 0
    with _LOCK:
        return sum(n for (kind, name), n in rec.counts.items()
                   if kind == "compile"
                   and (name_substr is None or name_substr in name))


def memory_snapshot(devices=None) -> Optional[list]:
    """Sample per-device live/peak bytes; None when the backend exposes no
    ``memory_stats`` (spoofed CPU devices).  Updates the run-peak
    watermark and appends a ``memory`` event when recording."""
    import jax

    stats = []
    for d in (devices if devices is not None else jax.local_devices()):
        s = d.memory_stats() if hasattr(d, "memory_stats") else None
        if not s:
            continue
        stats.append({
            "device": str(d),
            "bytes_in_use": int(s.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(s.get("peak_bytes_in_use", 0)),
        })
    if not stats:
        return None
    rec = _ACTIVE
    if rec is not None:
        with _LOCK:
            for s in stats:
                rec.peak_bytes[s["device"]] = max(
                    rec.peak_bytes.get(s["device"], 0),
                    s["peak_bytes_in_use"] or s["bytes_in_use"])
            rec.record("memory", devices=stats)
    return stats


def peak_memory() -> dict:
    """Run-peak watermark per device (final-summary material)."""
    rec = _ACTIVE
    if rec is None:
        return {}
    with _LOCK:
        return dict(rec.peak_bytes)
