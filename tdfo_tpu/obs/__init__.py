"""Flight-recorder telemetry: in-graph counters, compile/memory events,
and the stall watchdog (ISSUE 7).

Three independent pieces behind the validated ``[telemetry]`` config table:

- ``counters``  — trace-time collector registry the train/sparse steps emit
  device-computed diagnostics through; zero jaxpr footprint when off.
- ``events``    — process-global compile/retrace recorder + device memory
  sampler appending to ``events.jsonl``.
- ``watchdog``  — daemon thread writing ``heartbeat.jsonl`` and dumping all
  thread stacks when no step completes within the stall timeout.
- ``trace``     — span-based causal tracing across the online loop
  (``[telemetry] trace``): per-component ``trace-*.jsonl`` sinks carrying
  propagated ``(replica, seq)`` / cycle / version correlation ids.
- ``aggregate`` — offline assembler joining the trace sinks into per-cycle
  causal timelines, freshness lag, Chrome-trace export, and the fleet
  latency percentiles (``launch.py obs``).
"""

from tdfo_tpu.obs import aggregate, counters, events, trace
from tdfo_tpu.obs.watchdog import StallWatchdog

__all__ = ["aggregate", "counters", "events", "trace", "StallWatchdog"]
