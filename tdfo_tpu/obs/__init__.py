"""Flight-recorder telemetry: in-graph counters, compile/memory events,
and the stall watchdog (ISSUE 7).

Three independent pieces behind the validated ``[telemetry]`` config table:

- ``counters``  — trace-time collector registry the train/sparse steps emit
  device-computed diagnostics through; zero jaxpr footprint when off.
- ``events``    — process-global compile/retrace recorder + device memory
  sampler appending to ``events.jsonl``.
- ``watchdog``  — daemon thread writing ``heartbeat.jsonl`` and dumping all
  thread stacks when no step completes within the stall timeout.
"""

from tdfo_tpu.obs import counters, events
from tdfo_tpu.obs.watchdog import StallWatchdog

__all__ = ["counters", "events", "StallWatchdog"]
