"""Span-based causal tracing for the online loop (``[telemetry] trace``).

The PR-7 flight recorder observes components in isolation (counters see the
step, ``events.jsonl`` sees compiles, the frontend JSONL sees requests);
this module is the correlation layer that observes the loop as ONE system:
every component appends structured spans to a per-component
``trace-<component>.jsonl`` sink under one trace directory, carrying the
propagated ids that chain a served request ``(replica, seq)`` to the replay
batch that consumed it, the online cycle that trained on it, and the
version/digest the cycle produced (Monolith's end-to-end staleness
monitoring idiom; torchrec's ``train_pipeline`` stage timing).  The offline
assembler (``obs/aggregate.py``, ``launch.py obs``) joins the sinks into
per-cycle causal timelines.

Contracts (tests/test_trace.py):

  * **Off is free.**  ``trace = false`` (the default) leaves ``emit`` as an
    early return — no file I/O, no id minting, and the traced step jaxpr is
    byte-identical (spans are host-side only; nothing rides the step
    program).
  * **Every line is complete.**  Sinks are opened, appended one complete
    JSON line, and closed per record (the ``obs/events.py`` shape), then
    size-capped via ``utils/logrotate.maybe_rotate_path`` — a kill between
    appends never tears a line, so the assembler needs no torn-tail logic.
  * **Ids are deterministic.**  Span ids come from a locked module counter,
    never ``uuid``/``random``/``secrets`` — restarted runs stay
    reproducible, and the causal JOIN keys are the domain ids (replica,
    seq, cycle, version, digest) rather than the span id, so id reuse
    across restarts is harmless.  ``tests/test_quality.py`` confines both
    id minting and monotonic-clock differencing to this module.

Clock discipline: ``ts`` is ``time.time()`` (bare use, never differenced —
the only clock comparable across processes and sinks, what freshness lag
is computed from offline); durations are measured with the monotonic clock
via ``clock()``/``elapsed_ms()``/``elapsed_s()`` below, the single
sanctioned home for monotonic differencing so host-loop timing all flows
through one auditable site (the ``time.time()`` twin of this rule is
``tests/test_quality.py::test_no_wall_clock_differencing_around_device_work``).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from pathlib import Path
from typing import Iterator

from tdfo_tpu.utils.logrotate import maybe_rotate_path

_LOCK = threading.Lock()
_ROOT: Path | None = None
_ROTATE_BYTES = 0
_NEXT_ID = 0


def configure(root_dir: str | Path | None = None, *,
              rotate_bytes: int = 0) -> None:
    """Attach the module-global trace sink directory (``None`` detaches).

    The module-global configure/active shape of ``obs/events.py`` and
    ``utils/faults.py``: emission sites call ``emit`` unconditionally and
    the deconfigured path falls through for free."""
    global _ROOT, _ROTATE_BYTES, _NEXT_ID
    with _LOCK:
        _ROOT = Path(root_dir) if root_dir is not None else None
        _ROTATE_BYTES = int(rotate_bytes)
        _NEXT_ID = 0
        if _ROOT is not None:
            _ROOT.mkdir(parents=True, exist_ok=True)


def active() -> bool:
    return _ROOT is not None


def trace_dir() -> Path | None:
    return _ROOT


def clock() -> float:
    """Monotonic timestamp for host-loop interval timing.

    Pair with ``elapsed_ms``/``elapsed_s`` — the subtraction happens HERE
    (the one sanctioned monotonic-differencing site) so callers never
    lexically difference a clock, and the quality gate can audit every
    wall-time measurement in one place.  NOT for device timing: through
    the tunnel only chain differencing is honest (``bench.chain_time``)."""
    return time.monotonic()


def elapsed_ms(t0: float) -> float:
    """Milliseconds elapsed since ``t0`` (a ``clock()`` value)."""
    return (time.monotonic() - t0) * 1000.0


def elapsed_s(t0: float) -> float:
    """Seconds elapsed since ``t0`` (a ``clock()`` value)."""
    return time.monotonic() - t0


def emit(component: str, kind: str, **fields) -> None:
    """Append one complete span line to ``trace-<component>.jsonl``.

    No-op (early return, no I/O) unless ``configure`` attached a sink
    directory.  Values must be JSON-serializable — callers pass domain ids
    and plain numbers, never arrays."""
    root = _ROOT
    if root is None:
        return
    global _NEXT_ID
    with _LOCK:
        if _ROOT is None:  # detached while waiting on the lock
            return
        _NEXT_ID += 1
        rec = {"span": _NEXT_ID, "ts": time.time(), "component": component,
               "kind": kind, **fields}
        path = _ROOT / f"trace-{component}.jsonl"
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if _ROTATE_BYTES:
            maybe_rotate_path(path, _ROTATE_BYTES)


@contextlib.contextmanager
def span(component: str, kind: str, **fields) -> Iterator[dict]:
    """Time a region and emit one span with ``dur_ms`` on exit.

    Yields a dict the body may add fields to (verdict, counts); the span is
    emitted even when the body raises, so killed stages still leave their
    partial timing behind.  When tracing is off the body runs untouched
    (the yielded dict just falls on the floor)."""
    if _ROOT is None:
        yield {}
        return
    extra: dict = {}
    t0 = clock()
    try:
        yield extra
    finally:
        emit(component, kind, dur_ms=round(elapsed_ms(t0), 3),
             **{**fields, **extra})
