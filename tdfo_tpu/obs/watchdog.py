"""Stall watchdog: heartbeat file + all-thread stack dump on hang.

The "tunnel hung >180 s" failure mode is a silent wedge — the train loop
blocks inside a value fetch and nothing is ever printed.  The watchdog is
a daemon thread that wakes every ``timeout_s / 4`` seconds, appends the
last completed step and its age to ``heartbeat.jsonl``, and when no step
has completed within ``timeout_s`` logs a LOUD warning with the Python
stack of every live thread (``sys._current_frames``) so the hang site is
diagnosable post-mortem from the log alone.

Wall-clock deltas here are sanctioned: the watchdog times the HOST loop
(did a step complete?), not device execution — the dishonest-timing rule
(CLAUDE.md, ``test_quality.py``) is about differencing around device
work.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback

logger = logging.getLogger(__name__)


class StallWatchdog:
    """Heartbeat writer + stall detector.

    ``beat(step)`` is called by the train loop each completed step; the
    daemon thread does everything else.  Re-arms after each stall so a
    recovered loop gets fresh detection.

    The serving frontend reuses the same machinery with ``label="serve"``
    (``beat`` per shipped scoring batch, so a wedged scorer dumps stacks
    through the identical path as a wedged train step), and publishes its
    degradation state via :meth:`set_status` — extra key/values merged into
    every heartbeat record (e.g. ``degraded``/``bad_deltas`` from the swap
    store's quarantine counter).
    """

    def __init__(self, heartbeat_path, timeout_s: float, *,
                 clock=time.monotonic, label: str = "train",
                 rotate_bytes: int = 0):
        self.path = os.fspath(heartbeat_path)
        self.timeout_s = float(timeout_s)
        self.rotate_bytes = int(rotate_bytes)
        self.label = str(label)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_step = -1
        self._last_beat = clock()
        self._stalled = False
        self._status: dict = {}
        self.stall_events: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        open(self.path, "w").close()

    # ----------------------------------------------------------- loop API

    def beat(self, step: int) -> None:
        with self._lock:
            self._last_step = int(step)
            self._last_beat = self._clock()
            self._stalled = False

    def set_status(self, **kv) -> None:
        """Merge extra fields into every subsequent heartbeat record (the
        degraded-mode surface: ``set_status(degraded=True, bad_deltas=3)``)."""
        with self._lock:
            self._status.update(kv)

    def status(self) -> dict:
        with self._lock:
            return dict(self._status)

    def start(self) -> "StallWatchdog":
        if self.timeout_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tdfo-stall-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s)
            self._thread = None

    # ------------------------------------------------------------ daemon

    def _write(self, rec: dict) -> None:
        from tdfo_tpu.utils.logrotate import maybe_rotate_path

        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if self.rotate_bytes:
            # rotation happens between complete appends (closed file), so a
            # kill at any byte leaves whole lines in both generations
            maybe_rotate_path(self.path, self.rotate_bytes)

    def check(self) -> bool:
        """One watchdog pass (the daemon's body; callable from tests).
        Returns True when a stall was detected on this pass."""
        with self._lock:
            step, age = self._last_step, self._clock() - self._last_beat
            fresh_stall = age > self.timeout_s and not self._stalled
            if fresh_stall:
                self._stalled = True
            status = dict(self._status)
        self._write({"time": time.time(), "label": self.label,
                     "last_step": step, "step_age_s": age,
                     "stalled": age > self.timeout_s, **status})
        if fresh_stall:
            dump = self._dump_stacks()
            self.stall_events.append(
                {"last_step": step, "step_age_s": age})
            self._write({"time": time.time(), "kind": "stall",
                         "label": self.label, "last_step": step,
                         "step_age_s": age, "stacks": dump, **status})
            logger.warning(
                "STALL: no %s step completed in %.1fs (last step %d). "
                "Thread stacks:\n%s", self.label, age, step, dump)
        return fresh_stall

    def _dump_stacks(self) -> str:
        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for tid, frame in sys._current_frames().items():
            parts.append(f"--- thread {names.get(tid, '?')} ({tid}) ---\n"
                         + "".join(traceback.format_stack(frame)))
        return "\n".join(parts)

    def _run(self) -> None:
        interval = max(self.timeout_s / 4.0, 0.05)
        while not self._stop.wait(interval):
            self.check()
