"""Offline assembler for the causal trace sinks (``launch.py obs``).

Joins the per-component ``trace-*.jsonl`` sinks written by ``obs/trace.py``
into per-cycle causal timelines: which served requests ``(replica, seq)``
each replay batch consumed, which online cycle trained on them, what
version/digest that cycle exported and what verdict it earned, and when the
pointer flips put the version on the fleet — Monolith's end-to-end
staleness accounting and the per-stage wall-clock breakdown of Adnan et
al. (VLDB 2022), assembled after the fact from crash-safe logs instead of
a live collector.

Outputs:

  * ``assemble(spans)`` — per-cycle records (stage durations, consumed
    request keys, verdict, freshness lag) plus fleet-wide latency
    aggregates (p50/p99 per cohort and per replica).  Cycle spans are
    deduped by cycle number keeping the LAST durable emission, so a
    killed-and-restarted run (which re-runs the interrupted cycle and
    emits its span only at completion) assembles to exactly-once cycle
    accounting — tests/test_fleet.py audits the consumed ids against the
    replay cursor.
  * ``chrome_trace(spans)`` — a Chrome-trace/Perfetto JSON object
    (``traceEvents``; load via chrome://tracing or ui.perfetto.dev).
  * ``percentile(samples, q)`` — nearest-rank percentile, shared with the
    gated canary watch's ``max_p99_regression_ms`` verdict term
    (``train/online.py``) so the offline histograms and the online gate
    can never disagree on the statistic.

This module reads ONLY its own trace sinks — complete-line JSONL written
single-line-per-append by ``obs/trace.py`` (a live writer may leave at
most one torn tail mid-write, which is counted and skipped, never parsed
wrong) — hence its entry in the ``test_no_adhoc_jsonl_tailers`` blessed
set: there is no replay cursor to bypass here.
"""

from __future__ import annotations

import json
import math
from pathlib import Path


def load_spans(trace_dir: str | Path) -> list[dict]:
    """Read every ``trace-*.jsonl`` sink (rotated ``.1`` generation first,
    the ``utils/logrotate`` naming) and return spans sorted by wall ``ts``
    then span id.  Unparseable tails (a live writer mid-append) are
    skipped, never guessed at."""
    root = Path(trace_dir)
    spans: list[dict] = []
    for path in sorted(root.glob("trace-*.jsonl.1")) + \
            sorted(root.glob("trace-*.jsonl")):
        for line in path.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a live sink
            if isinstance(rec, dict):
                spans.append(rec)
    spans.sort(key=lambda r: (r.get("ts", 0.0), r.get("span", 0)))
    return spans


def percentile(samples, q: float) -> float | None:
    """Nearest-rank percentile (``q`` in [0, 100]); ``None`` when empty.

    The single definition shared by the offline histograms and the online
    ``max_p99_regression_ms`` canary verdict."""
    if not samples:
        return None
    s = sorted(float(v) for v in samples)
    k = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
    return s[k]


def _consumed_keys(consumed) -> list[tuple[int, int]]:
    """Normalise consumed span tuples to ``(replica, seq)`` join keys.

    Merged (fleet) consumers record 4-tuples ``(replica, seq, lo, hi)``;
    the flat single-log consumer records 3-tuples ``(seq, lo, hi)`` and
    maps to replica 0 (the only writer in that layout)."""
    keys = []
    for entry in consumed or []:
        e = list(entry)
        keys.append((int(e[0]), int(e[1])) if len(e) == 4 else (0, int(e[0])))
    return keys


def assemble(spans: list[dict]) -> dict:
    """Join the component sinks into per-cycle timelines + fleet stats."""
    cycle_spans: dict[int, dict] = {}
    stage_spans: dict[tuple[int, str], dict] = {}
    requests: dict[tuple[int, int], dict] = {}
    flips: list[dict] = []
    syncs: list[dict] = []
    heartbeats: list[dict] = []
    replays: list[dict] = []
    ingress_reqs: list[dict] = []
    loadgen_steps: list[dict] = []
    for s in spans:
        kind = s.get("kind")
        if kind == "online_cycle":
            # last durable emission wins: an interrupted cycle never emitted
            # a span, so the redo after restart is the one-and-only record
            cycle_spans[int(s["cycle"])] = s
        elif kind == "stage":
            stage_spans[(int(s["cycle"]), str(s["stage"]))] = s
        elif kind == "serve_request":
            requests[(int(s["replica"]), int(s["seq"]))] = s
        elif kind == "pointer_flip":
            flips.append(s)
        elif kind == "replica_sync":
            syncs.append(s)
        elif kind == "heartbeat":
            heartbeats.append(s)
        elif kind == "replay_batch":
            replays.append(s)
        elif kind == "ingress_request":
            # process-fleet path: latencies measured at the socket ingress
            # (submit stamp -> reply receipt), shed replies carry no latency
            ingress_reqs.append(s)
        elif kind == "loadgen_step":
            loadgen_steps.append(s)

    cycles = []
    seen_keys: dict[tuple[int, int], int] = {}
    for cyc in sorted(cycle_spans):
        s = cycle_spans[cyc]
        # distinct join keys: one request may contribute several row
        # ranges to a cycle (the consumer drains it in pieces)
        keys = sorted(set(_consumed_keys(s.get("consumed"))))
        for k in keys:
            seen_keys.setdefault(k, cyc)
        stages = {st: round(float(sp.get("dur_ms", 0.0)), 3)
                  for (c, st), sp in sorted(stage_spans.items()) if c == cyc}
        # freshness lag: oldest contributing request logged -> the produced
        # version first live on a replica (promote flip, else first sync)
        lag_s = None
        req_ts = [requests[k]["ts"] for k in keys if k in requests]
        if req_ts and s.get("verdict") == "promote":
            ver = s.get("version")
            live = [f["ts"] for f in flips
                    if f.get("op") == "promote" and f.get("version") == ver]
            live += [y["ts"] for y in syncs if y.get("version") == ver]
            if live:
                lag_s = round(min(live) - min(req_ts), 3)
        cycles.append({
            "cycle": cyc,
            "verdict": s.get("verdict"),
            "reason": s.get("reason"),
            "version": s.get("version"),
            "digest": s.get("digest"),
            "steps": [s.get("step_begin"), s.get("step_end")],
            "dur_ms": s.get("dur_ms"),
            "stages": stages,
            "consumed_keys": keys,
            "n_consumed_requests": len(keys),
            "n_traced_requests": sum(1 for k in keys if k in requests),
            "freshness_lag_s": lag_s,
        })

    def _lat(samples):
        return {"n": len(samples),
                "p50_ms": percentile(samples, 50),
                "p99_ms": percentile(samples, 99)}

    req_ms = [s["latency_ms"] for s in requests.values()
              if s.get("latency_ms") is not None]
    hb_ms = [s["ms"] for s in heartbeats if s.get("ms") is not None]
    per_replica = {}
    for s in heartbeats:
        per_replica.setdefault(int(s["replica"]), []).append(s)
    fleet = {
        "requests": _lat(req_ms),
        "heartbeats": _lat(hb_ms),
        "canary_heartbeats": _lat([s["ms"] for s in heartbeats
                                   if s.get("canary")]),
        "stable_heartbeats": _lat([s["ms"] for s in heartbeats
                                   if not s.get("canary")]),
        "per_replica": {
            rid: {**_lat([s["ms"] for s in ss]),
                  "last_queue_depth": ss[-1].get("queue_depth"),
                  "last_batch_fill": ss[-1].get("batch_fill")}
            for rid, ss in sorted(per_replica.items())
        },
    }
    if ingress_reqs:
        fleet["ingress"] = {
            **_lat([s["latency_ms"] for s in ingress_reqs
                    if not s.get("shed")]),
            "shed": sum(1 for s in ingress_reqs if s.get("shed")),
        }
    out_loadgen = [{k: s.get(k) for k in
                    ("mode", "offered", "concurrency", "offered_qps",
                     "completed", "achieved_qps", "p50_ms", "p99_ms",
                     "shed", "failed", "slo_ok")}
                   for s in loadgen_steps]
    return {
        "cycles": cycles,
        "fleet": fleet,
        "pointer_flips": [{k: f.get(k) for k in
                           ("ts", "op", "pointer", "version", "digest")}
                          for f in flips],
        "n_spans": len(spans),
        "n_requests": len(requests),
        "n_replay_batches": len(replays),
        "loadgen": out_loadgen,
    }


_PH_INSTANT = "i"


def chrome_trace(spans: list[dict]) -> dict:
    """Spans as a Chrome-trace JSON object (``chrome://tracing`` /
    Perfetto).  Components map to pids, replicas (where present) to tids;
    timed spans (``dur_ms``) become complete ``X`` events anchored at
    their start, the rest become instants."""
    components = sorted({s.get("component", "?") for s in spans})
    pid = {c: i + 1 for i, c in enumerate(components)}
    events = [{"name": "process_name", "ph": "M", "pid": pid[c], "tid": 0,
               "args": {"name": c}} for c in components]
    t0 = min((s.get("ts", 0.0) for s in spans), default=0.0)
    for s in spans:
        comp = s.get("component", "?")
        dur_ms = s.get("dur_ms")
        ts_us = (s.get("ts", t0) - t0) * 1e6
        name = s.get("kind", "span")
        if name == "stage":
            name = f"stage:{s.get('stage')}"
        elif "cycle" in s:
            name = f"{name}:c{s.get('cycle')}"
        args = {k: v for k, v in s.items()
                if k not in ("ts", "component", "span") and v is not None
                and isinstance(v, (int, float, str, bool))}
        ev = {"name": name, "cat": comp, "pid": pid[comp],
              "tid": int(s.get("replica", 0) or 0), "args": args}
        if dur_ms is not None:
            ev.update(ph="X", ts=ts_us - dur_ms * 1e3, dur=dur_ms * 1e3)
        else:
            ev.update(ph=_PH_INSTANT, ts=ts_us, s="p")
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def format_report(report: dict) -> str:
    """Human-readable timeline summary for the ``launch.py obs`` console."""
    lines = [f"spans: {report['n_spans']}  requests: {report['n_requests']}"
             f"  replay batches: {report['n_replay_batches']}"]
    for c in report["cycles"]:
        stages = "  ".join(f"{k}={v:.1f}ms" for k, v in c["stages"].items())
        lag = (f"  freshness_lag={c['freshness_lag_s']:.3f}s"
               if c["freshness_lag_s"] is not None else "")
        lines.append(
            f"cycle {c['cycle']}: verdict={c['verdict']} "
            f"version={c['version']} steps={c['steps'][0]}->{c['steps'][1]} "
            f"consumed={c['n_consumed_requests']} requests{lag}")
        if stages:
            lines.append(f"  {stages}")
    fl = report["fleet"]
    for label in ("requests", "heartbeats", "canary_heartbeats",
                  "stable_heartbeats"):
        d = fl[label]
        if d["n"]:
            lines.append(f"{label}: n={d['n']} p50={d['p50_ms']:.2f}ms "
                         f"p99={d['p99_ms']:.2f}ms")
    for rid, d in fl["per_replica"].items():
        lines.append(f"replica {rid}: n={d['n']} p50={d['p50_ms']:.2f}ms "
                     f"p99={d['p99_ms']:.2f}ms "
                     f"queue_depth={d['last_queue_depth']} "
                     f"batch_fill={d['last_batch_fill']}")
    ing = fl.get("ingress")
    if ing and ing["n"]:
        lines.append(f"ingress: n={ing['n']} p50={ing['p50_ms']:.2f}ms "
                     f"p99={ing['p99_ms']:.2f}ms shed={ing['shed']}")
    for s in report.get("loadgen", ()):
        axis = (f"conc={s['concurrency']}" if s["mode"] == "closed"
                else f"rate={s['offered_qps']:.1f}qps")
        p99 = "-" if s["p99_ms"] is None else f"{s['p99_ms']:.2f}ms"
        lines.append(
            f"loadgen {s['mode']} {axis}: qps={s['achieved_qps']:.1f} "
            f"p99={p99} shed={s['shed']} failed={s['failed']} "
            f"slo_ok={s['slo_ok']}")
    return "\n".join(lines)
