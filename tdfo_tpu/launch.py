"""Single entry point: ``python -m tdfo_tpu.launch --config config.toml``.

Replaces the reference's per-backend script zoo (``python train.py`` /
``train_dp.py`` / ``train_ps.py``; ``torchx run ... dist.ddp -j 1x2``,
``torchrec/README.md:56``).  On a TPU pod every host runs this same command;
``jax.distributed.initialize()`` discovers peers from the TPU environment —
no TF_CONFIG / cluster.json / torchx env plumbing (SURVEY.md §5.6).

Subcommands:
  * ``train`` (default)      — build the Trainer from config and fit.
  * ``serve``                — export the newest checkpoint to a serving
    bundle and run the micro-batching scoring frontend (+ a retrieval round
    for TwoTower and Bert4Rec; bert4rec configs serve the SEQ family —
    ragged histories bucketed into masked-position candidate scoring,
    ``tdfo_tpu/serve/seq_scoring.py``); ``[serving] replicas > 1`` runs a
    multi-replica fleet
    over one bundle store with per-replica request logs
    (``tdfo_tpu/serve/fleet.py``); knobs live in the ``[serving]`` table.
  * ``online``               — close the loop: replay the frontend's request
    log (``[serving] log_features``) into incremental training cycles, each
    ending in a delta export + hot swap (``tdfo_tpu/train/online.py``);
    with ``[online] canary_cycles > 0`` every candidate is shadow-scored on
    held-out replayed traffic, canaried on a fraction of the serving fleet
    and auto-rolled-back on AUC regression; knobs live in ``[online]``.
    ``[serving] fleet_mode = "process"`` runs the fleet as real OS
    processes behind a socket ingress with a respawning supervisor
    (``tdfo_tpu/serve/supervisor.py``).
  * ``serve-fleet``          — export a bundle and stand up the
    out-of-process fleet (N ``serve/replica_main.py`` children behind the
    power-of-two-choices ingress), then push a synthetic trace through it;
    the process twin of ``serve`` with ``[serving] replicas > 1``.
  * ``loadgen``              — drive the out-of-process fleet with zipf
    traffic (``[loadgen]``: open/closed loop, concurrency, rate) sweeping
    the load axis to the latency/throughput knee
    (``tdfo_tpu/serve/loadgen.py``).
  * ``plan``                 — price every per-table embedding placement
    against the measured cost model (``tdfo_tpu/plan``) using the
    preprocessing ``table_stats.json`` and write ``sharding_plan.json``;
    knobs live in the ``[planner]`` config table.
  * ``obs``                  — assemble the causal trace sinks written by a
    ``[telemetry] trace = true`` run (``trace-*.jsonl`` under
    checkpoint_dir/log_dir) into per-cycle timelines, freshness lag and
    fleet latency histograms (``tdfo_tpu/obs/aggregate.py``); writes a
    ``chrome_trace.json`` loadable in ``chrome://tracing`` / Perfetto.
  * ``preprocess-ctr``       — TwoTower ETL (jax-flax/preprocessing parity).
  * ``preprocess-seq``       — Bert4Rec ETL (torchrec/preprocessing parity).
  * ``preprocess-criteo``    — Criteo-format ETL (BASELINE.json DLRM family).
  * ``synth``                — write a synthetic raw-goodreads fixture.
  * ``synth-criteo``         — write a synthetic Criteo train.txt fixture.
"""

from __future__ import annotations

import argparse
import sys


def _init_distributed(flag: str) -> None:
    import jax

    if flag == "never":
        return
    try:
        jax.distributed.initialize()
    except Exception as e:  # single-process runs have no coordinator
        if flag == "always":
            raise
        print(f"single-process run (jax.distributed not initialised: {e})")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tdfo_tpu.launch", description=__doc__)
    p.add_argument("command", nargs="?", default="train",
                   choices=["train", "serve", "serve-fleet", "loadgen",
                            "online", "plan", "obs",
                            "preprocess-ctr", "preprocess-seq",
                            "preprocess-criteo", "synth", "synth-criteo"])
    p.add_argument("--config", default="config.toml", help="path to config.toml")
    p.add_argument("--data-dir", default=None, help="override config data_dir")
    p.add_argument("--distributed", default="auto", choices=["auto", "always", "never"],
                   help="jax.distributed.initialize policy (multi-host pods)")
    p.add_argument("--log-dir", default=None)
    args = p.parse_args(argv)

    from tdfo_tpu.core.config import read_configs

    overrides = {}
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    cfg = read_configs(args.config, **overrides)

    if args.command == "synth":
        from tdfo_tpu.data.synthetic import write_synthetic_goodreads

        write_synthetic_goodreads(cfg.data_dir)
        print(f"synthetic goodreads raw files written to {cfg.data_dir}")
        return 0
    if args.command == "synth-criteo":
        from tdfo_tpu.data.synthetic import write_synthetic_criteo

        write_synthetic_criteo(cfg.data_dir)
        print(f"synthetic criteo train.txt written to {cfg.data_dir}")
        return 0
    if args.command == "preprocess-criteo":
        from tdfo_tpu.data.criteo_preprocessing import run_criteo_preprocessing

        size_map = run_criteo_preprocessing(
            cfg.data_dir, seed=cfg.seed,
            hot_vocab=cfg.embeddings.hot_vocab,
            hot_fraction=cfg.embeddings.hot_fraction,
        )
        print(f"size_map: {{{len(size_map)} tables, "
              f"max vocab {max(size_map.values())}}}")
        return 0
    if args.command == "preprocess-ctr":
        from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing

        size_map = run_ctr_preprocessing(
            cfg.data_dir, seed=cfg.seed, write_format=cfg.write_format,
            hot_vocab=cfg.embeddings.hot_vocab,
            hot_fraction=cfg.embeddings.hot_fraction,
        )
        print(f"size_map: {size_map}")
        return 0
    if args.command == "plan":
        # pure host work: price placements from the stats artifact and the
        # measured cost table — no devices, no distributed init needed
        from tdfo_tpu.plan.planner import format_plan, plan_tables, write_plan
        from tdfo_tpu.plan.stats import load_table_stats

        if cfg.model not in ("dlrm", "twotower"):
            raise SystemExit(
                f"the planner targets the DMP sparse regimes (dlrm / "
                f"twotower), not model = {cfg.model!r}")
        stats = load_table_stats(cfg.data_dir)
        if stats is None:
            raise SystemExit(
                f"no table_stats.json under {cfg.data_dir} — re-run "
                "preprocessing (preprocess-ctr / preprocess-criteo) to "
                "emit the traffic-stats artifact")
        served = set(cfg.categorical_features or ())
        if served:
            stats = {k: v for k, v in stats.items() if k in served}
        plan = plan_tables(
            stats,
            dim=cfg.embed_dim,
            # the step's id traffic is the GLOBAL batch: every device's
            # rows funnel into the same sharded tables
            batch_size=cfg.per_device_train_batch_size
            * cfg.planner.n_devices,
            optimizer=cfg.sparse_optimizer,
            dense_model="twotower" if cfg.model == "twotower" else "dlrm",
            n_devices=cfg.planner.n_devices,
            hbm_gb=cfg.planner.hbm_gb,
            slot_dtype=cfg.embeddings.slot_dtype,
        )
        path = write_plan(cfg.data_dir, plan)
        print(format_plan(plan))
        print(f"plan written to {path}")
        return 0
    if args.command == "obs":
        # pure host work: fold the trace sinks of a finished (or killed)
        # traced run into one causal report — no devices, no distributed
        # init needed
        import json
        from pathlib import Path

        from tdfo_tpu.obs.aggregate import (assemble, chrome_trace,
                                            format_report, load_spans)

        out_dir = args.log_dir or cfg.checkpoint_dir
        if not out_dir:
            raise SystemExit(
                "obs needs the traced run's output dir — set checkpoint_dir "
                "in the config or pass --log-dir")
        trace_dir = Path(out_dir) / "trace"
        spans = load_spans(trace_dir)
        if not spans:
            raise SystemExit(
                f"no trace-*.jsonl spans under {trace_dir} — run with "
                "[telemetry] trace = true first")
        report = assemble(spans)
        print(format_report(report))
        chrome_path = trace_dir / "chrome_trace.json"
        chrome_path.write_text(json.dumps(chrome_trace(spans)))
        print(f"chrome trace written to {chrome_path} "
              "(load in chrome://tracing or ui.perfetto.dev)")
        return 0
    if args.command == "preprocess-seq":
        from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing

        stats = run_seq_preprocessing(
            cfg.data_dir, max_len=cfg.max_len, sliding_step=cfg.sliding_step,
            mask_prob=cfg.mask_prob, seed=cfg.seed, pad=not cfg.jagged,
        )
        print(f"seq preprocessing: {stats}")
        return 0

    _init_distributed(args.distributed)

    if cfg.model == "bert4rec":
        # bert4rec has its OWN handshake file with remapped 1-based ids
        # (torchrec parity); the CTR size_map.json that read_configs auto-merges
        # counts the full catalog and would mis-size the mask token.
        import json
        from pathlib import Path

        alt = Path(cfg.data_dir) / "size_map_bert4rec.json"
        if alt.exists():
            cfg = cfg.replace(size_map=json.loads(alt.read_text()))
    if cfg.faults.any():
        # a [faults] section deliberately kills/corrupts this run (test
        # harness, tdfo_tpu/utils/faults.py) — make that impossible to miss
        # in the launch log of a run that mysteriously dies with exit 17
        print(f"WARNING: fault injection armed: {cfg.faults}", flush=True)
    if args.command in ("serve", "serve-fleet", "loadgen", "online"):
        # explicit model-kind dispatch: resolve the serving family ONCE at
        # the entry point so an unsupported model dies here with the family
        # map (CTR = twotower/dlrm, seq = bert4rec) instead of deep in a
        # scorer traceback
        from tdfo_tpu.core.config import serving_model_kind

        try:
            kind = serving_model_kind(cfg)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        print(f"{args.command}: model {cfg.model!r} -> "
              f"{kind} serving family", flush=True)
    if args.command == "serve":
        from tdfo_tpu.serve.frontend import serve_from_config

        stats = serve_from_config(cfg, log_dir=args.log_dir)
        print({k: round(v, 5) if isinstance(v, float) else v
               for k, v in stats.items()})
        return 0
    if args.command == "serve-fleet":
        from tdfo_tpu.serve.loadgen import serve_fleet_from_config

        stats = serve_fleet_from_config(cfg, log_dir=args.log_dir)
        print({k: round(v, 5) if isinstance(v, float) else v
               for k, v in stats.items()})
        return 0
    if args.command == "loadgen":
        from tdfo_tpu.serve.loadgen import loadgen_from_config

        report = loadgen_from_config(cfg, log_dir=args.log_dir)
        for s in report["steps"]:
            axis = (f"conc={s['concurrency']}" if s["mode"] == "closed"
                    else f"rate={s['offered_qps']:.1f}qps")
            p99 = "-" if s["p99_ms"] is None else f"{s['p99_ms']:.2f}ms"
            print(f"loadgen {s['mode']} {axis}: "
                  f"qps={s['achieved_qps']:.1f} p99={p99} "
                  f"shed={s['shed']} failed={s['failed']} "
                  f"slo_ok={s['slo_ok']}")
        knee = report["knee"]
        print("knee: none (no step met the p99 SLO)" if knee is None else
              f"knee: qps={knee['achieved_qps']:.1f} at p99="
              f"{knee['p99_ms']:.2f}ms (SLO {knee['p99_slo_ms']} ms)")
        return 0
    if args.command == "online":
        from tdfo_tpu.train.online import online_from_config

        stats = online_from_config(cfg, log_dir=args.log_dir)
        print({k: round(v, 5) if isinstance(v, float) else v
               for k, v in stats.items()})
        return 0

    from tdfo_tpu.train.trainer import Trainer

    metrics = Trainer(cfg, log_dir=args.log_dir).fit()
    print({k: round(v, 5) for k, v in metrics.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
