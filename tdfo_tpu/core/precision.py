"""Mixed-precision policy + dynamic loss scaling.

Parity targets:
  * dtype selection bf16-on-TPU / f16-on-GPU (``jax-flax/models.py:142-151``).
  * ``DynamicScale`` loss scaling with non-finite-gradient rollback
    (``jax-flax/train_dp.py:28-29,55-81``).

TPU-first stance: bf16 needs no loss scaling (same exponent range as f32), so
the default mixed-precision path is plain bf16 compute with f32 params and no
scale.  The dynamic-scale machinery exists for parity and for f16 targets; it
is implemented SPMD-safely (scale state is replicated; the finite-check is a
global reduction, so no per-device divergence — SURVEY.md §7 hard part #5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["compute_dtype", "Policy", "DynamicLossScale", "scale_loss", "unscale_grads"]


def compute_dtype(mixed_precision: bool, platform: str | None = None) -> jnp.dtype:
    """bf16 on TPU, f16 on GPU, f32 otherwise (jax-flax/models.py:142-151).

    "axon" is the tunnelled TPU platform in this environment.
    """
    if not mixed_precision:
        return jnp.float32
    platform = platform or jax.local_devices()[0].platform
    if platform in ("tpu", "axon"):
        return jnp.bfloat16
    if platform in ("gpu", "cuda", "rocm"):
        return jnp.float16
    return jnp.float32


@dataclass(frozen=True)
class Policy:
    """Param/compute/output dtype triple (param master weights stay f32)."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class DynamicLossScale:
    """f16 dynamic loss scale with grow/backoff schedule.

    Semantics match flax's DynamicScale as used at
    ``jax-flax/train_dp.py:55-81``: scale the loss, unscale grads, and when any
    grad is non-finite skip the update and halve the scale; after
    ``growth_interval`` consecutive finite steps double it.
    """

    scale: jax.Array  # f32 scalar
    growth_counter: jax.Array  # i32 scalar
    growth_interval: int = field(default=2000, metadata=dict(static=True))
    growth_factor: float = field(default=2.0, metadata=dict(static=True))
    backoff_factor: float = field(default=0.5, metadata=dict(static=True))
    max_scale: float = field(default=2.0**24, metadata=dict(static=True))

    @classmethod
    def create(cls, initial_scale: float = 2.0**15, **kw) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(initial_scale, jnp.float32),
            growth_counter=jnp.asarray(0, jnp.int32),
            **kw,
        )

    def update(self, grads_finite: jax.Array) -> "DynamicLossScale":
        grow = self.growth_counter + 1 >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(
                grow,
                jnp.minimum(self.scale * self.growth_factor, self.max_scale),
                self.scale,
            ),
            jnp.maximum(self.scale * self.backoff_factor, 1.0),
        )
        new_counter = jnp.where(
            grads_finite & ~grow, self.growth_counter + 1, jnp.zeros_like(self.growth_counter)
        )
        return DynamicLossScale(
            scale=new_scale,
            growth_counter=new_counter,
            growth_interval=self.growth_interval,
            growth_factor=self.growth_factor,
            backoff_factor=self.backoff_factor,
            max_scale=self.max_scale,
        )


def scale_loss(loss: jax.Array, ls: DynamicLossScale | None) -> jax.Array:
    return loss if ls is None else loss * ls.scale


def unscale_grads(grads, ls: DynamicLossScale | None):
    if ls is None:
        return grads, jnp.asarray(True)
    inv = 1.0 / ls.scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    finite = jax.tree.reduce(
        jnp.logical_and,
        jax.tree.map(lambda g: jnp.all(jnp.isfinite(g)), grads),
        jnp.asarray(True),
    )
    return grads, finite
