"""Config system: ``config.toml`` -> frozen :class:`Config` + ``size_map.json`` handshake.

TPU-native unification of the three per-backend loaders in the reference
(``jax-flax/utils.py:10-38``, ``tensorflow2/utils.py:10-48``,
``torchrec/utils.py:8-39``).  One dataclass covers both workload families
(TwoTower CTR and Bert4Rec sequential) plus the mesh/parallelism knobs that the
reference scattered across ``cluster.json``, torchx env vars, and strategy
factories.

The ``size_map.json`` file written by preprocessing is the contract between the
offline data layer and model construction (vocab sizes per categorical
feature), exactly as in the reference (``jax-flax/preprocessing.py:273-275`` ->
``jax-flax/utils.py:31-32``).
"""

from __future__ import annotations

import dataclasses
import json
import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11 — tomli is the same parser/API
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

from tdfo_tpu.utils.faults import FaultSpec

__all__ = [
    "Config",
    "MeshSpec",
    "FaultSpec",
    "EmbeddingsSpec",
    "OnlineSpec",
    "ServingSpec",
    "TelemetrySpec",
    "TrainSpec",
    "read_configs",
    "load_size_map",
    "serving_model_kind",
]


def serving_model_kind(config) -> str:
    """Which serving family ``serve``/``online`` stand up for this config:
    ``"ctr"`` (twotower/dlrm scalar-logit bundles) or ``"seq"`` (bert4rec
    masked-position bundles).  ``[serving] model_kind = "auto"`` follows the
    model; an explicit kind was already cross-checked against the model at
    config time.  Unknown models refuse LOUDLY here — the serve/online
    dispatch point — instead of shape-crashing deep in a scorer."""
    kind = config.serving.model_kind
    if kind != "auto":
        return kind
    if config.model in ("twotower", "dlrm"):
        return "ctr"
    if config.model == "bert4rec":
        return "seq"
    raise ValueError(
        f"no serving family for model = {config.model!r}: CTR bundles "
        "serve twotower/dlrm, seq bundles serve bert4rec — serve/online "
        "cannot stand up this model")


@dataclass(frozen=True)
class MeshSpec:
    """Logical TPU mesh description.

    Replaces the reference's process-group / strategy / cluster.json plumbing
    (``torchrec/train.py:197-198``, ``tensorflow2/train_dp.py:21-36``,
    ``tensorflow2/train_ps.py:43-62``) with a single named-mesh spec.

    Axis sizes of ``-1`` mean "use all remaining devices" (at most one axis may
    be -1).  An axis of size 1 is kept in the mesh so sharding specs stay
    stable regardless of topology.
    """

    data: int = -1  # batch / data-parallel axis
    model: int = 1  # embedding-shard / tensor-parallel axis
    seq: int = 1  # sequence/context-parallel axis (ring attention)
    axis_names: tuple[str, ...] = ("data", "model", "seq")

    def sizes(self) -> tuple[int, ...]:
        return (self.data, self.model, self.seq)


@dataclass(frozen=True)
class EmbeddingsSpec:
    """``[embeddings]`` config table: frequency-partitioned hot/cold
    embedding storage (FAE / Neo-style popularity partitioning; torchrec
    ``MANAGED_CACHING`` analogue on a chip without SparseCore).

    ``hot_vocab`` > 0 enables the mode: preprocessing emits per-table
    hot-id sets (``hot_ids.json`` next to the parquet shards) of at most
    ``hot_vocab`` ids each, picked as the smallest frequency-ranked prefix
    covering ``hot_fraction`` of that column's lookup mass; at build time
    each table with a hot set splits into a small replicated hot table
    (scatter-free one-hot MXU update) and the residual cold table (the
    existing dedupe + row-scatter path over a smaller touched set).
    ``hot_vocab = 0`` disables the mode entirely (single-table storage,
    the default).
    """

    # per-table cap on the hot-id set size.  Keep <= ~16384: the one-hot
    # MXU segment-sum that makes hot updates scatter-free costs ~100-350 us
    # for vocabs 5k-16k on v5e and grows with the hot vocab.  0 disables.
    hot_vocab: int = 0
    # lookup-mass coverage target for the frequency pass: the hot set is
    # the smallest frequency-ranked id prefix whose train-split lookup
    # share reaches this fraction (then capped at hot_vocab).  Power-law id
    # traffic typically reaches 0.9 with a tiny prefix.
    hot_fraction: float = 0.9
    # grouped cross-table all-to-all (torchrec KJTAllToAll input-dist
    # parity): every row/table-sharded table's ids ride ONE offset-shifted
    # stream through ONE owner-sort + ONE id `all_to_all` (+ one for the
    # returned vectors), instead of a sort/bucket pipeline and 2 collectives
    # per table.  The backward takes the same single grouped id+grad
    # exchange.  Requires lookup_mode = "alltoall" + model_parallel; losses
    # are bit-identical to the per-table program.
    grouped_a2a: bool = False
    # STORAGE dtype of every embedding table in the DMP regime (fbgemm
    # quantized/mixed-precision TBE parity): "bfloat16" halves table HBM,
    # fat-line DMA bytes, and the grouped-a2a vector/grad payloads.  Compute
    # stays f32 — reads widen the small gathered block after the row gather,
    # writes requantize with stochastic rounding keyed on (step, table_id)
    # (ops/quant.py), so training stays bit-deterministic and
    # resume-identical.  "float32" (default) is byte-identical to the
    # unquantized storage layer.
    table_dtype: str = "float32"
    # STORAGE dtype of the Adam/Adagrad slot buffers of PLAIN (non-fused)
    # tables.  Fused fat-line tables pack their optimizer state into the
    # same lines as the rows, so their state width follows table_dtype.
    # rowwise_adagrad keeps its ONE f32 accumulator per row regardless
    # (fbgemm EXACT_ROWWISE_ADAGRAD parity contract) — bf16 slots with that
    # kind are rejected.
    slot_dtype: str = "float32"
    # per-table table_dtype overrides: a [embeddings.table_dtype_overrides]
    # toml sub-table mapping table name -> dtype string.  Tables not listed
    # use table_dtype.  Normalised to a sorted tuple of (name, dtype) pairs
    # so the Config stays hashable.
    table_dtype_overrides: Any = ()
    # device-resident update cache (fbgemm ``EmbeddingLocation.
    # MANAGED_CACHING`` / LXU-cache parity, software-managed): every plain
    # big-table array keeps a cache of this many rows resident in the train
    # state — sorted-id directory + value/slot mirrors + dirty mask +
    # frequency/recency counters.  Touched rows are admitted on miss
    # (gather-only) and updated scatter-free in the cache; dirty rows flush
    # back to the big table in ONE coalesced scatter every ``flush_every``
    # steps (and unconditionally before checkpoint/eval/export), amortizing
    # the ~60-110 ns/slot scatter floor across the interval.  Training is
    # bit-identical to the eager path.  Must bound the distinct rows an
    # array can touch per flush interval (the trainer fails loudly on
    # overflow).  0 disables (byte-identical default graphs).
    cache_rows: int = 0
    # cache write-back cadence in train steps: larger values amortize the
    # big-table scatter further but leave the main tables stale for longer
    # between flushes (training never reads stale values — the step serves
    # cached rows — but anything reading raw tables mid-interval would).
    # Checkpoint, eval, and serving export always flush first.
    flush_every: int = 64

    def __post_init__(self) -> None:
        ov = self.table_dtype_overrides
        if isinstance(ov, Mapping):
            ov = sorted(ov.items())
        object.__setattr__(
            self, "table_dtype_overrides",
            tuple((str(k), str(v)) for k, v in ov))

    def dtype_for(self, table_name: str) -> str:
        """Effective storage-dtype string for ``table_name``."""
        return dict(self.table_dtype_overrides).get(
            table_name, self.table_dtype)


@dataclass(frozen=True)
class ServingSpec:
    """``[serving]`` config table: online-inference knobs for the
    ``serve`` subcommand (``tdfo_tpu/serve/``) — checkpoint export,
    exact-MIPS candidate retrieval, and the micro-batching frontend.

    Every key is observable (``tests/test_config.py``): ``top_k`` is the
    retrieval output width, ``corpus_batch`` the item-tower sweep chunk,
    ``max_batch``/``batch_deadline_ms``/``buckets`` drive micro-batch
    assembly and the padded-shape set the jit cache may hold, and
    ``max_queue``/``shed_policy``/``swap_poll_s``/``max_bad_deltas`` are the
    overload/hot-swap resilience knobs (``serve/frontend.py`` admission
    control, ``serve/swap.py`` delta polling + quarantine).
    """

    # retrieved candidates per query (``lax.top_k`` width; ~16 us for an
    # 8k argsort on v5e, so exact brute-force MIPS needs no ANN index at
    # Goodreads/Criteo corpus scales)
    top_k: int = 100
    # item-tower sweep chunk when materialising the [N_items, D] corpus —
    # one jitted program, N/corpus_batch dispatches
    corpus_batch: int = 8192
    # micro-batcher flush threshold: a batch ships as soon as it holds
    # this many rows (must fit the largest bucket)
    max_batch: int = 8192
    # oldest-request deadline in milliseconds: when it expires the batcher
    # ships a PARTIAL padded batch instead of stalling the queue (graceful
    # degradation; 0 ships every request as its own batch)
    batch_deadline_ms: float = 10.0
    # allowed padded batch shapes (ascending).  Requests pad up to the
    # smallest bucket that fits, so the serving jit cache holds at most
    # ``len(buckets)`` programs — the compile-count regression contract.
    buckets: tuple[int, ...] = (256, 1024, 8192)
    # admission-queue cap in pending REQUESTS; an arrival beyond it sheds
    # deadline-expired requests first, then applies shed_policy (0 = the
    # pre-resilience unbounded queue)
    max_queue: int = 0
    # who loses when the bounded queue is still full after deadline sweeps:
    # "oldest" displaces the longest-waiting request (its latency bound is
    # nearest to broken anyway), "reject" bounces the new arrival
    shed_policy: str = "oldest"
    # how often the serving loop checks the export chain for the successor
    # delta bundle (serve/swap.py DeltaPoller cadence).  0 polls every tick;
    # a backwards host-clock jump re-arms rather than stalling (the poller
    # runs on an injectable monotonic-ish clock — see tests).
    swap_poll_s: float = 1.0
    # consecutive quarantined (digest-corrupt) deltas before the frontend
    # flips the degraded flag into its heartbeat — still serving the last
    # good version, but loudly
    max_bad_deltas: int = 3
    # two-stage retrieval (ScaNN, Guo et al. 2020 — quantized coarse scan
    # then exact re-rank): candidates kept per query by the coarse stage
    # before the exact f32 re-rank narrows them to top_k.  0 (default)
    # keeps the single-stage exact scan — byte-identical serving graphs.
    # Must be >= top_k when set; values above the corpus size degenerate
    # statically to the exact scan (bitwise-equal results).
    coarse_k: int = 0
    # storage dtype of the coarse-stage corpus scan: "int8" (rowwise
    # (scale, offset) codes, 4x less corpus HBM than f32), "bfloat16"
    # (2x), or "float32" (candidate pruning without quantization).  The
    # re-rank always gathers the exact f32 vectors.
    coarse_dtype: str = "int8"
    # log full feature payloads (+ labels when present) into the request
    # JSONL so served traffic can replay as an incremental training stream
    # (data/replay.py; Monolith §3.3 online-training joiner analogue).
    # Default-off: feature payloads multiply the log's byte rate.
    log_features: bool = False
    # rotate the request log into a sealed, digest-stamped segment once the
    # active file reaches this many bytes (0 = one unbounded segment).
    # Replay tails sealed segments with end-to-end verification; rotation
    # is atomic (seal lands before the successor opens).
    log_segment_bytes: int = 0
    # frontend replica count (serve/fleet.py): N micro-batching frontends
    # share one BundleStore and follow its CURRENT/CANARY pointers; each
    # writes its own request-log directory (replica-<k>) that the online
    # supervisor folds back into one exactly-once stream.  1 = the
    # single-frontend layout of PRs 9-10, byte-identical code path.
    replicas: int = 1
    # bundle-store retention: keep at most this many newest published
    # version directories beyond the protected CURRENT/CANARY chain
    # (serve/swap.py gc_versions, wired through recover() and promotion).
    # 0 = keep everything (the pre-retention behaviour).
    keep_versions: int = 0
    # fleet execution boundary: "inproc" keeps replicas as Python objects
    # inside the supervisor process (the PR-14 layout — spoofed-mesh unit
    # tests, zero process overhead); "process" runs each ReplicaFrontend as
    # a real OS process behind the socket ingress (serve/supervisor.py +
    # serve/ingress.py + serve/wire.py) so death drills are real SIGKILLs
    # and respawns cross a true process boundary.  Requires replicas >= 2.
    fleet_mode: str = "inproc"
    # heartbeat-staleness eviction window in milliseconds: the balancer
    # treats a replica whose last heartbeat is older than this as dead and
    # stops routing requests to it (serve/ingress.py; a stalled replica
    # keeping its final queue_depth forever was the PR-14 gap).  Must be
    # > 0 — a fleet cannot run without an eviction bound.
    heartbeat_stale_ms: float = 5000.0
    # wire-protocol frame cap in bytes (serve/wire.py): a declared frame
    # length beyond this is refused BEFORE the body is read, on both sides
    # — the bound on memory a malformed or hostile peer can demand.
    max_frame_bytes: int = 8 << 20
    # ingress -> replica connect retries (serve/wire.py connect, routed
    # through utils/retry.backoff_delay — the single backoff law); the
    # respawn window is exactly when these fire.  The default schedule
    # (10 attempts from 10 ms, capped at 2 s, ~4.5 s of cumulative sleep)
    # rides out a fresh child's interpreter + jax import; the child binds
    # its listener before loading the bundle, so the first RPC blocks on
    # the slow part instead of the connect.
    connect_retries: int = 10
    # base delay in milliseconds for the connect-retry backoff schedule
    # (doubles per attempt, capped + jittered by utils/retry.backoff_delay).
    connect_base_ms: float = 10.0
    # supervisor respawn backoff base in milliseconds: a replica's K-th
    # consecutive death waits backoff_delay(K) scaled from this base before
    # the respawn (serve/supervisor.py), so a crash-looping child cannot
    # hot-spin the supervisor.
    respawn_base_ms: float = 50.0
    # cap on the respawn backoff delay in milliseconds.
    respawn_max_ms: float = 2000.0
    # flap-quarantine window in seconds: deaths older than this no longer
    # count against a replica.
    flap_window_s: float = 30.0
    # deaths within flap_window_s that quarantine a replica permanently
    # (no further respawns; the fleet degrades to the survivors and the
    # quarantine is recorded loudly, never silent).
    flap_max_deaths: int = 3
    # which bundle family `serve`/`online` stand up: "auto" follows the
    # config's model (twotower/dlrm -> ctr, bert4rec -> seq), "ctr"/"seq"
    # pin it explicitly and REFUSE a mismatched model at config time — the
    # loud dispatch error instead of a shape crash deep in the scorer.
    model_kind: str = "auto"
    # newest raw-history items the seq frontend keeps when windowing a
    # ragged user history into the fixed [max_len] eval window (truncate-
    # left, torchrec/preprocessing.py:229-239).  0 = max_len - 1 (the eval
    # protocol's full window); smaller values drop older items and left-pad
    # more.  Must leave room for the appended MASK: <= max_len - 1.
    max_history: int = 0
    # row-count bucket set for the SEQ frontend's micro-batcher (sequence
    # requests carry [n, max_len] history panels, so the right fill
    # thresholds are smaller than CTR's).  Empty = reuse `buckets`.  The
    # jit-cache bound is len(history_buckets) programs, same contract.
    history_buckets: tuple[int, ...] = ()


@dataclass(frozen=True)
class LoadgenSpec:
    """``[loadgen]`` config table: the closed/open-loop load-generation
    harness (``serve/loadgen.py`` + ``launch.py loadgen``) that drives a
    process fleet to saturation and records the latency/throughput knee
    through the trace assembler's cohort p50/p99 histograms.

    Every key is observable (``tests/test_config.py``).
    """

    # arrival discipline: "closed" keeps exactly `concurrency` requests in
    # flight (each completion immediately issues the next — the classic
    # closed-loop saturation probe); "open" issues at `rate_qps` regardless
    # of completions (the knee appears as queueing + sheds, not slowdown).
    mode: str = "closed"
    # total requests to issue per run.
    requests: int = 200
    # closed-loop concurrency: in-flight request cap (ignored for "open").
    concurrency: int = 8
    # open-loop arrival rate in requests/second (ignored for "closed").
    rate_qps: float = 100.0
    # zipf exponent for item-popularity skew in generated request batches
    # (> 1; larger = hotter head — the realistic serving distribution).
    zipf_a: float = 1.1
    # rows per generated request batch (micro-batcher fill pressure).
    rows_per_request: int = 4
    # rng seed for the request stream (ids, continuous features, arrival
    # jitter) — a fixed seed makes knee runs comparable across builds.
    seed: int = 606
    # the SLO the knee is measured against: bench.py serve_fleet reports
    # sustained QPS/replica at this p99 bound, and past the knee admitted
    # requests must still meet it while sheds are counted, never silent.
    p99_slo_ms: float = 50.0


@dataclass(frozen=True)
class TrainSpec:
    """``[train]`` config table: train-loop pipelining knobs
    (torchrec ``TrainPipelineSparseDist`` parity)."""

    # cross-batch input-dist pipelining: batch N+1's owner-bucketing + id
    # all-to-all (which never reads the tables) is issued inside the jitted
    # step BEFORE batch N's dense fwd/bwd + table update, so XLA's
    # latency-hiding scheduler overlaps the ICI exchange with MXU work
    # (torchrec/train.py TrainPipelineSparseDist).  Losses are bit-identical
    # to eager order but arrive one batch late; the trainer primes on the
    # first batch and flushes the last at epoch end.  Requires
    # grouped_a2a = true and steps_per_execution = 1.
    pipeline_overlap: bool = False


@dataclass(frozen=True)
class TelemetrySpec:
    """``[telemetry]`` config table: flight-recorder knobs (``tdfo_tpu/obs``).

    The reference's only observability is tqdm bars and a
    ``tf.keras.callbacks.TensorBoard`` callback (``tensorflow2/
    train_ps.py:154``); torchrec's production analogue is ``TrainPipeline``
    throughput logging.  Every key is observable
    (``tests/test_telemetry.py``).
    """

    # in-graph step diagnostics (per-table touched/unique rows, cache
    # hit/miss/dirty/flushed, a2a fill/overflow, grad/param norms,
    # nonfinite logits) carried alongside the pending losses — zero extra
    # host syncs, fetched at log cadence into metrics.jsonl (+ TB when
    # tensorboard = true).  false compiles a byte-identical step jaxpr
    # (pinned by test) so the default path cannot regress.
    counters: bool = False
    # compile/retrace + memory events: every jax compilation (name,
    # duration, per-name count) appends to <log_dir>/events.jsonl;
    # compilations after warmup are flagged as unexpected retraces with a
    # loud warning, and device.memory_stats() live/peak bytes are sampled
    # at log cadence with a run-peak watermark in the final summary
    # (no-op on backends without memory_stats, e.g. spoofed CPU devices).
    events: bool = False
    # stall watchdog: a daemon thread appends {last_step, step_age_s} to
    # <log_dir>/heartbeat.jsonl and logs a LOUD warning with every
    # thread's Python stack when no train step completes within this many
    # seconds (the "tunnel hung >180 s" failure mode, made diagnosable).
    # 0 disables the watchdog thread (heartbeat.jsonl is not written).
    stall_timeout_s: float = 0.0
    # size-based rotation for the run's append-only JSONL sinks
    # (metrics.jsonl via MetricLogger, retries.jsonl via utils/retry,
    # events.jsonl, heartbeat*.jsonl, and the trace-*.jsonl span sinks):
    # when a sink crosses this many bytes it is atomically renamed to
    # `<name>.1` (replacing any previous overflow) and a fresh file
    # continues — a long-running online loop must not fill the disk.
    # 0 = unbounded.
    log_rotate_bytes: int = 0
    # span-based causal tracing (tdfo_tpu/obs/trace.py): every component of
    # the online loop appends correlation-id-carrying spans to per-component
    # trace-*.jsonl sinks under <out_dir>/trace, assembled offline by
    # `launch.py obs` into per-cycle causal timelines, freshness lag, and
    # fleet latency percentiles.  Spans are host-side only: false (the
    # default) emits nothing and the step program is byte-identical either
    # way (pinned by tests/test_trace.py).
    trace: bool = False


@dataclass(frozen=True)
class OnlineSpec:
    """``[online]`` config table: the serve -> retrain -> delta-export ->
    swap supervisor (``tdfo_tpu/train/online.py``; Monolith §3.3 online
    training / torchrec streaming-retrain analogue).

    The supervisor tails the frontend's request log through the crash-safe
    replay consumer (``data/replay.py``), trains ``steps_per_cycle``
    incremental steps, checkpoints state + replay cursor atomically, then
    ``export_delta`` -> ``BundleStore`` publish -> ``MicroBatcher.swap`` —
    forever (or ``max_cycles``).  Every knob below is observable
    (``tests/test_online.py`` / ``tests/test_replay.py``).
    """

    # directory of request-log segments to tail ("" disables the online
    # loop; `launch online` requires it).  The frontend writes it when
    # [serving] log_features is on.
    request_log: str = ""
    # incremental train steps (= replay batches) per cycle before the
    # delta-export/publish/swap stages run.  Each step consumes one
    # per_device_train_batch_size * data-axis batch from the log.
    steps_per_cycle: int = 8
    # stop after this many full cycles (0 = run until the log is exhausted
    # — the test/drain mode; production tails forever).
    max_cycles: int = 0
    # complete-but-garbage log records tolerated (quarantined with a
    # counter) before replay fails the run — mirrors max_bad_shards.
    # 0 = any bad record is fatal.
    max_bad_records: int = 0
    # bounded-lag backpressure: when replay falls more than this many
    # records behind the durable log head, lag_policy decides (0 = lag is
    # unbounded, the metric still reports).
    max_lag_records: int = 0
    # "fail" refuses to train on stale data (raises once max_lag_records is
    # exceeded); "skip" drops oldest records down to the bound — counted in
    # replay/skipped — and keeps training on fresh traffic.
    lag_policy: str = "fail"
    # canary gatekeeper (Monolith §3.3 staged parameter sync): when > 0,
    # every candidate bundle is shadow-scored before publish, published to
    # the CANARY pointer (served by canary_fraction of the fleet), watched
    # for this many heartbeat rounds, then promoted to CURRENT or rolled
    # back to the last good version bitwise.  0 = the ungated PR-10 path
    # (publish straight to CURRENT).  Requires [serving] replicas >= 2.
    canary_cycles: int = 0
    # fraction of replicas that serve the CANARY pointer during the watch
    # window (at least one replica; always fewer than the whole fleet, so
    # a regression reaches at most this slice of traffic).
    canary_fraction: float = 0.25
    # maximum tolerated AUC drop: the shadow gate refuses a candidate whose
    # held-out AUC falls more than this below the serving baseline, and the
    # canary watch rolls back when canary-replica AUC falls more than this
    # below the stable replicas.
    max_auc_regression: float = 0.02
    # latency verdict term for the canary watch: roll the candidate back
    # when the canary cohort's heartbeat-scoring p99 exceeds the stable
    # cohort's p99 by more than this many milliseconds across the watch
    # window (nearest-rank percentile, obs/aggregate.percentile — the same
    # statistic `launch.py obs` reports offline).  Catches regressions AUC
    # cannot see (a slow scorer serves stale ranking under load).  0
    # disables the term; requires canary_cycles > 0 to mean anything.
    max_p99_regression_ms: float = 0.0
    # replay batches held out per gated cycle as the shadow-eval slice:
    # traffic the candidate has NOT trained on (it trains in a later cycle
    # — progressive validation), scored by candidate + baseline for the
    # gate and by every replica for canary heartbeats.
    shadow_eval_batches: int = 1
    # replay-log retention: keep at most this many fully-consumed sealed
    # segments behind the committed cursor, deleting older ones (GC refuses
    # to touch any segment the cursor has not fully passed).  0 = keep
    # everything.  NOTE: after GC the log only replays from a committed
    # cursor — replay-from-zero is gone by design.
    keep_consumed_segments: int = 0


@dataclass(frozen=True)
class PlannerSpec:
    """``[planner]`` config table: cost-model-driven auto-sharding
    (``tdfo_tpu/plan``; torchrec ``EmbeddingShardingPlanner`` parity).

    ``python -m tdfo_tpu.launch plan --config ...`` prices every per-table
    placement against the measured v5e cost table (``plan/costs.py``) using
    the preprocessing traffic stats (``table_stats.json``) and writes a
    deterministic ``sharding_plan.json``; setting ``plan`` to that path
    makes the trainer apply it as per-table spec overrides (sharding /
    fused storage / dtype / hot split) and stamp its digest into
    checkpoints.
    """

    # path to a sharding_plan.json consumed at train time ("" = no plan;
    # the hand-set global knobs apply).  A plan OWNS the per-table levers,
    # so it conflicts with hot_vocab / cache_rows / non-f32 dtypes
    # (validated below) — those must come from the plan, not the config.
    plan: str = ""
    # per-device HBM budget the PLANNING step must fit allocated table +
    # optimizer-slot bytes under (128-lane padding included); 0 = unlimited.
    hbm_gb: float = 0.0
    # device count the plan targets (row shards divide descriptor work and
    # bytes by this; table-wise placement balances across it).
    n_devices: int = 1


@dataclass(frozen=True)
class Config:
    """Unified training configuration.

    Field-by-field parity sources:
      * data/paths + streaming: ``jax-flax/config.toml``, ``jax-flax/utils.py:10-33``
      * write_format / steps_per_execution / jit_xla / use_tpu:
        ``tensorflow2/utils.py:10-38`` (jit_xla=false here means eager debug
        execution — a REAL knob, unlike the reference's normalise-to-None)
      * sequence-model params (n_heads..mask_prob, model_parallel):
        ``torchrec/utils.py:8-34`` (incl. the ``max_len >= sliding_step`` assert)
    """

    # --- data (L1) ---
    data_dir: Path = Path("data/goodreads")
    train_data: str = "train_part_*.parquet"
    eval_data: str = "eval_part_*.parquet"
    # held-out TEST split (bert4rec leave-last-one): evaluated ONCE after
    # fit() finishes.  The reference computes this split and never consumes
    # it (torchrec/train.py:147-177); empty string disables.
    test_data: str = "test_part_*.parquet"
    streaming: bool = True
    write_format: str = "parquet"
    num_workers: int = 0
    shuffle_buffer_size: int = 2_000_000

    # --- optimisation (L4) ---
    n_epochs: int = 10
    learning_rate: float = 3e-4
    weight_decay: float = 1e-4
    per_device_train_batch_size: int = 2048
    per_device_eval_batch_size: int = 2048
    mixed_precision: bool = False
    loss_scale: str = "dynamic"  # "dynamic" | "none" (only used with f16)
    seed: int = 42

    # --- model (L2) ---
    model: str = "twotower"  # "twotower" | "bert4rec" | "dlrm"
    embed_dim: int = 16
    # custom CTR feature schema (dlrm only): categorical column names (one
    # embedding table each, vocab sizes from size_map) and continuous column
    # names for the bottom MLP.  Empty = the Goodreads TwoTower schema.
    # This is what trains Criteo-class data (data/criteo_preprocessing.py,
    # BASELINE.json north-star family): 26 cats + 13 conts by column name.
    categorical_features: tuple[str, ...] = ()
    continuous_features: tuple[str, ...] = ()
    # sequential-model params (Bert4Rec)
    n_heads: int = 2
    n_layers: int = 2
    max_len: int = 20
    sliding_step: int = 10
    mask_prob: float = 0.2
    dropout: float = 0.1
    # runtime variable-length sequences (torchrec KJT parity): preprocessing
    # writes RAGGED windows (preprocess-seq pads nothing), the loader ships
    # (values, lengths) pairs, and jagged_to_dense runs inside the jitted
    # step.  bert4rec only.
    jagged: bool = False

    # --- parallelism (L3) ---
    model_parallel: bool = False
    embedding_sharding: str = "row"  # "row" | "column" | "table" | "replicated"
    # embedding-lookup program (parallel/embedding.py): "gspmd" (compiler
    # schedules the collectives), "psum" (explicit shard_map, one psum), or
    # "alltoall" (torchrec input-dist/output-dist parity, 2 collectives)
    lookup_mode: str = "gspmd"
    # alltoall send-bucket capacity as a multiple of the balanced share
    # (local_batch / n_shards); 0 = exact worst case (capacity = local
    # batch).  Finite factors shrink the a2a payload ~n_shards/factor but
    # DROP ids past a bucket's capacity under extreme skew — they resolve
    # to zero vectors, a silent quality hazard.  The Trainer therefore logs
    # `a2a_overflow_ids` (dropped ids in the logged batch) at every log
    # boundary in this regime; watch it when tuning the factor.
    a2a_capacity_factor: float = 0.0
    # attention core for sequence models: "full" (T x T), "ring"
    # (sequence-parallel over the seq mesh axis; XLA blockwise innards —
    # the fastest long-T path measured on v5e), "ring_flash" (ring with the
    # Pallas flash kernels inside each ring step; ~2.4x slower than "ring"
    # at dh=64 on v5e — see bench_kernels.bench_ring_flash), "flash"
    # (single-device Pallas O(T) kernel)
    attn: str = "full"
    # ring attention only: chunk each ring step's local attention to
    # O(Tq x ring_block_k) logits with a rematerialised backward (0 = one
    # chunk per ring step).  Must divide the per-device sequence length.
    ring_block_k: int = 0
    # Megatron-style tensor parallelism over the model axis for the sequence
    # model's dense layers (feed-forward + vocab projection — the FLOPs peak
    # and biggest dense param).  A sharding-spec change only; GSPMD inserts
    # the collectives.  Beyond-reference capability (SURVEY.md §2.3: absent).
    tensor_parallel: bool = False
    # in-backward sparse optimizer for embedding tables in the DMP regime
    # (fbgemm EmbOptimType parity: the reference picks ADAM on GPU and SGD on
    # CPU, torchrec/train.py:187-195).  "rowwise_adagrad" stores ONE f32
    # accumulator per row (fbgemm EXACT_ROWWISE_ADAGRAD, the >=1e9-row
    # configuration).  Every kind composes with fat-line fused storage —
    # the packed-line geometry adapts to the kind's state width.
    sparse_optimizer: str = "adam"
    # TBE unique-then-expand lookup (gspmd mode only): ONE sort per table
    # array per step deduplicates the ids; the forward gathers only unique
    # rows (compact, cache-resident) and the update reuses the same mapping
    # — measured ~25% off the DLRM-Criteo step.  Identical numerics; ids
    # must be non-negative (every shipped ETL's contract).
    dedup_lookup: bool = False
    # stack PLAIN (non-fused) embedding tables sharing (dim, sharding) into
    # one array (the 2D analogue of the always-on fat-row stacking): a
    # many-table model (DLRM-Criteo, 26 tables) then pays ONE dedupe + ONE
    # gather/scatter per step instead of one per table.  Opt-in because it
    # changes checkpoint state keys.
    stack_tables: bool = False
    # vocab size above which DMP-regime tables use fused fat-line storage
    # (ops/pallas_kernels.line_layout + the in-place DMA update kernel,
    # available for EVERY sparse_optimizer kind); smaller tables take the
    # gather/scatter or one-hot MXU tiers.  0 fuses every table; -1 disables
    # fused storage entirely (every table stays plain 2D — the measured-
    # faster choice at the DLRM-Criteo profile, docs/BUDGET.md).  The kernel
    # choice itself is automatic per backend — there is no "use pallas"
    # switch to misconfigure.
    fused_table_threshold: int = 16384
    # [embeddings] table: frequency-partitioned hot/cold storage knobs
    embeddings: EmbeddingsSpec = field(default_factory=EmbeddingsSpec)
    # [train] table: train-loop pipelining knobs
    train: TrainSpec = field(default_factory=TrainSpec)
    # [serving] table: online-inference knobs (launch serve / tdfo_tpu.serve)
    serving: ServingSpec = field(default_factory=ServingSpec)
    # [loadgen] table: load-generation harness knobs (launch loadgen)
    loadgen: LoadgenSpec = field(default_factory=LoadgenSpec)
    # [telemetry] table: flight-recorder knobs (tdfo_tpu/obs)
    telemetry: TelemetrySpec = field(default_factory=TelemetrySpec)
    # [online] table: serve -> retrain -> swap supervisor knobs
    online: OnlineSpec = field(default_factory=OnlineSpec)
    planner: PlannerSpec = field(default_factory=PlannerSpec)
    mesh: MeshSpec = field(default_factory=MeshSpec)

    # --- runtime knobs ---
    # compiled multi-step loop: each device dispatch runs this many train
    # steps (tensorflow2/utils.py steps_per_execution parity; a real TPU win
    # because per-step host round trips disappear)
    steps_per_execution: int = 1
    # jit_xla = false -> the whole fit runs under jax.disable_jit(): op-by-op
    # eager execution for debugging (tensorflow2/utils.py jit_compile=False
    # parity; None/true = compiled, the default and the only sane production
    # setting)
    jit_xla: bool | None = None
    # use_tpu = true -> fail fast at Trainer construction unless jax's
    # backend really is TPU (tensorflow2 TPUStrategy-resolution parity: the
    # reference connected to a TPU cluster or died; silently training a
    # "TPU" config on CPU is the failure mode this guards)
    use_tpu: bool = False
    # PS-strategy parity (tensorflow2/train_ps.py:55-58 MinSizePartitioner):
    # dense-regime variables whose per-shard size stays >= this many bytes
    # are sharded over the model axis; 0 disables.  "Parameter servers" are
    # just sharded arrays under GSPMD (SURVEY.md §2.3).
    ps_min_shard_bytes: int = 0
    checkpoint_dir: str | None = None
    checkpoint_every_n_epochs: int = 10
    # --- fault tolerance ---
    # step-granular checkpoints: every N train steps the full state PLUS the
    # data-stream cursor (epoch, batch offset) is saved, so a preempted run
    # resumes from the exact batch instead of replaying the epoch
    # (BackupAndRestore at step granularity, tensorflow2/train_ps.py:156).
    # 0 = epoch-granular only (checkpoint_every_n_epochs still applies).
    checkpoint_every_n_steps: int = 0
    # corrupted-shard quarantine: a shard that fails to open/decode is
    # skipped with a warning; the run fails only once MORE than this many
    # shards are bad.  0 = any bad shard is fatal (the pre-quarantine
    # behaviour).  Single-host semantics; on multi-host meshes a skipped
    # shard must be skipped identically by every host (shared storage).
    max_bad_shards: int = 0
    # non-finite guard: after K CONSECUTIVE non-finite train losses the
    # trainer restores the last good on-device state snapshot and skips the
    # offending batch window (a `rollback` record lands in metrics.jsonl)
    # instead of silently training on NaN optimizer state.  The guard
    # fetches losses in windows of K steps (one host sync per window).
    # 0 disables guard, snapshots, and syncs entirely.
    nonfinite_tolerance: int = 3
    # refresh the guard's on-device state snapshot every N steps (only at a
    # window boundary whose losses were all finite, so the snapshot is
    # known-good).  Copy cost is one HBM pass over the state — size this to
    # taste on multi-GB-table runs.  Ignored when nonfinite_tolerance = 0.
    snapshot_every_n_steps: int = 100
    # deterministic fault injection ([faults] config table): kill_at_step /
    # nan_at_step / fail_io_nth — see tdfo_tpu/utils/faults.py.  Test-only
    # by design, but honoured by every real run so crash/resume tests run
    # the exact production path.
    faults: FaultSpec = field(default_factory=FaultSpec)
    log_every_n_steps: int = 100
    profile: bool = False
    # mirror every logged scalar into a TensorBoard events file next to the
    # JSONL (tensorflow2/train_ps.py:154 TensorBoard-callback parity, made
    # framework-wide; TF-free writer, tdfo_tpu/utils/tensorboard.py):
    # `tensorboard --logdir <checkpoint_dir>` shows train/eval curves
    tensorboard: bool = False

    # --- preprocessing handshake ---
    size_map: Mapping[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.max_len < self.sliding_step:
            raise ValueError(
                f"max_len ({self.max_len}) must be >= sliding_step ({self.sliding_step})"
            )
        if self.write_format not in ("parquet", "tfrecord"):
            raise ValueError(f"unsupported write_format: {self.write_format!r}")
        if self.model not in ("twotower", "dlrm", "bert4rec"):
            raise ValueError(f"unknown model: {self.model!r}")
        if ((self.categorical_features or self.continuous_features)
                and self.model != "dlrm"):
            raise ValueError(
                "categorical_features/continuous_features define a custom CTR "
                "schema, which only the dlrm model consumes (twotower and "
                "bert4rec have fixed reference schemas)"
            )
        if self.model == "dlrm" and self.continuous_features and                 not self.categorical_features:
            raise ValueError(
                "continuous_features without categorical_features: a custom "
                "schema must name its embedding-table columns"
            )
        if self.embedding_sharding not in ("row", "column", "table", "replicated"):
            raise ValueError(f"unknown embedding_sharding: {self.embedding_sharding!r}")
        if self.lookup_mode not in ("gspmd", "psum", "alltoall"):
            raise ValueError(f"unknown lookup_mode: {self.lookup_mode!r}")
        if self.dedup_lookup and self.lookup_mode != "gspmd":
            raise ValueError("dedup_lookup composes with lookup_mode \"gspmd\" only")
        if self.a2a_capacity_factor < 0:
            raise ValueError("a2a_capacity_factor must be >= 0 (0 = exact)")
        if self.jagged and self.model != "bert4rec":
            raise ValueError("jagged=true is a sequence-model knob (bert4rec)")
        if self.model == "bert4rec" and self.write_format != "parquet":
            # the seq ETL writes list-valued columns, which the TFRecord
            # sidecar schema does not carry — rejected rather than silently
            # reading parquet anyway (every config key must DO something)
            raise ValueError(
                "model=\"bert4rec\" supports write_format=\"parquet\" only "
                "(sequence columns are list-valued)"
            )
        if self.attn not in ("full", "ring", "ring_flash", "flash"):
            raise ValueError(f"unknown attn: {self.attn!r}")
        if self.ring_block_k < 0:
            raise ValueError("ring_block_k must be >= 0 (0 = unchunked)")
        if self.ring_block_k and self.attn != "ring":
            raise ValueError("ring_block_k requires attn = \"ring\"")
        if self.sparse_optimizer not in ("adam", "sgd", "adagrad",
                                         "rowwise_adagrad"):
            raise ValueError(f"unknown sparse_optimizer: {self.sparse_optimizer!r}")
        _storage_dtypes = ("float32", "bfloat16", "int8")
        emb = self.embeddings
        for label, dt in (("table_dtype", emb.table_dtype),
                          *((f"table_dtype_overrides[{n!r}]", d)
                            for n, d in emb.table_dtype_overrides)):
            if dt not in _storage_dtypes:
                raise ValueError(
                    f"embeddings {label} must be one of {_storage_dtypes}, "
                    f"got {dt!r}")
        if emb.slot_dtype not in ("float32", "bfloat16"):
            # int8 slots would put second-moment state on a per-row grid the
            # optimizer math cannot survive (ops/quant.py module docstring)
            raise ValueError(
                "embeddings slot_dtype must be one of ('float32', "
                f"'bfloat16'), got {emb.slot_dtype!r}")
        _any_int8 = (emb.table_dtype == "int8"
                     or any(d == "int8" for _, d in emb.table_dtype_overrides))
        # int8 composes with the update cache (rows admitted dequantized,
        # requantized per row at write time, codes + sidecar bit-copied at
        # flush) and with hot/cold (the full-block one-hot update only ever
        # touches the f32 hot HEAD; the cold residual stays row-sparse int8)
        # — both former refusals lifted; the cache mirrors the sidecar in a
        # "qs" buffer and hot heads dequantize at init.
        if (_any_int8 and self.sparse_optimizer == "rowwise_adagrad"
                and self.fused_table_threshold != -1):
            raise ValueError(
                'table_dtype = "int8" with sparse_optimizer = '
                '"rowwise_adagrad" cannot use fused fat-line storage: the '
                "f32 per-row accumulator contract cannot ride a quantized "
                "line.  Set fused_table_threshold = -1 (disable fusing) or "
                "pick sparse_optimizer = adagrad/adam/sgd")
        if (emb.slot_dtype == "bfloat16"
                and self.sparse_optimizer == "rowwise_adagrad"):
            raise ValueError(
                'slot_dtype = "bfloat16" cannot combine with '
                'sparse_optimizer = "rowwise_adagrad": that kind stores ONE '
                "f32 accumulator per row (the fbgemm EXACT_ROWWISE_ADAGRAD "
                "parity contract), so quantizing the slot buffer is refused")
        if (emb.table_dtype != "float32" or emb.slot_dtype != "float32"
                or any(d != "float32"
                       for _, d in emb.table_dtype_overrides)):
            if not (self.model == "dlrm"
                    or (self.model == "twotower" and self.model_parallel)):
                raise ValueError(
                    "embeddings table_dtype/slot_dtype configure the DMP "
                    "sparse regime (dlrm, or twotower with model_parallel "
                    "= true); other regimes would silently ignore the knob")
        if self.steps_per_execution < 1:
            raise ValueError("steps_per_execution must be >= 1")
        if self.checkpoint_every_n_steps < 0:
            raise ValueError(
                "checkpoint_every_n_steps must be >= 0 (0 = epoch-granular)")
        if self.max_bad_shards < 0:
            raise ValueError("max_bad_shards must be >= 0 (0 = fail on any)")
        if self.nonfinite_tolerance < 0:
            raise ValueError(
                "nonfinite_tolerance must be >= 0 (0 = guard disabled)")
        if self.snapshot_every_n_steps < 1:
            raise ValueError("snapshot_every_n_steps must be >= 1")
        if not self.streaming and self.write_format != "parquet":
            raise ValueError("streaming=false (map-style) requires parquet data")
        if self.fused_table_threshold < -1:
            raise ValueError(
                "fused_table_threshold must be >= 0 (0 = fuse every table) "
                "or exactly -1 (disable fused storage)")
        if self.embeddings.hot_vocab < 0:
            raise ValueError("hot_vocab must be >= 0 (0 = hot/cold disabled)")
        if not (0.0 < self.embeddings.hot_fraction <= 1.0):
            raise ValueError("hot_fraction must be in (0, 1]")
        if self.embeddings.cache_rows < 0:
            raise ValueError("cache_rows must be >= 0 (0 = update cache off)")
        if self.embeddings.flush_every < 1:
            raise ValueError("flush_every must be >= 1 (steps between cache "
                             "write-backs)")
        if self.embeddings.cache_rows > 0:
            if not (self.model == "dlrm"
                    or (self.model == "twotower" and self.model_parallel)):
                raise ValueError(
                    "cache_rows > 0 configures the DMP sparse regime (dlrm, "
                    "or twotower with model_parallel = true); other regimes "
                    "would silently ignore the knob")
            if self.lookup_mode != "gspmd":
                raise ValueError(
                    "the update cache (cache_rows > 0) composes with "
                    "lookup_mode \"gspmd\" only: cache directory routing and "
                    "the hit overlay run inside the jitted step, which the "
                    "explicit psum/alltoall shard_map programs (and the "
                    "grouped exchange) do not carry")
            if self.steps_per_execution != 1:
                raise ValueError(
                    "cache_rows > 0 requires steps_per_execution = 1: the "
                    "trainer schedules flushes between steps, which a "
                    "compiled multi-step loop would skip")
            if self.train.pipeline_overlap:
                raise ValueError(
                    "the update cache (cache_rows > 0) does not compose "
                    "with train.pipeline_overlap: the pipelined step runs "
                    "the grouped alltoall exchange, not lookup_mode "
                    "\"gspmd\"")
        if self.embeddings.hot_vocab > 0 and self.lookup_mode != "gspmd":
            raise ValueError(
                "hot/cold embedding storage (hot_vocab > 0) composes with "
                "lookup_mode \"gspmd\" only: hot tables are replicated and "
                "routed inside the jitted step, which the explicit psum/"
                "alltoall shard_map programs do not carry")
        if self.embeddings.grouped_a2a:
            if self.lookup_mode != "alltoall":
                raise ValueError(
                    "grouped_a2a groups the alltoall exchange and therefore "
                    "requires lookup_mode = \"alltoall\"")
            if not self.model_parallel:
                raise ValueError(
                    "grouped_a2a requires model_parallel = true: without "
                    "sharded tables there is no exchange to group")
        if self.serving.top_k < 1:
            raise ValueError("serving top_k must be >= 1")
        if self.serving.coarse_k < 0:
            raise ValueError(
                "serving coarse_k must be >= 0 (0 = exact single-stage "
                "retrieval)")
        if self.serving.coarse_k and self.serving.coarse_k < self.serving.top_k:
            raise ValueError(
                "serving coarse_k must be >= top_k: the coarse stage must "
                "hand the re-rank at least top_k candidates "
                f"(coarse_k={self.serving.coarse_k}, "
                f"top_k={self.serving.top_k})")
        if self.serving.coarse_dtype not in _storage_dtypes:
            raise ValueError(
                f"serving coarse_dtype must be one of {_storage_dtypes}, "
                f"got {self.serving.coarse_dtype!r}")
        if self.serving.corpus_batch < 1:
            raise ValueError("serving corpus_batch must be >= 1")
        if self.serving.max_batch < 1:
            raise ValueError("serving max_batch must be >= 1")
        if self.serving.batch_deadline_ms < 0:
            raise ValueError(
                "serving batch_deadline_ms must be >= 0 (0 = ship every "
                "request immediately)")
        if not self.serving.buckets:
            raise ValueError("serving buckets must name at least one shape")
        if any(b < 1 for b in self.serving.buckets):
            raise ValueError("serving buckets must be positive batch shapes")
        if list(self.serving.buckets) != sorted(set(self.serving.buckets)):
            raise ValueError(
                "serving buckets must be strictly increasing (each padded "
                "shape compiles one program; duplicates/disorder hide that)")
        if self.serving.max_queue < 0:
            raise ValueError(
                "serving max_queue must be >= 0 (0 = unbounded admission)")
        if self.serving.shed_policy not in ("oldest", "reject"):
            raise ValueError(
                "serving shed_policy must be 'oldest' or 'reject', got "
                f"{self.serving.shed_policy!r}")
        if self.serving.swap_poll_s < 0:
            raise ValueError(
                "serving swap_poll_s must be >= 0 (0 = poll every tick)")
        if self.serving.max_bad_deltas < 1:
            raise ValueError(
                "serving max_bad_deltas must be >= 1 (how many consecutive "
                "corrupt deltas flip degraded mode)")
        if self.serving.max_batch > self.serving.buckets[-1]:
            raise ValueError(
                "serving max_batch must fit the largest bucket: a full batch "
                f"of {self.serving.max_batch} rows cannot pad into "
                f"buckets[-1] = {self.serving.buckets[-1]}")
        if self.serving.log_segment_bytes < 0:
            raise ValueError(
                "serving log_segment_bytes must be >= 0 (0 = one unbounded "
                "request-log segment)")
        if self.serving.log_segment_bytes and not self.serving.log_features:
            raise ValueError(
                "serving log_segment_bytes rotates the replayable request "
                "log, which only exists with log_features = true")
        if self.serving.replicas < 1:
            raise ValueError(
                "serving replicas must be >= 1 (1 = the single-frontend "
                "layout)")
        if self.serving.keep_versions < 0:
            raise ValueError(
                "serving keep_versions must be >= 0 (0 = keep every "
                "published version)")
        if self.serving.fleet_mode not in ("inproc", "process"):
            raise ValueError(
                "serving fleet_mode must be 'inproc' or 'process', got "
                f"{self.serving.fleet_mode!r}")
        if self.serving.fleet_mode == "process" and self.serving.replicas < 2:
            raise ValueError(
                "serving fleet_mode = 'process' requires replicas >= 2: a "
                "one-process fleet has no survivors to degrade to — use the "
                "single-frontend 'inproc' layout instead")
        if self.serving.heartbeat_stale_ms <= 0:
            raise ValueError(
                "serving heartbeat_stale_ms must be > 0: the balancer needs "
                "a finite staleness bound to evict silent replicas")
        if self.serving.max_frame_bytes < 1024:
            raise ValueError(
                "serving max_frame_bytes must be >= 1024 (the wire refuses "
                "frames beyond it; smaller caps cannot carry a sync message)")
        if self.serving.connect_retries < 1:
            raise ValueError("serving connect_retries must be >= 1")
        if self.serving.connect_base_ms <= 0:
            raise ValueError("serving connect_base_ms must be > 0")
        if self.serving.respawn_base_ms <= 0:
            raise ValueError("serving respawn_base_ms must be > 0")
        if self.serving.respawn_max_ms < self.serving.respawn_base_ms:
            raise ValueError(
                "serving respawn_max_ms must be >= respawn_base_ms (it caps "
                "the respawn backoff schedule)")
        if self.serving.flap_window_s <= 0:
            raise ValueError("serving flap_window_s must be > 0")
        if self.serving.flap_max_deaths < 2:
            raise ValueError(
                "serving flap_max_deaths must be >= 2: one death must never "
                "quarantine a replica (every kill drill dies exactly once)")
        if self.serving.model_kind not in ("auto", "ctr", "seq"):
            raise ValueError(
                "serving model_kind must be 'auto', 'ctr' or 'seq', got "
                f"{self.serving.model_kind!r}")
        if self.serving.model_kind == "ctr" and self.model == "bert4rec":
            raise ValueError(
                "serving model_kind = 'ctr' does not match model = "
                "'bert4rec': the seq family exports a bert4rec bundle — set "
                "model_kind to 'seq' (or 'auto')")
        if (self.serving.model_kind == "seq"
                and self.model not in ("bert4rec",)):
            raise ValueError(
                f"serving model_kind = 'seq' does not match model = "
                f"{self.model!r}: only bert4rec exports a sequence bundle — "
                "set model_kind to 'ctr' (or 'auto')")
        if self.serving.max_history < 0:
            raise ValueError(
                "serving max_history must be >= 0 (0 = the full max_len - 1 "
                "eval window)")
        if self.serving.max_history > self.max_len - 1:
            raise ValueError(
                "serving max_history must leave room for the appended MASK "
                f"position: <= max_len - 1 = {self.max_len - 1}, got "
                f"{self.serving.max_history}")
        if self.serving.history_buckets:
            if any(b < 1 for b in self.serving.history_buckets):
                raise ValueError(
                    "serving history_buckets must be positive batch shapes")
            if (list(self.serving.history_buckets)
                    != sorted(set(self.serving.history_buckets))):
                raise ValueError(
                    "serving history_buckets must be strictly increasing "
                    "(each padded shape compiles one program; duplicates/"
                    "disorder hide that)")
        if self.loadgen.mode not in ("closed", "open"):
            raise ValueError(
                "loadgen mode must be 'closed' or 'open', got "
                f"{self.loadgen.mode!r}")
        if self.loadgen.requests < 1:
            raise ValueError("loadgen requests must be >= 1")
        if self.loadgen.concurrency < 1:
            raise ValueError("loadgen concurrency must be >= 1")
        if self.loadgen.rate_qps <= 0:
            raise ValueError("loadgen rate_qps must be > 0")
        if self.loadgen.zipf_a <= 1.0:
            raise ValueError(
                "loadgen zipf_a must be > 1 (the zipf popularity exponent; "
                "<= 1 has no normalizable tail)")
        if self.loadgen.rows_per_request < 1:
            raise ValueError("loadgen rows_per_request must be >= 1")
        if self.loadgen.p99_slo_ms <= 0:
            raise ValueError(
                "loadgen p99_slo_ms must be > 0 (the SLO the knee is "
                "measured against)")
        if self.telemetry.stall_timeout_s < 0:
            raise ValueError(
                "telemetry stall_timeout_s must be >= 0 (0 = watchdog off)")
        if self.telemetry.log_rotate_bytes < 0:
            raise ValueError(
                "telemetry log_rotate_bytes must be >= 0 (0 = unbounded "
                "metrics/retries JSONL)")
        if self.online.steps_per_cycle < 1:
            raise ValueError("online steps_per_cycle must be >= 1")
        if self.online.max_cycles < 0:
            raise ValueError(
                "online max_cycles must be >= 0 (0 = drain the log)")
        if self.online.max_bad_records < 0:
            raise ValueError(
                "online max_bad_records must be >= 0 (0 = fail on any)")
        if self.online.max_lag_records < 0:
            raise ValueError(
                "online max_lag_records must be >= 0 (0 = unbounded lag)")
        if self.online.lag_policy not in ("fail", "skip"):
            raise ValueError(
                "online lag_policy must be 'fail' or 'skip', got "
                f"{self.online.lag_policy!r}")
        if self.online.request_log and not self.checkpoint_dir:
            raise ValueError(
                "online.request_log requires checkpoint_dir: the replay "
                "cursor persists as a checkpoint sidecar — without it the "
                "loop cannot be crash-safe")
        if self.online.canary_cycles < 0:
            raise ValueError(
                "online canary_cycles must be >= 0 (0 = ungated publish)")
        if self.online.canary_cycles:
            if self.serving.replicas < 2:
                raise ValueError(
                    "online canary_cycles requires serving replicas >= 2: "
                    "the canary verdict compares canary replicas against "
                    "stable ones, which a single frontend cannot stage")
            if self.serving.keep_versions == 1:
                raise ValueError(
                    "online canary_cycles requires serving keep_versions "
                    "of 0 (unbounded) or >= 2: the watch window needs the "
                    "last good version AND the canary candidate on disk")
        if not (0.0 < self.online.canary_fraction < 1.0):
            raise ValueError(
                "online canary_fraction must be in (0, 1): at least one "
                "canary replica, never the whole fleet "
                f"(got {self.online.canary_fraction})")
        if self.online.max_auc_regression < 0:
            raise ValueError(
                "online max_auc_regression must be >= 0 (the tolerated "
                "held-out/canary AUC drop)")
        if self.online.max_p99_regression_ms < 0:
            raise ValueError(
                "online max_p99_regression_ms must be >= 0 (0 disables the "
                "latency verdict term; positive = the tolerated canary-over-"
                "stable heartbeat p99 excess in milliseconds)")
        if self.online.shadow_eval_batches < 1:
            raise ValueError(
                "online shadow_eval_batches must be >= 1: the gate needs "
                "at least one held-out batch to score")
        if self.online.keep_consumed_segments < 0:
            raise ValueError(
                "online keep_consumed_segments must be >= 0 (0 = keep "
                "every sealed segment)")
        if self.planner.hbm_gb < 0:
            raise ValueError(
                "planner hbm_gb must be >= 0 (0 = unlimited device memory)")
        if self.planner.n_devices < 1:
            raise ValueError("planner n_devices must be >= 1")
        if self.planner.plan:
            if not (self.model == "dlrm"
                    or (self.model == "twotower" and self.model_parallel)):
                raise ValueError(
                    "planner.plan configures the DMP sparse regime (dlrm, "
                    "or twotower with model_parallel = true); other regimes "
                    "would silently ignore the plan")
            if self.lookup_mode != "gspmd":
                raise ValueError(
                    "planner.plan composes with lookup_mode \"gspmd\" only: "
                    "planned placements (replicated tables, hot heads, "
                    "table-wise assignment) route inside the jitted step")
            # the plan OWNS the per-table levers; a config that also sets
            # them by hand would be silently overridden — refuse instead
            if self.embeddings.hot_vocab > 0:
                raise ValueError(
                    "planner.plan conflicts with embeddings.hot_vocab > 0: "
                    "the plan embeds its own per-table hot splits")
            if self.embeddings.cache_rows > 0:
                raise ValueError(
                    "planner.plan conflicts with embeddings.cache_rows > 0: "
                    "the plan prices the update cache itself and carries "
                    "its own cache_rows/cache_flush_every decision (> 0 "
                    "only for plain-int8 plans where the model predicts a "
                    "win)")
            if (self.embeddings.table_dtype != "float32"
                    or self.embeddings.slot_dtype != "float32"
                    or self.embeddings.table_dtype_overrides):
                raise ValueError(
                    "planner.plan conflicts with hand-set embeddings "
                    "table_dtype/slot_dtype/table_dtype_overrides: storage "
                    "dtypes are per-table plan decisions")
        if self.train.pipeline_overlap:
            if not self.embeddings.grouped_a2a:
                raise ValueError(
                    "pipeline_overlap pipelines the grouped input-dist and "
                    "therefore requires [embeddings] grouped_a2a = true "
                    "(and lookup_mode = \"alltoall\")")
            if self.steps_per_execution != 1:
                raise ValueError(
                    "pipeline_overlap carries the next batch's input-dist "
                    "across step boundaries and composes with "
                    "steps_per_execution = 1 only")

    @property
    def effective_fused_threshold(self) -> int | None:
        """Vocab threshold for fused fat-line storage, or ``None`` when
        ``fused_table_threshold = -1`` disables fusion outright.  The packed
        line geometry adapts to the optimizer kind
        (``ops/pallas_kernels.line_layout``), so every sparse-optimizer
        kind gets the fused in-place DMA update path."""
        if self.fused_table_threshold == -1:
            return None
        return self.fused_table_threshold

    @property
    def global_train_batch_size(self) -> int:
        import jax

        return self.per_device_train_batch_size * jax.device_count()

    def replace(self, **kwargs: Any) -> "Config":
        return dataclasses.replace(self, **kwargs)


def load_size_map(data_dir: Path) -> dict[str, int]:
    """Load the preprocessing -> training vocab-size contract if present."""
    path = Path(data_dir) / "size_map.json"
    if path.exists():
        with open(path) as f:
            return {k: int(v) for k, v in json.load(f).items()}
    return {}


_CONFIG_FIELDS = {f.name for f in dataclasses.fields(Config)}
_MESH_FIELDS = {f.name for f in dataclasses.fields(MeshSpec)} - {"axis_names"}
_FAULT_FIELDS = {f.name for f in dataclasses.fields(FaultSpec)}
_EMBEDDINGS_FIELDS = {f.name for f in dataclasses.fields(EmbeddingsSpec)}
_TRAIN_FIELDS = {f.name for f in dataclasses.fields(TrainSpec)}
_SERVING_FIELDS = {f.name for f in dataclasses.fields(ServingSpec)}
_LOADGEN_FIELDS = {f.name for f in dataclasses.fields(LoadgenSpec)}
_TELEMETRY_FIELDS = {f.name for f in dataclasses.fields(TelemetrySpec)}
_ONLINE_FIELDS = {f.name for f in dataclasses.fields(OnlineSpec)}
_PLANNER_FIELDS = {f.name for f in dataclasses.fields(PlannerSpec)}


def read_configs(config_path: str | os.PathLike | None = None, **overrides: Any) -> Config:
    """Read ``config.toml`` (flat keys, reference-compatible) into a Config.

    Reference-compatible behaviours preserved:
      * flat toml keys (no sections required); unknown keys are rejected so
        typos fail loudly (the reference dataclasses did this implicitly).
      * ``size_map.json`` next to the data dir merged in when it exists.
      * a ``[mesh]`` table maps onto :class:`MeshSpec` (new capability).
    """
    raw: dict[str, Any] = {}
    if config_path is not None:
        with open(config_path, "rb") as f:
            raw = tomllib.load(f)
    raw.update(overrides)

    mesh_raw = raw.pop("mesh", {})
    if isinstance(mesh_raw, MeshSpec):
        mesh = mesh_raw
    else:
        unknown_mesh = set(mesh_raw) - _MESH_FIELDS
        if unknown_mesh:
            raise ValueError(f"unknown mesh config keys: {sorted(unknown_mesh)}")
        mesh = MeshSpec(**mesh_raw)

    faults_raw = raw.pop("faults", {})
    if isinstance(faults_raw, FaultSpec):
        faults = faults_raw
    else:
        unknown_faults = set(faults_raw) - _FAULT_FIELDS
        if unknown_faults:
            raise ValueError(
                f"unknown faults config keys: {sorted(unknown_faults)}")
        faults = FaultSpec(**faults_raw)

    emb_raw = raw.pop("embeddings", {})
    if isinstance(emb_raw, EmbeddingsSpec):
        embeddings = emb_raw
    else:
        unknown_emb = set(emb_raw) - _EMBEDDINGS_FIELDS
        if unknown_emb:
            raise ValueError(
                f"unknown embeddings config keys: {sorted(unknown_emb)}")
        embeddings = EmbeddingsSpec(**emb_raw)

    train_raw = raw.pop("train", {})
    if isinstance(train_raw, TrainSpec):
        train = train_raw
    else:
        unknown_train = set(train_raw) - _TRAIN_FIELDS
        if unknown_train:
            raise ValueError(
                f"unknown train config keys: {sorted(unknown_train)}")
        train = TrainSpec(**train_raw)

    serving_raw = raw.pop("serving", {})
    if isinstance(serving_raw, ServingSpec):
        serving = serving_raw
    else:
        unknown_serving = set(serving_raw) - _SERVING_FIELDS
        if unknown_serving:
            raise ValueError(
                f"unknown serving config keys: {sorted(unknown_serving)}")
        for tup_key in ("buckets", "history_buckets"):
            if tup_key in serving_raw:
                serving_raw = dict(
                    serving_raw, **{tup_key: tuple(serving_raw[tup_key])})
        serving = ServingSpec(**serving_raw)

    loadgen_raw = raw.pop("loadgen", {})
    if isinstance(loadgen_raw, LoadgenSpec):
        loadgen = loadgen_raw
    else:
        unknown_loadgen = set(loadgen_raw) - _LOADGEN_FIELDS
        if unknown_loadgen:
            raise ValueError(
                f"unknown loadgen config keys: {sorted(unknown_loadgen)}")
        loadgen = LoadgenSpec(**loadgen_raw)

    telemetry_raw = raw.pop("telemetry", {})
    if isinstance(telemetry_raw, TelemetrySpec):
        telemetry = telemetry_raw
    else:
        unknown_telemetry = set(telemetry_raw) - _TELEMETRY_FIELDS
        if unknown_telemetry:
            raise ValueError(
                f"unknown telemetry config keys: {sorted(unknown_telemetry)}")
        telemetry = TelemetrySpec(**telemetry_raw)

    online_raw = raw.pop("online", {})
    if isinstance(online_raw, OnlineSpec):
        online = online_raw
    else:
        unknown_online = set(online_raw) - _ONLINE_FIELDS
        if unknown_online:
            raise ValueError(
                f"unknown online config keys: {sorted(unknown_online)}")
        online = OnlineSpec(**online_raw)

    planner_raw = raw.pop("planner", {})
    if isinstance(planner_raw, PlannerSpec):
        planner = planner_raw
    else:
        unknown_planner = set(planner_raw) - _PLANNER_FIELDS
        if unknown_planner:
            raise ValueError(
                f"unknown planner config keys: {sorted(unknown_planner)}")
        planner = PlannerSpec(**planner_raw)

    unknown = set(raw) - _CONFIG_FIELDS
    if unknown:
        raise ValueError(f"unknown config keys: {sorted(unknown)}")

    if "data_dir" in raw:
        raw["data_dir"] = Path(raw["data_dir"]).expanduser()
    for key in ("categorical_features", "continuous_features"):
        if key in raw:
            raw[key] = tuple(raw[key])  # toml arrays / lists -> tuples

    cfg = Config(mesh=mesh, faults=faults, embeddings=embeddings, train=train,
                 serving=serving, loadgen=loadgen, telemetry=telemetry,
                 online=online, planner=planner, **raw)
    if not cfg.size_map:
        size_map = load_size_map(cfg.data_dir)
        if size_map:
            cfg = cfg.replace(size_map=size_map)
    return cfg
