"""Device-mesh bootstrap: the single distribution mechanism of the framework.

Replaces all three per-backend distribution planes in the reference with one
named-mesh abstraction (SURVEY.md §2.3):

  * ``torch.distributed.init_process_group`` + NCCL/gloo
    (``torchrec/train.py:186-198``)  -> :func:`initialize_distributed` +
    XLA collectives over ICI/DCN.
  * ``tf.distribute`` strategy factories (``tensorflow2/train_dp.py:21-36``)
    and the gRPC PS cluster (``tensorflow2/train_ps.py:43-62``) -> sharding
    specs on the mesh; "parameter servers" are just sharded arrays.
  * ``jax.pmap`` (``jax-flax/train_dp.py:179-186``) -> ``jax.jit`` with
    :class:`~jax.sharding.NamedSharding` (GSPMD).

Axes convention:
  ``data``  - batch-parallel axis (DP).
  ``model`` - embedding/tensor-parallel axis (MP); row/column/table-wise
              embedding shards live along it.
  ``seq``   - sequence/context-parallel axis (ring attention).
"""

from __future__ import annotations

import functools
import math
import os
import re
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdfo_tpu.core.config import MeshSpec

__all__ = [
    "make_mesh",
    "initialize_distributed",
    "spoof_cpu_devices",
    "shard_map",
    "axis_size",
    "data_sharding",
    "replicated_sharding",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
]

def _suppress_counters(f):
    # Telemetry counters (tdfo_tpu/obs/counters.py) may not be emitted from
    # inside a shard_map body: the per-shard tracer would leak out through
    # the side collector instead of being a declared output.  Every body
    # therefore runs suppressed; sites needing per-shard diagnostics declare
    # them as real shard_map outputs and emit from the caller.
    @functools.wraps(f)
    def suppressed(*args, **kwargs):
        from tdfo_tpu.obs import counters

        with counters.suppress():
            return f(*args, **kwargs)

    return suppressed


try:  # jax >= 0.5 exports shard_map at top level
    _shard_map_impl = jax.shard_map

    def shard_map(f, *args, **kwargs):
        return _shard_map_impl(_suppress_counters(f), *args, **kwargs)
except AttributeError:
    # 0.4.x: same callable in the experimental namespace, with the
    # replication check still spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_exp(_suppress_counters(f), *args, **kwargs)

try:  # jax >= 0.5
    axis_size = jax.lax.axis_size
except AttributeError:
    def axis_size(axis_name):
        # 0.4.x: jax.core.axis_frame returns the concrete size of a bound
        # mesh axis — the same int lax.axis_size reports on newer jax
        return jax.core.axis_frame(axis_name)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def spoof_cpu_devices(n: int = 8) -> None:
    """Force N virtual CPU devices for tests (call BEFORE first jax use).

    The jax-idiomatic equivalent of every fake-cluster mechanism in the
    reference (SURVEY.md §4.1): the commented-out
    ``xla_force_host_platform_device_count`` hint at
    ``jax-flax/train_dp.py:21-24``, TF logical devices, the in-process gRPC
    PS cluster, and torchrec's ``mp.spawn`` gloo harness.  Uses the config
    knobs rather than env vars so it also works when a sitecustomize has
    already imported jax and pinned another platform.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    # REPLACE any inherited device-count flag rather than keeping it: the
    # 2-process multihost workers inherit the pytest parent's 8-device
    # XLA_FLAGS via Popen(env=...) and must be able to ask for fewer (the
    # env flag beats jax_num_cpu_devices on this jax version)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # jax < 0.5 has no jax_num_cpu_devices knob; the XLA_FLAGS hint set
        # above covers it as long as jax has not initialised yet
        pass


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap (DCN across slices, ICI within a slice).

    Fills the multi-host gap the reference's jax backend left open (it was
    single-host pmap only; ``torchrec`` used env-var rank/world from torchx,
    ``torchrec/data.py:53-54``).  Reads the same style of env vars when args
    are not given, then delegates to ``jax.distributed.initialize``.
    No-op for single-process runs.
    """
    num_processes = num_processes or int(os.environ.get("WORLD_SIZE", "1"))
    if num_processes <= 1:
        return
    process_id = process_id if process_id is not None else int(os.environ.get("RANK", "0"))
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _resolve_sizes(spec: MeshSpec, n_devices: int) -> tuple[int, ...]:
    sizes = list(spec.sizes())
    wildcard = [i for i, s in enumerate(sizes) if s == -1]
    if len(wildcard) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if wildcard:
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed mesh axes {sizes}"
            )
        sizes[wildcard[0]] = n_devices // fixed
    if math.prod(sizes) != n_devices:
        raise ValueError(f"mesh {sizes} != device count {n_devices}")
    return tuple(sizes)


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the named device mesh.

    Device order follows ``jax.devices()`` which already reflects physical
    ICI topology on TPU slices; the ``data`` axis is outermost so model-axis
    collectives (embedding all-to-all) ride the innermost — fastest — ICI
    links.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = _resolve_sizes(spec, len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, spec.axis_names)


@functools.lru_cache(maxsize=None)
def _cached_sharding(mesh: Mesh, pspec: P) -> NamedSharding:
    return NamedSharding(mesh, pspec)


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Leading (batch) dim sharded over ``data``, all other dims replicated."""
    return _cached_sharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return _cached_sharding(mesh, P())
