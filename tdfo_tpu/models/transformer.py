"""Transformer blocks — flax, TPU-first.

Behavioral parity with the reference's hand-rolled torch stack
(``torchrec/models.py:11-129``: scaled dot-product attention with a boolean
mask driven to -1e9, multi-head projection, position-wise feed-forward,
pre-norm residual sublayers).  TPU-first departures:

  * QKV is one fused ``Dense(3*dim)`` matmul (one big MXU op instead of three
    thin ones); heads are split by reshape.
  * softmax runs in f32 regardless of the compute dtype (bf16-safe), and the
    mask fill value is the dtype minimum rather than a hard-coded -1e9.
  * the attention inner function is pluggable (``attn_fn``) so the same block
    serves full attention and ring/blockwise attention over a sequence mesh
    axis (``tdfo_tpu/parallel/ring_attention.py``) without re-wiring.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["dot_product_attention", "MultiHeadAttention", "FeedForward", "TransformerBlock"]


def dot_product_attention(
    q: jax.Array,  # [B, H, T, Dh]
    k: jax.Array,  # [B, H, S, Dh]
    v: jax.Array,  # [B, H, S, Dh]
    mask: jax.Array | None = None,  # broadcastable to [B, H, T, S]; True = attend
) -> jax.Array:
    """Scaled dot-product attention (``torchrec/models.py:11-28`` parity),
    f32 softmax, mask fill = f32 min."""
    dh = q.shape[-1]
    logits = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / jnp.sqrt(dh)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", probs.astype(v.dtype), v)


class MultiHeadAttention(nn.Module):
    """Multi-head self-attention (``torchrec/models.py:31-71`` parity) with a
    fused QKV projection and a pluggable attention core."""

    n_heads: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array | None = None, *,
                 deterministic: bool = True) -> jax.Array:
        b, t, d = x.shape
        if d % self.n_heads:
            raise ValueError(f"dim {d} not divisible by {self.n_heads} heads")
        dh = d // self.n_heads
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="qkv")(x)  # [B, T, 3D]
        # feature layout (head, qkv, dh) — NOT (qkv, head, dh): contiguous
        # chunks of the fused output features are then whole heads, so a
        # Megatron column split of the qkv kernel (megatron_tp_rule) shards
        # cleanly onto the head axis under GSPMD with no resharding.
        # COMPAT: this reinterprets the fused kernel's columns vs the old
        # (qkv, head, dh) layout — same shapes, scrambled values.  Guarded
        # by the checkpoint layout stamp: CheckpointManager refuses to
        # restore checkpoints from a different LAYOUT_VERSION
        # (train/checkpoint.py) instead of resuming silently corrupted.
        qkv = qkv.reshape(b, t, self.n_heads, 3, dh)
        q, k, v = (jnp.swapaxes(qkv[:, :, :, i, :], 1, 2) for i in range(3))  # [B,H,T,Dh]
        out = self.attn_fn(q, k, v, mask)  # [B, H, T, Dh]
        out = jnp.moveaxis(out, 1, 2).reshape(b, t, d)
        out = nn.Dropout(self.dropout)(out, deterministic=deterministic)
        return nn.Dense(d, dtype=self.dtype, name="out")(out)


class FeedForward(nn.Module):
    """Position-wise FFN (``torchrec/models.py:74-88`` parity), GELU."""

    hidden_dim: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        d = x.shape[-1]
        h = nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc1")(x)
        h = jax.nn.gelu(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return nn.Dense(d, dtype=self.dtype, name="fc2")(h)


class TransformerBlock(nn.Module):
    """Pre-norm residual block (``torchrec/models.py:91-129`` parity:
    ``x + dropout(sublayer(LN(x)))`` for attention then FFN)."""

    n_heads: int
    ff_dim: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array | None = None, *,
                 deterministic: bool = True) -> jax.Array:
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        h = MultiHeadAttention(
            self.n_heads, self.dropout, self.dtype, attn_fn=self.attn_fn, name="attn"
        )(h, mask, deterministic=deterministic)
        x = x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_ff")(x)
        h = FeedForward(self.ff_dim, self.dropout, self.dtype, name="ff")(
            h, deterministic=deterministic
        )
        return x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
