"""Bert4Rec — masked-LM sequential recommender, flax + sharded embeddings.

Capability parity with the reference (``torchrec/models.py:132-223``):
``HistoryArch`` (item ``EmbeddingCollection`` + learned positional encoding +
LayerNorm/dropout) feeding N transformer blocks and a vocab-size output
projection; padding id 0, mask token ``n_items + 1``
(``torchrec/preprocessing.py:14-15``); attention mask = key-validity
broadcast to [B, 1, T, T] (``torchrec/models.py:214-219``).

Two usage modes mirror the reference's DMP/DDP split (``torchrec/train.py:235-260``):

  * :class:`Bert4Rec` owns its item table as a flax ``nn.Embed`` — the
    replicated/DDP-equivalent path; one module, one param tree.
  * :class:`Bert4RecBackbone` consumes *already gathered* item vectors, with
    the table living in a :class:`~tdfo_tpu.parallel.embedding.ShardedEmbeddingCollection`
    outside the module — the DMP-equivalent model-parallel path, used with
    ``make_sparse_train_step`` (in-backward sparse optimizer, tables sharded
    over the ``model`` mesh axis).  :func:`make_sharded_bert4rec` wires both
    halves.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import linen as nn

from tdfo_tpu.models.transformer import TransformerBlock, dot_product_attention

__all__ = [
    "PAD_ID",
    "Bert4RecConfig",
    "Bert4RecBackbone",
    "Bert4Rec",
    "make_sharded_bert4rec",
    "init_bert4rec",
]

PAD_ID = 0  # torchrec/preprocessing.py:14


@dataclass(frozen=True)
class Bert4RecConfig:
    """Hyperparameters (``torchrec/utils.py:8-26`` + size_map handshake).

    ``vocab_size = n_items + 2``: PAD(0) + items(1..n) + MASK(n+1)
    (``torchrec/train.py:227-233``).
    """

    n_items: int
    max_len: int = 20
    embed_dim: int = 64
    n_heads: int = 2
    n_layers: int = 2
    ff_mult: int = 4
    dropout: float = 0.1

    @property
    def vocab_size(self) -> int:
        return self.n_items + 2

    @property
    def mask_id(self) -> int:
        return self.n_items + 1


def key_padding_mask(item_ids: jax.Array) -> jax.Array:
    """[B, T] ids -> [B, 1, T, T] attention mask (True = attend); keys at PAD
    are masked for every query (``torchrec/models.py:214-219``)."""
    valid = item_ids != PAD_ID  # [B, T]
    return valid[:, None, None, :]


class Bert4RecBackbone(nn.Module):
    """Everything after the embedding lookup: positional encoding, LN/dropout
    (HistoryArch tail, ``torchrec/models.py:144-146,177-178``), transformer
    stack, vocab projection (``torchrec/models.py:220-223``)."""

    cfg: Bert4RecConfig
    dtype: jnp.dtype = jnp.float32
    attn_fn: staticmethod = staticmethod(dot_product_attention)

    @nn.compact
    def __call__(self, item_embs: jax.Array, mask: jax.Array | None, *,
                 deterministic: bool = True) -> jax.Array:
        cfg = self.cfg
        b, t, d = item_embs.shape
        pos = self.param(
            "pos_embed",
            jax.nn.initializers.normal(0.02),
            (cfg.max_len, d),
            jnp.float32,
        )
        h = item_embs.astype(self.dtype) + pos[None, :t].astype(self.dtype)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_in")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        for i in range(cfg.n_layers):
            h = TransformerBlock(
                n_heads=cfg.n_heads,
                ff_dim=cfg.ff_mult * d,
                dropout=cfg.dropout,
                dtype=self.dtype,
                attn_fn=self.attn_fn,
                name=f"block_{i}",
            )(h, mask, deterministic=deterministic)
        # [B, T, V] — the FLOPs peak; under a mesh the caller constrains the
        # vocab axis (column) sharding if desired.
        return nn.Dense(cfg.vocab_size, dtype=self.dtype, name="out_proj")(h)


class Bert4Rec(nn.Module):
    """Self-contained Bert4Rec (replicated item table — the DDP branch,
    ``torchrec/train.py:256-260``)."""

    cfg: Bert4RecConfig
    dtype: jnp.dtype = jnp.float32
    # same init as the DMP path's EmbeddingSpec(init_scale=1.0) — torchrec's
    # weight_init_min/max = -1/1 — so the two regimes are init-equivalent
    init_scale: float = 1.0

    @nn.compact
    def __call__(self, item_ids: jax.Array, *, deterministic: bool = True) -> jax.Array:
        scale = self.init_scale
        emb = nn.Embed(
            self.cfg.vocab_size,
            self.cfg.embed_dim,
            dtype=self.dtype,
            embedding_init=lambda key, shape, dtype: jax.random.uniform(
                key, shape, dtype, minval=-scale, maxval=scale
            ),
            name="item_embed",
        )
        h = emb(item_ids)
        return Bert4RecBackbone(self.cfg, self.dtype, name="backbone")(
            h, key_padding_mask(item_ids), deterministic=deterministic
        )


def init_bert4rec(rng: jax.Array, cfg: Bert4RecConfig, dtype=jnp.float32):
    model = Bert4Rec(cfg=cfg, dtype=dtype)
    dummy = jnp.zeros((1, cfg.max_len), jnp.int32)
    params = model.init(rng, dummy)["params"]
    return model, params


def make_sharded_bert4rec(
    rng: jax.Array,
    cfg: Bert4RecConfig,
    mesh,
    *,
    sharding: str = "row",
    dtype=jnp.float32,
    attn: str = "full",
    fused_threshold: int | None = 16384,
    fused_kind: str = "adam",
    a2a_capacity_factor: float | None = None,
    ring_block_k: int | None = None,
    tp_heads: bool = False,
    grouped_a2a: bool = False,
):
    """The DMP-equivalent wiring (``torchrec/train.py:235-254``): item table in
    a ShardedEmbeddingCollection (sharded over ``model``), dense transformer
    replicated.

    Returns ``(collection, tables, backbone, dense_params)``; feed a batch as
    ``{"item": [B, T] ids, ...}`` through ``collection.lookup`` then
    ``backbone.apply``.  Pairs with ``make_sparse_train_step``.
    """
    from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection

    coll = ShardedEmbeddingCollection(
        [
            EmbeddingSpec(
                "item_embedding",
                num_embeddings=cfg.vocab_size,
                embedding_dim=cfg.embed_dim,
                features=("item",),
                sharding=sharding,
                init_scale=1.0,  # torchrec weight_init_min/max = -1/1
                # big item catalogues get fused fat-row storage (in-place
                # DMA Adam, O(touched rows) updates)
                fused=(fused_threshold is not None
                       and sharding in ("row", "replicated")
                       and cfg.vocab_size > fused_threshold),
            )
        ],
        mesh=mesh,
        a2a_capacity_factor=a2a_capacity_factor,
        fused_kind=fused_kind,
        grouped_a2a=grouped_a2a,
    )
    k_table, k_dense = jax.random.split(rng)
    tables = coll.init(k_table)
    if attn in ("ring", "ring_flash"):
        # sequence parallelism: attention shards T over the "seq" mesh axis
        # (ring K/V rotation over ICI) — long-context capability beyond the
        # reference's full T×T attention.  ``tp_heads`` composes it with
        # Megatron attention TP (heads over the "model" axis — pair with
        # megatron_tp_rule(n_heads=...) on the dense params); the batch stays
        # sharded over "data" rather than gathering per layer.
        from tdfo_tpu.core.mesh import DATA_AXIS, MODEL_AXIS
        from tdfo_tpu.parallel.ring_attention import make_ring_attn_fn

        attn_fn = make_ring_attn_fn(
            mesh, block_k=ring_block_k,
            head_axis=MODEL_AXIS if tp_heads else None,
            batch_axis=DATA_AXIS,
            impl="flash" if attn == "ring_flash" else "xla",
        )
    elif attn == "flash":
        # single-device long-context path: Pallas blockwise online-softmax
        # kernel, O(T) memory (tdfo_tpu/ops/pallas_kernels.py)
        from tdfo_tpu.ops.pallas_kernels import flash_attention

        def attn_fn(q, k, v, mask=None):
            key_valid = None if mask is None else mask[:, 0, 0, :]
            interp = jax.default_backend() != "tpu"
            return flash_attention(q, k, v, key_valid, interpret=interp)
    elif attn == "full":
        attn_fn = dot_product_attention
    else:
        raise ValueError(f"unknown attn {attn!r}")
    backbone = Bert4RecBackbone(cfg=cfg, dtype=dtype, attn_fn=attn_fn)
    dummy = jnp.zeros((1, cfg.max_len, cfg.embed_dim), dtype)
    dense_params = backbone.init(k_dense, dummy, None)["params"]
    return coll, tables, backbone, dense_params
