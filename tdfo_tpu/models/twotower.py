"""TwoTower CTR model — flax, TPU-first.

Feature/architecture parity with the reference
(``jax-flax/models.py:10-102``; keras twin at ``tensorflow2/models.py:4-71``):
7 categorical embedding tables (user, item, language, is_ebook, format,
publisher, pub_decade) + 2 continuous features (avg_rating, num_pages);
user tower = MLP over the user embedding; item tower = MLP over the concat of
6 item-side embeds + scalars; score = row-wise dot product.

Two usage modes mirror the torchrec DDP/DMP split (``torchrec/train.py:235-260``):

  * :class:`TwoTower` owns its 7 tables as flax ``nn.Embed`` modules — the
    replicated/data-parallel regime (one param tree, dense AdamW), matching
    the reference recipes directly.
  * :class:`TwoTowerBackbone` consumes *already gathered* embedding vectors;
    the tables live in a :class:`~tdfo_tpu.parallel.embedding.ShardedEmbeddingCollection`
    declared by :func:`ctr_embedding_specs` — the DMP-equivalent regime, used
    with ``make_sparse_train_step`` (row-sparse in-backward optimizer, tables
    sharded over the ``model`` mesh axis).  This is the path that scales to
    >=1B-row tables: per-step HBM traffic is O(batch rows), not O(vocab).

TPU-first departures from the reference:
  * compute dtype is a policy (bf16 on TPU) while params stay f32; the
    reference instead cast whole modules (``jax-flax/models.py:122-124``).
  * towers are fused into single batched matmuls (the two hidden layers per
    tower are back-to-back Dense ops on [B, E] — MXU-friendly shapes).
"""

from __future__ import annotations

from typing import Callable, Mapping

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = [
    "TwoTower",
    "TwoTowerBackbone",
    "TWOTOWER_CATEGORICAL",
    "TWOTOWER_CONTINUOUS",
    "init_twotower",
    "ctr_embedding_specs",
]

# item-side categorical features, concat order fixed for parity with
# jax-flax/models.py:89-101
TWOTOWER_ITEM_CATEGORICAL = ("item", "language", "is_ebook", "format", "publisher", "pub_decade")
TWOTOWER_CATEGORICAL = ("user",) + TWOTOWER_ITEM_CATEGORICAL
TWOTOWER_CONTINUOUS = ("avg_rating", "num_pages")

_FEATURE_TO_INPUT = {
    "user": "user_id",
    "item": "item_id",
    "language": "language",
    "is_ebook": "is_ebook",
    "format": "format",
    "publisher": "publisher",
    "pub_decade": "pub_decade",
}


class Tower(nn.Module):
    """Two-layer MLP head (fc1 -> act -> fc2), both widths = embed_dim."""

    embed_dim: int
    activation: Callable = jax.nn.swish
    dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = jax.nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.embed_dim, dtype=self.dtype, kernel_init=self.kernel_init, name="fc1")(x)
        x = self.activation(x)
        return nn.Dense(self.embed_dim, dtype=self.dtype, kernel_init=self.kernel_init, name="fc2")(x)


class TwoTower(nn.Module):
    size_map: Mapping[str, int]
    embed_dim: int
    dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = jax.nn.initializers.glorot_uniform()
    activation: Callable = jax.nn.swish

    def setup(self):
        self.embeds = {
            feat: nn.Embed(
                int(self.size_map[feat]),
                self.embed_dim,
                dtype=self.dtype,
                embedding_init=self.kernel_init,
                name=f"{feat}_embed",
            )
            for feat in TWOTOWER_CATEGORICAL
        }
        self.user_tower = Tower(
            self.embed_dim, self.activation, self.dtype,
            kernel_init=self.kernel_init, name="user_tower",
        )
        self.item_tower = Tower(
            self.embed_dim, self.activation, self.dtype,
            kernel_init=self.kernel_init, name="item_tower",
        )

    def __call__(self, x: Mapping[str, jax.Array]) -> jax.Array:
        u = self.user_embeddings(x)
        v = self.item_embeddings(x)
        return jnp.einsum("be,be->b", u, v)  # [B] logits

    def user_embeddings(self, x) -> jax.Array:
        return self.user_tower(self.embeds["user"](x["user_id"]))

    def item_embeddings(self, x) -> jax.Array:
        parts = [self.embeds[f](x[_FEATURE_TO_INPUT[f]]) for f in TWOTOWER_ITEM_CATEGORICAL]
        parts += [x[c].astype(self.dtype)[:, None] for c in TWOTOWER_CONTINUOUS]
        return self.item_tower(jnp.concatenate(parts, axis=-1))


class TwoTowerBackbone(nn.Module):
    """Dense half of TwoTower for the DMP regime: consumes gathered embedding
    vectors keyed by input-column name (``user_id``, ``item_id``, ...) plus
    the raw batch for continuous features.  Tables live outside the module in
    a ShardedEmbeddingCollection; pairs with ``make_sparse_train_step``."""

    embed_dim: int
    activation: Callable = jax.nn.swish
    dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = jax.nn.initializers.glorot_uniform()

    @nn.compact
    def __call__(
        self, embs: Mapping[str, jax.Array], batch: Mapping[str, jax.Array]
    ) -> jax.Array:
        u = Tower(
            self.embed_dim, self.activation, self.dtype,
            kernel_init=self.kernel_init, name="user_tower",
        )(embs[_FEATURE_TO_INPUT["user"]].astype(self.dtype))
        parts = [
            embs[_FEATURE_TO_INPUT[f]].astype(self.dtype)
            for f in TWOTOWER_ITEM_CATEGORICAL
        ]
        parts += [batch[c].astype(self.dtype)[:, None] for c in TWOTOWER_CONTINUOUS]
        v = Tower(
            self.embed_dim, self.activation, self.dtype,
            kernel_init=self.kernel_init, name="item_tower",
        )(jnp.concatenate(parts, axis=-1))
        return jnp.einsum("be,be->b", u, v)  # [B] logits


def ctr_embedding_specs(
    size_map: Mapping[str, int],
    embed_dim: int,
    sharding: str = "row",
    fused_threshold: int | None = 16384,
):
    """Declare the 7 CTR tables for a ShardedEmbeddingCollection.

    Table ``{feat}_embed`` serves the corresponding input column; init and
    fusion policy live in :func:`~tdfo_tpu.parallel.embedding.make_embedding_specs`
    (shared with the custom-schema builder so the two CTR paths never
    diverge).
    """
    from tdfo_tpu.parallel.embedding import make_embedding_specs

    return make_embedding_specs(
        size_map,
        [(feat, f"{feat}_embed", _FEATURE_TO_INPUT[feat])
         for feat in TWOTOWER_CATEGORICAL],
        embed_dim, sharding, fused_threshold,
    )


def dummy_batch(batch_size: int = 1) -> dict[str, jnp.ndarray]:
    """Shape-inference inputs (init_model parity, jax-flax/models.py:111-121)."""
    ints = {v: jnp.zeros((batch_size,), jnp.int32) for v in _FEATURE_TO_INPUT.values()}
    floats = {c: jnp.zeros((batch_size,), jnp.float32) for c in TWOTOWER_CONTINUOUS}
    return {**ints, **floats, "label": jnp.zeros((batch_size,), jnp.float32)}


def init_twotower(
    rng: jax.Array,
    size_map: Mapping[str, int],
    embed_dim: int,
    dtype: jnp.dtype = jnp.float32,
):
    model = TwoTower(size_map=dict(size_map), embed_dim=embed_dim, dtype=dtype)
    params = model.init(rng, dummy_batch())["params"]
    return model, params
