"""DLRM-style CTR model — feature-interaction head over sharded tables.

The reference contains no DLRM, but the driver's north star
(``/root/repo/BASELINE.json``: DLRM-Criteo examples/sec/chip, >=1B-row
row-sharded embeddings) names the DLRM recipe as the CTR scaling target.  This
module is the standard DLRM architecture (bottom MLP over dense features,
pairwise dot-product interactions between all embedding vectors and the
bottom output, top MLP over [bottom, interactions]) expressed TPU-first:

  * it consumes *gathered* embedding vectors — the tables are declared with
    :func:`tdfo_tpu.models.twotower.ctr_embedding_specs` and live in a
    :class:`~tdfo_tpu.parallel.embedding.ShardedEmbeddingCollection`, so the
    model always runs in the DMP regime (``make_sparse_train_step``:
    row-sparse in-backward optimizer, per-step traffic O(batch) not O(vocab));
  * the interaction is one batched ``einsum`` ([B, F, D] x [B, F, D] ->
    [B, F, F]) — a single MXU-shaped contraction instead of per-pair ops;
  * all layers run in the compute dtype policy (bf16 on TPU), params f32.

Feature set matches the CTR pipeline (7 categorical + 2 continuous,
``jax-flax/preprocessing.py`` schema) so DLRM trains from the exact same
preprocessed data as TwoTower.
"""

from __future__ import annotations

from typing import Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from tdfo_tpu.models.twotower import (
    TWOTOWER_CATEGORICAL,
    TWOTOWER_CONTINUOUS,
    _FEATURE_TO_INPUT,
)

__all__ = ["DLRMBackbone", "generic_embedding_specs"]

# default schema: the Goodreads CTR columns (TwoTower parity data)
_DEFAULT_CAT_COLUMNS = tuple(_FEATURE_TO_INPUT[f] for f in TWOTOWER_CATEGORICAL)


def generic_embedding_specs(
    size_map: Mapping[str, int],
    columns: tuple[str, ...],
    embed_dim: int,
    sharding: str = "row",
    fused_threshold: int | None = 16384,
):
    """Declare one table per categorical COLUMN (custom-schema CTR: e.g. the
    26 Criteo tables).  Init and fusion policy are shared with
    :func:`~tdfo_tpu.models.twotower.ctr_embedding_specs` via
    :func:`~tdfo_tpu.parallel.embedding.make_embedding_specs`."""
    from tdfo_tpu.parallel.embedding import make_embedding_specs

    return make_embedding_specs(
        size_map, [(col, f"{col}_embed", col) for col in columns],
        embed_dim, sharding, fused_threshold,
    )


class DLRMBackbone(nn.Module):
    """Bottom MLP -> pairwise dot interactions -> top MLP -> [B] logits.

    ``embs``: gathered vectors keyed by input-column name (one [B, D] array
    per categorical feature); ``batch`` supplies the continuous columns.
    """

    embed_dim: int
    bottom_dims: tuple[int, ...] = (64,)
    top_dims: tuple[int, ...] = (128, 64)
    dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = jax.nn.initializers.glorot_uniform()
    # feature schema by input-column name; defaults = the Goodreads CTR
    # columns, overridden for custom schemas (Criteo: 26 cats + 13 conts)
    cat_columns: tuple[str, ...] = _DEFAULT_CAT_COLUMNS
    cont_columns: tuple[str, ...] = TWOTOWER_CONTINUOUS

    @nn.compact
    def __call__(
        self, embs: Mapping[str, jax.Array], batch: Mapping[str, jax.Array]
    ) -> jax.Array:
        # bottom MLP over the continuous features, projected to embed_dim so
        # it joins the interaction as an (F+1)-th vector (standard DLRM).
        # A schema with NO continuous columns skips the bottom vector and
        # interacts the embeddings alone.
        stack = [embs[c].astype(self.dtype) for c in self.cat_columns]
        if self.cont_columns:
            x = jnp.stack(
                [batch[c].astype(self.dtype) for c in self.cont_columns],
                axis=-1,
            )  # [B, C]
            for i, width in enumerate(self.bottom_dims):
                x = nn.Dense(width, dtype=self.dtype,
                             kernel_init=self.kernel_init,
                             name=f"bottom_{i}")(x)
                x = nn.relu(x)
            x = nn.Dense(self.embed_dim, dtype=self.dtype,
                         kernel_init=self.kernel_init, name="bottom_out")(x)
            x = nn.relu(x)  # [B, D]
            stack.append(x)

        vecs = jnp.stack(stack, axis=1)  # [B, F(+1), D]
        inter = jnp.einsum("bfd,bgd->bfg", vecs, vecs)  # one MXU contraction
        f = vecs.shape[1]
        iu, ju = np.triu_indices(f, k=1)  # static at trace time
        flat = inter[:, iu, ju]  # [B, F(F+1)/2 - F] upper-triangle pairs

        top = (jnp.concatenate([x, flat], axis=-1) if self.cont_columns
               else flat)
        for i, width in enumerate(self.top_dims):
            top = nn.Dense(width, dtype=self.dtype, kernel_init=self.kernel_init,
                           name=f"top_{i}")(top)
            top = nn.relu(top)
        return nn.Dense(1, dtype=self.dtype, kernel_init=self.kernel_init,
                        name="top_out")(top)[:, 0]  # [B] logits
