"""tdfo_tpu — a TPU-native distributed training framework for recommender
workloads, providing the full capability surface of massquantity/tdfo
(TwoTower CTR + Bert4Rec sequential recommendation, data/model/sequence
parallelism, streaming data, checkpointing) re-designed for JAX/XLA/Pallas
on device meshes.

Layering (SURVEY.md §7):
  core/      config + mesh + precision (L0 + distribution bootstrap)
  data/      jagged tensors, preprocessing ETLs, streaming loaders (L1)
  models/    TwoTower, Bert4Rec, transformer blocks (L2)
  parallel/  sharded embedding collections, sharding plans, collectives (L3)
  ops/       Pallas kernels + XLA compound ops (native compute layer)
  train/     state, steps, metrics, checkpoint, epoch driver (L4)
  utils/     logging, timing, profiling
"""

from tdfo_tpu.core.config import Config, MeshSpec, read_configs
from tdfo_tpu.core.mesh import make_mesh, spoof_cpu_devices

__version__ = "0.1.0"

__all__ = ["Config", "MeshSpec", "read_configs", "make_mesh", "spoof_cpu_devices"]
