"""Goodreads sequential ETL — Bert4Rec masked-LM training + sampled eval data.

Capability parity with ``torchrec/preprocessing.py``, re-implemented on
pandas/numpy with vectorised window generation (the reference loops per user,
``torchrec/preprocessing.py:194-221``):

  * interactions: users with 20..200 interactions, per-user sorted items
    (``:28-43``); ids remapped 1-based contiguous, PAD_ID=0,
    MASK_ID=n_items+1 (``:14-15,46-72``).
  * split: leave-last-two — last item test, second-to-last eval, rest train
    (``:83-109``; the reference computes the test item and then only keeps
    train/eval — here all three are returned and train/eval written).
  * masking: each train item masked with prob ``mask_prob``; the LAST item of
    every user sequence is always masked (paper protocol, ``:112-150``);
    labels = original item where masked else PAD_ID.
  * sliding windows: length ``max_len``, stride ``sliding_step``, PAD-padded
    tail (``:194-221``).
  * eval: last ``max_len - 1`` train items + MASK, LEFT-padded to ``max_len``
    (``:229-239``); candidates = [eval item] + 100 popularity-sampled
    negatives excluding the user's positives (``:16,260-315``).
  * output: 2 pandas-parquet shards per split (list columns), train shuffled
    seed 42 (``:318-334``), plus ``size_map_bert4rec.json``.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pandas as pd

from tdfo_tpu.data.shards import shard_ranges, write_df_part

__all__ = ["run_seq_preprocessing", "PAD_ID", "EVAL_NEG_NUM"]

MIN_INTERACTIONS = 20
MAX_INTERACTIONS = 200
PAD_ID = 0
EVAL_NEG_NUM = 100
FILE_NUM = 2


def read_interactions(data_dir: Path) -> pd.DataFrame:
    df = pd.read_csv(
        data_dir / "goodreads_interactions.csv",
        dtype={"user_id": np.int32, "book_id": np.int32},
        usecols=["user_id", "book_id"],
    )
    counts = df.groupby("user_id")["book_id"].transform("size")
    df = df[(counts >= MIN_INTERACTIONS) & (counts <= MAX_INTERACTIONS)]
    return df.sort_values(["user_id", "book_id"], kind="stable").reset_index(drop=True)


def map_ids(df: pd.DataFrame) -> tuple[pd.DataFrame, int, int]:
    """1-based contiguous ids; 0 is PAD, n_items+1 becomes MASK."""
    out = pd.DataFrame(index=df.index)
    sizes = {}
    for col in ("user_id", "book_id"):
        uniq = np.sort(df[col].unique())
        mapping = pd.Series(np.arange(1, len(uniq) + 1, dtype=np.int32), index=uniq)
        out[col] = mapping[df[col].to_numpy()].to_numpy()
        sizes[col] = len(uniq)
    n_users, n_items = sizes["user_id"], sizes["book_id"]
    assert out["user_id"].min() == 1 and out["user_id"].max() == n_users
    assert out["book_id"].min() == 1 and out["book_id"].max() == n_items
    return out, n_users, n_items


def item_popularity(df: pd.DataFrame) -> tuple[np.ndarray, np.ndarray]:
    counts = df["book_id"].value_counts()
    items = counts.index.to_numpy(dtype=np.int32)
    probs = (counts.to_numpy() / counts.sum()).astype(np.float64)
    return items, probs


def split_leave_last_two(df: pd.DataFrame) -> pd.DataFrame:
    """Per user (items sorted): train = seq[:-2], eval = seq[-2], test = seq[-1]."""
    g = df.groupby("user_id")["book_id"]
    agg = g.agg(list)
    return pd.DataFrame({
        "user_id": agg.index.to_numpy(dtype=np.int32),
        "train": [np.asarray(s[:-2], np.int32) for s in agg],
        "eval_item": np.asarray([s[-2] for s in agg], np.int32),
        "test_item": np.asarray([s[-1] for s in agg], np.int32),
    })


def mask_train_sequences(
    split: pd.DataFrame, mask_prob: float, mask_id: int, rng: np.random.Generator
) -> tuple[list[np.ndarray], list[np.ndarray], float]:
    """BERT-style masking + always-mask-last; returns (inputs, labels, ratio)."""
    inputs, labels = [], []
    n_masked = n_total = 0
    for seq in split["train"]:
        draw = rng.random(len(seq), dtype=np.float32)
        m = draw <= mask_prob
        if len(m):
            m[-1] = True  # always mask the final item (paper protocol)
        inp = np.where(m, mask_id, seq).astype(np.int32)
        lab = np.where(m, seq, PAD_ID).astype(np.int32)
        inputs.append(inp)
        labels.append(lab)
        n_masked += int(m.sum())
        n_total += len(seq)
    ratio = n_masked / max(n_total, 1)
    return inputs, labels, ratio


def sliding_windows(
    user_ids: np.ndarray,
    inputs: list[np.ndarray],
    labels: list[np.ndarray],
    max_len: int,
    step: int,
    pad: bool = True,
) -> pd.DataFrame:
    """Windows of ``max_len`` at stride ``step`` over each user's sequence.

    ``pad=True`` PAD-pads every window to ``max_len`` (offline padding, the
    original recipe).  ``pad=False`` writes RAGGED windows — true lengths
    only, no storage wasted on padding — for the runtime jagged path
    (``Config.jagged``), where the trainer ships (values, lengths) and
    ``jagged_to_dense`` runs inside the jitted step (torchrec KJT parity,
    ``torchrec/train.py:33-41``).
    """
    users, starts, seq_idx = [], [], []
    for i, (u, seq) in enumerate(zip(user_ids, inputs)):
        for s in range(0, max(len(seq), 1), step):
            users.append(u)
            starts.append(s)
            seq_idx.append(i)
    if pad:
        win_items = np.full((len(starts), max_len), PAD_ID, np.int32)
        win_labels = np.full((len(starts), max_len), PAD_ID, np.int32)
        for row, (i, s) in enumerate(zip(seq_idx, starts)):
            chunk = inputs[i][s : s + max_len]
            win_items[row, : len(chunk)] = chunk
            lab = labels[i][s : s + max_len]
            win_labels[row, : len(lab)] = lab
        items_col, labels_col = list(win_items), list(win_labels)
    else:
        items_col = [inputs[i][s : s + max_len].astype(np.int32)
                     for i, s in zip(seq_idx, starts)]
        labels_col = [labels[i][s : s + max_len].astype(np.int32)
                      for i, s in zip(seq_idx, starts)]
    return pd.DataFrame({
        "user_id": np.asarray(users, np.int32),
        "train_interactions": items_col,
        "labels": labels_col,
    })


def eval_sequences(split: pd.DataFrame, max_len: int, mask_id: int) -> list[np.ndarray]:
    """(train tail + MASK) right-aligned in a LEFT-padded window of max_len."""
    seqs = []
    for seq in split["train"]:
        tail = np.concatenate([seq[-(max_len - 1):], [mask_id]]).astype(np.int32)
        out = np.full((max_len,), PAD_ID, np.int32)
        out[-len(tail):] = tail
        seqs.append(out)
    return seqs


def test_sequences(split: pd.DataFrame, max_len: int, mask_id: int) -> list[np.ndarray]:
    """Leave-one-out TEST inputs: by test time the eval item is known history,
    so the window is (train + eval_item) tail + MASK.  The reference computes
    its test split and never consumes it (``train_val_test`` neither writes
    nor evaluates it, ``/root/reference/torchrec/train.py:147-177``) — this
    framework writes test shards and runs a final post-fit test evaluation."""
    seqs = []
    for seq, ev in zip(split["train"], split["eval_item"]):
        hist = np.concatenate([seq, [ev]])
        tail = np.concatenate([hist[-(max_len - 1):], [mask_id]]).astype(np.int32)
        out = np.full((max_len,), PAD_ID, np.int32)
        out[-len(tail):] = tail
        seqs.append(out)
    return seqs


def sample_negatives(
    split: pd.DataFrame,
    items: np.ndarray,
    probs: np.ndarray,
    rng: np.random.Generator,
    n_neg: int = EVAL_NEG_NUM,
    extra_positives: list[np.ndarray] | None = None,
) -> list[np.ndarray]:
    """Per user: ``n_neg`` unique popularity-weighted negatives excluding the
    user's positives (train + eval item, plus ``extra_positives`` rows — the
    test split passes the test item so test candidates never leak it).

    Shared-pool amortisation (the reference's scheme, ``:260-299``): weighted
    no-replacement draws cost O(n_items) each, so one pool serves many users —
    each user consumes a slice sized ``n_pos + n_neg + slack``, set-differences
    its positives, and only the rare short rows trigger a per-user top-up.
    Unlike the reference (set-difference then ``head(100)``, which can leave
    SHORT rows), every user here ends with exactly ``n_neg`` candidates
    (fixed-width rows batch with static shapes); only a catalog smaller than
    positives + n_neg cycle-pads with duplicates."""
    n_avail = len(items)
    needs = [len(seq) + n_neg + 16 for seq in split["train"]]
    chunk = max(min(n_avail, max(needs)), min(n_avail, 4 * n_neg))

    pool = np.empty((0,), np.int64)

    def refill(min_size: int):
        nonlocal pool
        parts = [pool]
        have = len(pool)
        while have < min_size:
            draw = rng.choice(items, size=chunk, replace=False, p=probs)
            parts.append(draw)
            have += chunk
        pool = np.concatenate(parts)

    extras = extra_positives or [np.empty((0,), np.int32)] * len(split)
    out = []
    for seq, ev, extra, need in zip(split["train"], split["eval_item"], extras, needs):
        pos = set(seq.tolist())
        pos.add(int(ev))
        pos.update(int(x) for x in np.atleast_1d(extra))
        want = min(n_neg, n_avail - len(pos))
        refill(need)
        slice_, pool = pool[:need], pool[need:]
        keep = pd.unique(slice_[~np.isin(slice_, list(pos))])[:n_neg]
        while len(keep) < want:  # rare: slack eaten by overlap/duplicates
            refill(chunk)
            top_up, pool = pool[:chunk], pool[chunk:]
            top_up = top_up[~np.isin(top_up, list(pos))]
            keep = pd.unique(np.concatenate([keep, top_up]))[:n_neg]
        if len(keep) < n_neg:  # tiny catalog: duplicate rather than go ragged
            keep = np.resize(keep, n_neg)
        out.append(keep.astype(np.int32))
    return out


def write_shards(data_dir: Path, df: pd.DataFrame, prefix: str, *,
                 file_num: int = FILE_NUM, seed: int = 42) -> list[Path]:
    write_dir = data_dir / "parquet_bert4rec"
    write_dir.mkdir(exist_ok=True)
    return [
        write_df_part(df.iloc[start:end], write_dir, prefix, i,
                      shuffle=prefix == "train", seed=seed)
        for i, start, end in shard_ranges(len(df), file_num)
    ]


def run_seq_preprocessing(
    data_dir: str | Path,
    *,
    max_len: int = 20,
    sliding_step: int = 10,
    mask_prob: float = 0.2,
    seed: int = 42,
    file_num: int = FILE_NUM,
    pad: bool = True,
) -> dict[str, int]:
    """Full ETL: raw interactions -> masked train windows + eval candidates.
    ``pad=False`` writes ragged train windows for the runtime jagged path."""
    data_dir = Path(data_dir)
    rng = np.random.default_rng(seed)

    raw = read_interactions(data_dir)
    data, n_users, n_items = map_ids(raw)
    mask_id = n_items + 1
    items, probs = item_popularity(data)
    with open(data_dir / "size_map_bert4rec.json", "w") as f:
        json.dump({"n_users": n_users, "n_items": n_items}, f, indent=4)

    split = split_leave_last_two(data)
    inputs, labels, ratio = mask_train_sequences(split, mask_prob, mask_id, rng)
    train_df = sliding_windows(
        split["user_id"].to_numpy(), inputs, labels, max_len, sliding_step,
        pad=pad,
    )
    write_shards(data_dir, train_df, "train", file_num=file_num, seed=seed)

    eval_seqs = eval_sequences(split, max_len, mask_id)
    negs = sample_negatives(split, items, probs, rng)
    eval_df = pd.DataFrame({
        "user_id": split["user_id"],
        "eval_seqs": eval_seqs,
        "candidate_items": [
            np.concatenate([[ev], ng]).astype(np.int32)
            for ev, ng in zip(split["eval_item"], negs)
        ],
    })
    write_shards(data_dir, eval_df, "eval", file_num=file_num, seed=seed)

    # test split (leave-last-one): the reference computes test_item and drops
    # it (torchrec/preprocessing.py:83-109, train.py:147-177); here it is
    # written with the SAME column names as eval so the trainer's eval
    # machinery serves both by swapping the file pattern.
    tst_seqs = test_sequences(split, max_len, mask_id)
    tst_negs = sample_negatives(
        split, items, probs, rng,
        extra_positives=[np.asarray([t], np.int32) for t in split["test_item"]],
    )
    test_df = pd.DataFrame({
        "user_id": split["user_id"],
        "eval_seqs": tst_seqs,
        "candidate_items": [
            np.concatenate([[t], ng]).astype(np.int32)
            for t, ng in zip(split["test_item"], tst_negs)
        ],
    })
    write_shards(data_dir, test_df, "test", file_num=file_num, seed=seed)
    return {"n_users": n_users, "n_items": n_items, "masked_ratio": ratio}
