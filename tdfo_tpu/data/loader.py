"""Streaming data loading: parquet shards -> shuffled, host-sharded, device-fed
batches.

Unifies the reference's three loading stacks (HF iterable datasets with a 2M
shuffle buffer, ``jax-flax/train_dp.py:94-136``; ``tf.data`` with
shuffle/prefetch/AUTOTUNE, ``tensorflow2/data.py:134-210``; torchrec's
``split_dataset_by_node`` DataLoader, ``torchrec/data.py:13-49``) into one
pyarrow-native pipeline with no per-row Python:

  * :class:`ParquetStream` — record-batch streaming with a block shuffle
    buffer (each row emitted exactly once per epoch; mixing radius =
    ``buffer_size``), per-host sharding (files round-robin when there are
    enough files, else strided batch slices — ``split_dataset_by_node``
    parity), epoch reseeding (``set_epoch`` parity), and ``drop_last`` for
    static shapes (``jax-flax/train_dp.py:111-114`` rationale: ragged final
    batches would retrigger XLA compilation).
  * :func:`load_parquet_table` / :func:`permutation_batches` — the map-style
    full-permutation loader (``jax-flax/train.py:52-70`` parity).
  * :func:`prefetch_to_mesh` — double-buffered host->HBM transfer onto a
    named mesh (``flax.jax_utils.prefetch_to_device`` parity,
    ``jax-flax/train_dp.py:211``), multihost-aware via
    ``jax.make_array_from_process_local_data``.

List-typed columns (Bert4Rec windows) are stacked into dense [B, T] arrays at
the arrow level.
"""

from __future__ import annotations

import glob as _glob
import zlib
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from tdfo_tpu.utils.retry import retry_call

# failure modes a corrupted/truncated shard presents as: quarantinable when
# the stream was configured with max_bad_shards > 0
_BAD_SHARD_ERRORS = (OSError, EOFError, zlib.error, pa.ArrowException)

__all__ = [
    "ParquetStream",
    "TFRecordStream",
    "MapStream",
    "load_parquet_table",
    "permutation_batches",
    "prefetch_to_mesh",
]


def _to_numpy_columns(batch: pa.RecordBatch | pa.Table,
                      allow_ragged: bool = False) -> dict[str, np.ndarray]:
    """Arrow -> dict of numpy; fixed-width list columns become [B, T] arrays.

    With ``allow_ragged`` (the jagged training path), variable-length list
    columns become object arrays of per-row numpy arrays — the shuffle/slice
    machinery is row-indexed either way, and consumers pack them into
    (values, lengths) at batch emit (``tdfo_tpu/data/jagged.py``).  Without
    it, ragged data fails HERE with an actionable message instead of as an
    obscure object-dtype error at device transfer."""
    out: dict[str, np.ndarray] = {}
    for name, col in zip(batch.schema.names, batch.columns):
        if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
            arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            flat = arr.flatten().to_numpy(zero_copy_only=False)
            offsets = arr.offsets.to_numpy(zero_copy_only=False)
            widths = np.diff(offsets)
            ragged = len(widths) and (widths != widths[0]).any()
            if ragged and not allow_ragged:
                raise ValueError(
                    f"list column {name!r} is ragged; these shards were "
                    "written for the jagged path (config jagged = true) "
                    "— or pad them in preprocessing"
                )
            if allow_ragged:
                # ALWAYS object rows under allow_ragged — an arrow batch
                # whose rows coincidentally share one length must not switch
                # representation mid-stream (the shuffle pool concatenates
                # across batches and mixed ndim crashes it)
                # flatten() is slice-aware but .offsets is absolute: rebase
                # so sliced arrays split correctly
                rel = offsets - offsets[0]
                rows = np.split(flat, rel[1:-1])
                obj = np.empty(len(arr), dtype=object)
                for i, r in enumerate(rows):
                    obj[i] = r
                out[name] = obj
                continue
            t = int(widths[0]) if len(widths) else 0
            out[name] = flat.reshape(len(arr), t)
        else:
            out[name] = col.to_numpy(zero_copy_only=False)
    return out


def _concat_rows(parts: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


def _take(d: dict[str, np.ndarray], idx) -> dict[str, np.ndarray]:
    return {k: v[idx] for k, v in d.items()}


def resolve_files(data_dir: str | Path, pattern: str) -> list[str]:
    files = sorted(_glob.glob(str(Path(data_dir) / pattern)))
    if not files:
        raise FileNotFoundError(f"no parquet files match {pattern!r} in {data_dir}")
    return files


class ParquetStream:
    """Streaming shuffled batches from parquet shards.

    Each epoch yields every (host-local) row exactly once, in an order
    randomised by (seed, epoch): file order is permuted, then rows pass
    through a ``buffer_size``-row block shuffle.  With ``drop_last`` the
    ragged tail batch is dropped (train); otherwise it is emitted short
    (eval, to be padded by the caller).
    """

    def __init__(
        self,
        files: Sequence[str],
        batch_size: int,
        *,
        shuffle: bool = True,
        buffer_size: int = 2_000_000,  # jax-flax/train_dp.py:129 default
        seed: int = 42,
        drop_last: bool = True,
        process_index: int | None = None,
        process_count: int | None = None,
        columns: Sequence[str] | None = None,
        allow_ragged: bool = False,
        num_workers: int = 0,
        max_bad_shards: int = 0,
    ):
        import jax

        self.files = list(files)
        # corrupted-shard quarantine: files that failed to open/decode are
        # skipped (0 rows) with a warning; the (max_bad_shards+1)-th bad
        # shard is fatal.  0 keeps the historical any-failure-is-fatal
        # behaviour.
        self.max_bad_shards = int(max_bad_shards)
        self._bad_files: dict[str, str] = {}
        # resume support: _skip batches are fast-forwarded (decoded and
        # discarded) by the next __iter__; _emitted tracks this epoch's
        # position for state_dict().  One live iterator per stream.
        self._skip = 0
        self._emitted = 0
        self.allow_ragged = allow_ragged
        # >0: that many background threads read files ahead of the consumer
        # (order-preserving, so shuffles stay deterministic) — the
        # capability the reference gets from tf.data num_parallel_reads /
        # DataLoader num_workers; pyarrow/zlib release the GIL, so plain
        # threads pipeline decode behind device compute.
        self.num_workers = int(num_workers)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.buffer_size = int(buffer_size)
        self.seed = seed
        self.drop_last = drop_last
        self.columns = list(columns) if columns is not None else None
        self._epoch = 0
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        self.process_count = (
            jax.process_count() if process_count is None else process_count
        )
        # split_dataset_by_node parity (torchrec/data.py:58): whole files per
        # host when they divide evenly, else strided row-block sharding.
        self._shard_by_file = (
            self.process_count > 1 and len(self.files) % self.process_count == 0
        )

    # ---- file-format hooks (overridden by TFRecordStream) ----

    def _file_row_count(self, path: str) -> int:
        return retry_call(
            lambda: pq.ParquetFile(path).metadata.num_rows,
            description=f"parquet_metadata:{Path(path).name}",
        )

    def _file_batches(self, path: str):
        pf = retry_call(pq.ParquetFile, path,
                        description=f"open_shard:{Path(path).name}")
        for rb in pf.iter_batches(batch_size=65536, columns=self.columns):
            yield _to_numpy_columns(rb, allow_ragged=self.allow_ragged)

    # ---- corrupted-shard quarantine ----

    def _quarantine(self, path: str, err: BaseException) -> None:
        """Record ``path`` as bad (skip + warn).  Raises once MORE than
        ``max_bad_shards`` distinct shards have failed — a data set that
        rotten is a pipeline bug, not a shard to shrug off."""
        if path not in self._bad_files:
            self._bad_files[path] = f"{type(err).__name__}: {err}"
            print(f"[loader] quarantined bad shard {path}: "
                  f"{self._bad_files[path]} "
                  f"({len(self._bad_files)}/{self.max_bad_shards} allowed)",
                  flush=True)
        if len(self._bad_files) > self.max_bad_shards:
            raise RuntimeError(
                f"{len(self._bad_files)} corrupted shard(s), more than "
                f"max_bad_shards={self.max_bad_shards} allows: "
                f"{self._bad_files}"
            ) from err

    def _row_count_safe(self, path: str) -> int:
        """Row count with quarantine: a shard whose footer/sidecar cannot be
        read counts 0 rows and is excluded from iteration — deterministic
        across hosts because EVERY host scans every footer for the budget."""
        if path in self._bad_files:
            return 0
        try:
            return self._file_row_count(path)
        except _BAD_SHARD_ERRORS as e:
            self._quarantine(path, e)
            return 0

    def _files_batches(self, files: Sequence[str]):
        """All batches across ``files`` in order; with ``num_workers`` > 0 a
        background thread per in-flight file decodes into a small BOUNDED
        queue (never a whole materialised file), up to ``num_workers`` files
        ahead of the consumer.  Order is preserved — determinism is part of
        the loader's contract — and host memory stays O(num_workers x a few
        arrow batches)."""
        files = [f for f in files if f not in self._bad_files]
        if self.num_workers <= 0:
            for f in files:
                try:
                    yield from self._file_batches(f)
                except _BAD_SHARD_ERRORS as e:
                    # mid-read corruption: rows already emitted from this
                    # shard stay emitted; the remainder is quarantined.  On
                    # multi-host meshes this can shrink one host's row count
                    # below the footer-derived budget — shared-storage
                    # corruption is visible to every host, but keep
                    # max_bad_shards=0 on pods unless shards replicate.
                    self._quarantine(f, e)
            return
        import collections
        import queue as _queue
        import threading

        _END = object()
        # set when the consumer abandons the generator (exception mid-epoch,
        # generator GC): workers must notice and exit instead of blocking on
        # a full queue forever, pinning open readers and decoded batches
        stop = threading.Event()

        def start_reader(path: str):
            q: _queue.Queue = _queue.Queue(maxsize=2)

            def put(item) -> bool:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        return True
                    except _queue.Full:
                        continue
                return False

            def worker():
                try:
                    for d in self._file_batches(path):
                        if not put(d):
                            return
                    put(_END)
                except BaseException as e:  # surfaced on the consumer side
                    put(e)

            t = threading.Thread(target=worker, daemon=True)
            t.start()
            return q

        pending: collections.deque = collections.deque()
        it = iter(files)
        try:
            for _ in range(self.num_workers):
                f = next(it, None)
                if f is None:
                    break
                pending.append((f, start_reader(f)))
            while pending:
                path, q = pending.popleft()
                while True:
                    item = q.get()
                    if item is _END:
                        break
                    if isinstance(item, BaseException):
                        if isinstance(item, _BAD_SHARD_ERRORS):
                            self._quarantine(path, item)  # skip the rest
                            break
                        raise item
                    yield item
                f = next(it, None)
                if f is not None:
                    pending.append((f, start_reader(f)))
        finally:
            stop.set()
            for _, q in pending:  # unblock any waiting worker
                while not q.empty():
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        break

    def _batches_per_host(self) -> int | None:
        """Cross-host batch budget from parquet metadata (no communication).

        Hosts MUST run the same number of batches per epoch or the first
        collective after the shortest host's last batch deadlocks the mesh
        (SURVEY.md §7 hard part #4).  Row counts come from file footers, so
        every host computes the same minimum independently."""
        if self.process_count <= 1:
            return None
        if self._shard_by_file:
            rows = [
                sum(
                    self._row_count_safe(f)
                    for f in self.files[r :: self.process_count]
                )
                for r in range(self.process_count)
            ]
            min_rows = min(rows)
        else:
            # strided: rank r owns global rows g with g % P == r_assigned;
            # the smallest share is floor(N / P).
            n = sum(self._row_count_safe(f) for f in self.files)
            min_rows = n // self.process_count
        return min_rows // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle order for a new epoch (HF ``set_epoch`` parity,
        ``jax-flax/train.py:143``).  Clears any pending resume fast-forward —
        call :meth:`load_state_dict` AFTER set_epoch to resume mid-epoch."""
        self._epoch = int(epoch)
        self._skip = 0

    # ---- step-granular resume (checkpoint cursor contract) ----

    def state_dict(self) -> dict[str, int]:
        """Position cursor: (seed, epoch, batches emitted this epoch).  The
        epoch's batch sequence is a pure function of (seed, epoch) — file
        permutation, block shuffle and batch assembly all derive from
        ``default_rng((seed, epoch))`` — so the cursor pins the exact batch.

        NOTE: counts batches handed to the CALLER of ``__iter__``.  Behind a
        prefetcher, count consumed batches yourself (the Trainer does) and
        build the cursor from that."""
        return {"seed": int(self.seed), "epoch": int(self._epoch),
                "batches_emitted": int(self._emitted)}

    def load_state_dict(self, state: dict[str, int]) -> None:
        """Resume: the next ``__iter__`` fast-forwards ``batches_emitted``
        batches (decode-and-discard — the shuffle pool must replay to
        reproduce the stream bit-exactly) and yields from there."""
        if int(state.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"stream cursor was recorded with seed "
                f"{state['seed']}, this stream uses {self.seed} — resuming "
                "would yield a different batch sequence"
            )
        self._epoch = int(state["epoch"])
        self._skip = int(state["batches_emitted"])

    def max_batches_per_host(self) -> int:
        """The LARGEST per-host batch count this epoch (ceil division, no
        drop_last) — the eval-loop budget: every host must run this many step
        calls, topping up with zero-weight padding batches, or the mesh
        deadlocks (same invariant as :meth:`_batches_per_host`, opposite
        rounding)."""
        counts = []
        for r in range(max(self.process_count, 1)):
            if self._shard_by_file:
                rows = sum(
                    self._row_count_safe(f)
                    for f in self.files[r :: self.process_count]
                )
            else:
                n = sum(self._row_count_safe(f) for f in self.files)
                p = max(self.process_count, 1)
                rows = (n - r + p - 1) // p
            counts.append(-(-rows // self.batch_size))
        return max(counts)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        budget = self._batches_per_host() if self.drop_last else None
        skip, self._skip = self._skip, 0
        pos = 0
        self._emitted = 0
        for batch in self._iter_unbounded():
            if budget is not None and pos >= budget:
                return
            pos += 1
            self._emitted = pos
            if pos <= skip:
                continue  # resume fast-forward: already consumed pre-crash
            yield batch

    def _iter_unbounded(self) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.default_rng((self.seed, self._epoch))
        files = list(self.files)
        if self._shard_by_file:
            files = files[self.process_index :: self.process_count]
        if self.shuffle:
            rng.shuffle(files)

        def raw_batches():
            stride_pos = 0
            for d in self._files_batches(files):
                if not self._shard_by_file and self.process_count > 1:
                    # strided slice so every host sees a disjoint subset
                    n = len(next(iter(d.values())))
                    idx = np.arange(
                        (self.process_index - stride_pos) % self.process_count,
                        n,
                        self.process_count,
                    )
                    stride_pos = (stride_pos + n) % self.process_count
                    d = _take(d, idx)
                yield d

        pool: list[dict[str, np.ndarray]] = []
        pooled = 0
        pending: list[dict[str, np.ndarray]] = []
        pend_n = 0

        def emit(d):
            nonlocal pending, pend_n
            pending.append(d)
            pend_n += len(next(iter(d.values())))
            while pend_n >= self.batch_size:
                rows = _concat_rows(pending)
                n = len(next(iter(rows.values())))
                yield _take(rows, slice(0, self.batch_size))
                rest = _take(rows, slice(self.batch_size, n))
                pending = [rest]
                pend_n = n - self.batch_size

        for d in raw_batches():
            if not self.shuffle:
                yield from emit(d)
                continue
            pool.append(d)
            pooled += len(next(iter(d.values())))
            if pooled >= self.buffer_size:
                rows = _concat_rows(pool)
                perm = rng.permutation(pooled)
                half = pooled // 2  # emit half, keep half for further mixing
                yield from emit(_take(rows, perm[:half]))
                pool = [_take(rows, perm[half:])]
                pooled -= half
        if pool:
            rows = _concat_rows(pool)
            yield from emit(_take(rows, rng.permutation(pooled)))
        if pend_n and not self.drop_last:
            yield _concat_rows(pending)


class TFRecordStream(ParquetStream):
    """The same streaming pipeline over TFRecord shards
    (``tensorflow2/data.py:171-210`` capability — schema comes from the
    Example protos themselves instead of ``FixedLenFeature`` declarations).

    Row counts come from the ``{prefix}_data_size.json`` sidecar written at
    preprocessing time (``tensorflow2/data.py:83-84`` parity); scanning a
    gzip TFRecord just to count it would defeat streaming.
    """

    def __init__(self, files, batch_size, *, compression: str | None = "GZIP",
                 **kw):
        super().__init__(files, batch_size, **kw)
        self.compression = compression
        self._row_counts: dict[str, int] = {}

    def _file_row_count(self, path: str) -> int:
        from tdfo_tpu.data.tfrecord import read_shard_sizes, read_tfrecord_records

        if path not in self._row_counts:
            p = Path(path)
            prefix = p.name.split("_part_")[0]
            sizes = read_shard_sizes(p.parent, prefix)
            if sizes is not None and p.name in sizes:
                for name, n in sizes.items():
                    self._row_counts[str(p.parent / name)] = n
            else:
                # no per-shard sidecar: count by scanning once, then CACHE
                # the count to a sidecar so later epochs (and other runs /
                # hosts) never rescan the whole gzip stream again
                self._row_counts[path] = retry_call(
                    lambda: sum(
                        1 for _ in read_tfrecord_records(path, self.compression)
                    ),
                    description=f"scan_tfrecord:{p.name}",
                )
                from tdfo_tpu.data.tfrecord import write_shard_sizes_entry

                write_shard_sizes_entry(
                    p.parent, prefix, p.name, self._row_counts[path]
                )
        return self._row_counts[path]

    def _file_batches(self, path: str):
        from tdfo_tpu.data.tfrecord import (
            decode_example,
            read_tfrecord_records,
            stack_example_rows,
        )

        rows: list[dict[str, np.ndarray]] = []
        for payload in read_tfrecord_records(path, self.compression):
            rows.append(decode_example(payload))
            if len(rows) >= 8192:
                yield stack_example_rows(rows, self.columns)
                rows = []
        if rows:
            yield stack_example_rows(rows, self.columns)


def count_rows(files: Sequence[str]) -> int:
    """Total row count from parquet metadata without reading data
    (``get_data_size`` parity, ``jax-flax/utils.py:36-38``)."""
    return sum(pq.ParquetFile(f).metadata.num_rows for f in files)


def load_parquet_table(files: Sequence[str],
                       columns: Sequence[str] | None = None) -> dict[str, np.ndarray]:
    """Map-style: read everything into memory (``jax-flax/train.py:52-60``)."""
    tables = [pq.read_table(f, columns=list(columns) if columns else None) for f in files]
    return _to_numpy_columns(pa.concat_tables(tables).combine_chunks())


def permutation_batches(
    data: dict[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 42,
    epoch: int = 0,
    drop_last: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Full-permutation epoch over an in-memory table
    (``jax-flax/train.py:52-70`` parity)."""
    n = len(next(iter(data.values())))
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng((seed, epoch)).shuffle(idx)
    end = n - n % batch_size if drop_last else n
    for i in range(0, end, batch_size):
        yield _take(data, idx[i : i + batch_size])


class MapStream:
    """Map-style epochs over an in-memory table, presenting the same
    interface as :class:`ParquetStream` (``config streaming = false``;
    ``jax-flax/train.py:52-70`` full-permutation loader parity).

    Single-process only: the whole table lives on this host, so multi-host
    budget logic does not apply (use the streaming loader on pods).
    """

    def __init__(self, files: Sequence[str], batch_size: int, *,
                 shuffle: bool = True, seed: int = 42, drop_last: bool = True,
                 columns: Sequence[str] | None = None):
        import jax

        if jax.process_count() > 1:
            raise ValueError(
                "streaming=false (map-style) loading is single-process only; "
                "multi-host runs need the streaming loader's per-host budgets"
            )
        self.table = load_parquet_table(files, columns)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0
        self._skip = 0
        self._emitted = 0
        self._n = len(next(iter(self.table.values())))

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self._skip = 0

    def state_dict(self) -> dict[str, int]:
        """Same cursor contract as :meth:`ParquetStream.state_dict`."""
        return {"seed": int(self.seed), "epoch": int(self._epoch),
                "batches_emitted": int(self._emitted)}

    def load_state_dict(self, state: dict[str, int]) -> None:
        """Resume mid-epoch; map-style skip is O(1) (index arithmetic into
        the epoch permutation), no replay needed."""
        if int(state.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"stream cursor was recorded with seed "
                f"{state['seed']}, this stream uses {self.seed}"
            )
        self._epoch = int(state["epoch"])
        self._skip = int(state["batches_emitted"])

    def max_batches_per_host(self) -> int:
        # must mirror the __iter__ count exactly: drop_last floors, else ceils
        if self.drop_last:
            return self._n // self.batch_size
        return -(-self._n // self.batch_size)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        skip, self._skip = self._skip, 0
        self._emitted = skip
        idx = np.arange(self._n)
        if self.shuffle:
            np.random.default_rng((self.seed, self._epoch)).shuffle(idx)
        end = self._n - self._n % self.batch_size if self.drop_last else self._n
        for i in range(skip * self.batch_size, end, self.batch_size):
            self._emitted += 1
            yield _take(self.table, idx[i : i + self.batch_size])


def prefetch_to_mesh(it, mesh, pspec=None, *, size: int = 2):
    """Double-buffered host->device transfer onto a mesh.

    ``jax-flax/train_dp.py:210-211`` parity (shard + prefetch_to_device(2)):
    keeps ``size`` batches in flight; jax dispatches transfers asynchronously
    so compute overlaps the next batch's copy.  Multihost: each host provides
    its local rows via ``make_array_from_process_local_data``.

    Jagged batches need no special casing: per-host-packed ``values`` and
    ``lengths`` both ship batch-sharded ``P("data")`` (each process provides
    exactly its local slice), and ``jagged_to_dense_per_host`` reads the
    host-segmented layout back inside the step.
    """
    import collections

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, pspec if pspec is not None else P("data"))

    def put(batch):
        if jax.process_count() > 1:
            return {
                k: jax.make_array_from_process_local_data(sharding, v)
                for k, v in batch.items()
            }
        return jax.device_put(batch, sharding)

    q = collections.deque()
    it = iter(it)
    try:
        for _ in range(size):
            q.append(put(next(it)))
    except StopIteration:
        pass
    while q:
        b = q.popleft()
        try:
            q.append(put(next(it)))
        except StopIteration:
            pass
        yield b
