"""Criteo-style CTR ETL — the BASELINE.json north-star data family.

The reference pipelines only cover Goodreads (``jax-flax/preprocessing.py``,
``tensorflow2/preprocessing.py``); the driver's north star targets
DLRM-Criteo (``/root/repo/BASELINE.json``: examples/sec/chip on
Criteo-class data, >=1B-row tables).  This ETL brings the Criteo display-ads
format (``label \\t 13 ints \\t 26 hex categoricals`` per line, TSV, no
header — the Kaggle/Terabyte layout) into the SAME on-disk contract the rest
of the framework consumes: shuffled parquet shards under ``data_dir/parquet``
plus ``size_map.json`` — so the generic-schema DLRM trainer
(``Config.categorical_features``) runs on it unchanged.

Transforms (standard DLRM recipe):
  * integer features: missing -> 0, clipped at 0, ``log1p``, then min-max to
    [0, 1] with GLOBAL min/max (mirrors the Goodreads ETL's continuous
    handling, ``jax-flax/preprocessing.py:110-128`` semantics);
  * categorical features: frequency-thresholded vocab (values seen >=
    ``min_freq`` times get ids 1.. by descending frequency; everything else
    — incl. missing — folds into the out-of-vocab id 0), the standard
    Criteo-DLRM vocabulary construction;
  * split: the ROW-ORDERED tail ``eval_fraction`` becomes eval (Criteo rows
    are time-ordered; the reference's per-user leave-tail split has no
    meaning here).

Two streaming passes over the TSV (stats+vocab, then transform+write), so
memory stays O(vocab), not O(rows).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

import numpy as np
import pandas as pd

from tdfo_tpu.data.shards import shard_ranges, write_df_part

__all__ = [
    "CRITEO_CONTINUOUS",
    "CRITEO_CATEGORICAL",
    "run_criteo_preprocessing",
]

N_CONT, N_CAT = 13, 26
CRITEO_CONTINUOUS = tuple(f"cont_{i}" for i in range(N_CONT))
CRITEO_CATEGORICAL = tuple(f"cat_{i}" for i in range(N_CAT))
_COLUMNS = ("label", *CRITEO_CONTINUOUS, *CRITEO_CATEGORICAL)
FILE_NUM = 8


def _chunks(path: Path, chunksize: int):
    return pd.read_csv(
        path, sep="\t", header=None, names=_COLUMNS,
        dtype={c: "Float64" for c in CRITEO_CONTINUOUS}
        | {c: "string" for c in CRITEO_CATEGORICAL} | {"label": np.int8},
        chunksize=chunksize,
    )


def run_criteo_preprocessing(
    data_dir: str | Path,
    *,
    source: str = "train.txt",
    min_freq: int = 4,
    eval_fraction: float = 0.1,
    file_num: int = FILE_NUM,
    seed: int = 42,
    chunksize: int = 500_000,
    hot_vocab: int = 0,
    hot_fraction: float = 0.9,
) -> dict[str, int]:
    """TSV -> parquet shards + size_map.json.  Returns the size map.

    ``hot_vocab > 0`` additionally emits the hot/cold remap artifact
    (``hot_ids.json``, see ``tdfo_tpu/data/hot_ids.py``) from the SAME
    pass-1 frequency counts the vocab build consumes — no extra scan.
    Because this ETL assigns ids 1.. by descending frequency (0 = OOV,
    which absorbs the below-threshold + missing mass and usually ranks in
    the head), hot sets are contiguous ``[0, K)`` prefixes whenever the
    OOV mass makes the cut — the layout the collection detects and remaps
    branch-free with one compare (otherwise one sort-method
    searchsorted)."""
    data_dir = Path(data_dir)
    src = data_dir / source

    # ---- pass 1: row count, per-column min/max of log1p, vocab counts ----
    n_rows = 0
    lo = np.full(N_CONT, np.inf)
    hi = np.full(N_CONT, -np.inf)
    counts: list[Counter] = [Counter() for _ in range(N_CAT)]
    for chunk in _chunks(src, chunksize):
        n_rows += len(chunk)
        for i, c in enumerate(CRITEO_CONTINUOUS):
            v = np.log1p(chunk[c].fillna(0).clip(lower=0).to_numpy(np.float64))
            if len(v):
                lo[i] = min(lo[i], float(v.min()))
                hi[i] = max(hi[i], float(v.max()))
        for i, c in enumerate(CRITEO_CATEGORICAL):
            counts[i].update(chunk[c].dropna())
    if n_rows == 0:
        raise ValueError(f"no rows in {src}")

    vocab_maps: list[dict[str, int]] = []
    size_map: dict[str, int] = {}
    for i, c in enumerate(CRITEO_CATEGORICAL):
        kept = [v for v, n in counts[i].most_common() if n >= min_freq]
        vocab_maps.append({v: j + 1 for j, v in enumerate(kept)})  # 0 = OOV
        size_map[c] = len(kept) + 1
    with open(data_dir / "size_map.json", "w") as f:
        json.dump(size_map, f, indent=4)

    # per-table id lookup counts from the SAME pass-1 frequency scan: id 0
    # (OOV) folds the below-threshold + missing lookup mass — every row
    # contributes exactly one lookup per column
    id_counts_by_col: dict[str, np.ndarray] = {}
    for i, c in enumerate(CRITEO_CATEGORICAL):
        kept_counts = [n for _, n in counts[i].most_common() if n >= min_freq]
        id_counts = np.zeros(size_map[c], np.int64)
        id_counts[0] = n_rows - sum(kept_counts)
        id_counts[1:] = kept_counts
        id_counts_by_col[c] = id_counts

    # always emit the planner's traffic-stats artifact (plan/stats.py):
    # the auto-sharding planner prices per-table placements from it, and
    # it costs no extra scan
    from tdfo_tpu.plan.stats import write_table_stats

    write_table_stats(data_dir, id_counts_by_col)

    if hot_vocab > 0:
        from tdfo_tpu.data.hot_ids import hot_ids_from_counts, write_hot_ids

        per_table: dict[str, "np.ndarray"] = {}
        coverage: dict[str, float] = {}
        for c in CRITEO_CATEGORICAL:
            id_counts = id_counts_by_col[c]
            per_table[c] = hot_ids_from_counts(
                id_counts, hot_vocab=hot_vocab, hot_fraction=hot_fraction)
            coverage[c] = float(id_counts[per_table[c]].sum() / n_rows)
        write_hot_ids(data_dir, per_table, hot_vocab=hot_vocab,
                      hot_fraction=hot_fraction, coverage=coverage)

    # ---- pass 2: transform, split by time order, STREAM to shards --------
    # Rows append to open parquet writers as they stream past — no
    # transformed copy of the dataset ever exists in memory (the property
    # that makes Criteo-Terabyte-scale runs possible).  Train rows land on a
    # uniformly random shard, so each shard is a random SUBSET in time order;
    # the loader's file-order permutation + shuffle buffer finish the
    # randomisation at read time (vs the Goodreads ETL, which is small
    # enough to pre-shuffle whole shards in memory).
    import pyarrow as pa
    import pyarrow.parquet as pq

    n_eval = int(n_rows * eval_fraction)
    if n_eval == 0 or n_eval == n_rows:
        raise ValueError(
            f"degenerate split: {n_rows} rows at eval_fraction="
            f"{eval_fraction} leaves {'no eval' if n_eval == 0 else 'no train'} "
            "rows — provide more data or adjust eval_fraction"
        )
    split_at = n_rows - n_eval
    span = np.where(hi > lo, hi - lo, 1.0)
    write_dir = data_dir / "parquet"
    write_dir.mkdir(exist_ok=True)
    rng = np.random.default_rng(seed)
    writers: dict[tuple[str, int], pq.ParquetWriter] = {}

    def append(prefix: str, shard: int, df: pd.DataFrame) -> None:
        tbl = pa.Table.from_pandas(df, preserve_index=False)
        key = (prefix, shard)
        if key not in writers:
            writers[key] = pq.ParquetWriter(
                write_dir / f"{prefix}_part_{shard}.parquet", tbl.schema
            )
        writers[key].write_table(tbl)

    seen = 0
    try:
        for chunk in _chunks(src, chunksize):
            out = pd.DataFrame(index=chunk.index)
            out["label"] = chunk["label"].to_numpy(np.int8)
            for i, c in enumerate(CRITEO_CONTINUOUS):
                v = np.log1p(
                    chunk[c].fillna(0).clip(lower=0).to_numpy(np.float64))
                out[c] = ((v - lo[i]) / span[i]).astype(np.float32)
            for i, c in enumerate(CRITEO_CATEGORICAL):
                out[c] = (
                    chunk[c].map(vocab_maps[i]).fillna(0).to_numpy(np.int32)
                )
            cut = max(0, min(len(out), split_at - seen))
            if cut:
                train = out.iloc[:cut]
                shard_of = rng.integers(0, file_num, len(train))
                for s in np.unique(shard_of):
                    append("train", int(s), train.iloc[shard_of == s])
            if cut < len(out):
                ev = out.iloc[cut:]
                # time-ordered eval rows round-robin over shards by chunk
                append("eval", (seen + cut) // chunksize % file_num, ev)
            seen += len(out)
    finally:
        for w in writers.values():
            w.close()
    return size_map
