"""Synthetic Goodreads raw files — test/demo fixture for the ETL pipeline.

Generates the four raw inputs the preprocessing layer consumes
(``goodreads_interactions.csv``, ``goodreads_books.json`` ndjson,
``user_id_map.csv``, ``book_id_map.csv``) with the same schema and the same
dirt the real dump has: empty strings in categoricals/continuous, years
outside [1900, 2030], ``num_pages`` outliers above 2000 — so every cleaning
branch of the ETL is exercised.  The reference has no such fixture (it has no
tests at all, SURVEY.md §4); this is part of the test pyramid it lacks.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pandas as pd

__all__ = ["write_synthetic_goodreads", "write_synthetic_criteo", "zipf_ids"]


def zipf_ids(rng: np.random.Generator, vocab: int, size,
             *, a: float = 1.2) -> np.ndarray:
    """Frequency-RANKED power-law ids: id ``i`` drawn with mass ∝ (i+1)^-a,
    so low ids are the hot head — exactly the layout the Criteo ETL
    produces (ids assigned by descending frequency, 0 = OOV absorbing the
    folded tail).  Samples past the vocab wrap onto the head (they carry
    the zipf tail's negligible mass).  The bench harness uses this to
    model real power-law lookup traffic; uniform ids would understate
    every frequency-partitioned optimisation."""
    ids = rng.zipf(a, size).astype(np.int64) - 1
    return (ids % vocab).astype(np.int32)

_LANGS = ["eng", "en-US", "spa", "fre", "ger", ""]
_FORMATS = ["Paperback", "Hardcover", "ebook", "Audio CD", ""]
_PUBLISHERS = [f"publisher_{i}" for i in range(12)] + [""]


def write_synthetic_goodreads(
    data_dir: str | Path,
    *,
    n_users: int = 120,
    n_books: int = 300,
    interactions_per_user: tuple[int, int] = (5, 60),
    seed: int = 0,
    signal: float = 0.0,
) -> Path:
    """Write raw files under ``data_dir``; returns the dir.  Zipf-ish item
    popularity so popularity-weighted negative sampling has signal.

    ``signal`` in [0, 1] plants LEARNABLE structure (default 0 keeps the
    historical pure-noise fixtures byte-identical): books fall into latent
    clusters, each user has a theme cluster, themed draws are preferred
    with probability ``signal``, and ratings are biased up on theme
    matches.  The CTR label (rating >= 4) then correlates with the
    user x item embedding interaction and item sequences are
    theme-coherent — so converged eval AUC / Recall@K measurably beat the
    0.5 / popularity floors (the quality-parity evidence the reference
    establishes with real Goodreads data, torchrec/train.py:143-144,
    jax-flax/train_dp.py:219-245).
    """
    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)

    # --- interactions: variable per-user counts, popularity-skewed items.
    # ids are 0-based contiguous, exactly like the real goodreads dump (the
    # id-map CSVs define the contiguous range; Embed tables are sized by the
    # map row count, so an id == n_users would be out of bounds). ---
    item_weights = 1.0 / np.arange(1, n_books + 1) ** 0.8
    item_weights /= item_weights.sum()
    n_clusters = 8
    book_cluster = np.arange(n_books) % n_clusters
    rows = []
    for u in range(n_users):
        k = int(rng.integers(*interactions_per_user))
        k = min(k, n_books)
        if signal > 0.0:
            theme = int(rng.integers(0, n_clusters))
            w = item_weights * np.where(
                book_cluster == theme, 1.0 + 19.0 * signal, 1.0)
            w /= w.sum()
            books = rng.choice(np.arange(n_books), size=k, replace=False, p=w)
            match = book_cluster[books] == theme
            # themed books rate high, off-theme low (plus noise): the
            # rating>=4 label becomes predictable from (user, item)
            base = np.where(match, 4.3, 1.7)
            ratings = np.clip(np.round(
                base + rng.normal(0.0, 1.2 * (1.0 - signal) + 0.6, size=k)
            ), 0, 5).astype(int)
        else:
            books = rng.choice(np.arange(n_books), size=k, replace=False,
                               p=item_weights)
            ratings = rng.integers(0, 6, size=k)
        for b, r in zip(books, ratings):
            rows.append((u, int(b), int(rng.integers(0, 2)), int(r),
                         int(rng.integers(0, 2))))
    inter = pd.DataFrame(rows, columns=["user_id", "book_id", "is_read",
                                        "rating", "is_reviewed"])
    inter.to_csv(data_dir / "goodreads_interactions.csv", index=False)

    # --- id maps (contiguous id -> original id) ---
    pd.DataFrame({
        "user_id_csv": np.arange(n_users),
        "user_id": [f"u{i:08x}" for i in range(n_users)],
    }).to_csv(data_dir / "user_id_map.csv", index=False)
    pd.DataFrame({
        "book_id_csv": np.arange(n_books),
        "book_id": [f"b{i:08x}" for i in range(n_books)],
    }).to_csv(data_dir / "book_id_map.csv", index=False)

    # --- book metadata ndjson, with dirty fields ---
    with open(data_dir / "goodreads_books.json", "w") as f:
        for i in range(n_books):
            year = int(rng.integers(1880, 2035))  # some out of decade range
            pages = int(rng.integers(20, 3000))  # some past the 2000 outlier bound
            rec = {
                "book_id": f"b{i:08x}",
                "language_code": str(rng.choice(_LANGS)),
                "is_ebook": bool(rng.integers(0, 2)),
                "average_rating": "" if rng.random() < 0.05 else f"{rng.uniform(1, 5):.2f}",
                "format": str(rng.choice(_FORMATS)),
                "publisher": str(rng.choice(_PUBLISHERS)),
                "num_pages": "" if rng.random() < 0.1 else str(pages),
                "publication_year": "" if rng.random() < 0.1 else str(year),
            }
            f.write(json.dumps(rec) + "\n")
    return data_dir


def write_synthetic_criteo(
    data_dir: str | Path,
    *,
    n_rows: int = 4000,
    seed: int = 0,
) -> Path:
    """Criteo-format ``train.txt`` fixture: label \\t 13 ints \\t 26 hex cats,
    TSV, no header, with the real dump's dirt — missing ints, missing cats,
    skewed (zipf) category popularity so the frequency-thresholded vocab
    build has both kept and OOV-folded values."""
    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)
    lines = []
    cat_pools = [
        [f"{rng.integers(0, 2**32):08x}" for _ in range(max(4, 3 + i * 2))]
        for i in range(26)
    ]
    for _ in range(n_rows):
        label = int(rng.random() < 0.25)
        ints = []
        for i in range(13):
            if rng.random() < 0.15:
                ints.append("")  # missing
            else:
                ints.append(str(int(rng.zipf(1.7)) - 1 + (i % 3)))
        cats = []
        for i in range(26):
            if rng.random() < 0.1:
                cats.append("")  # missing
            else:
                pool = cat_pools[i]
                # zipf-ranked pick: head values frequent, tail values rare
                j = min(int(rng.zipf(1.5)) - 1, len(pool) - 1)
                cats.append(pool[j])
        lines.append("\t".join([str(label), *ints, *cats]))
    (data_dir / "train.txt").write_text("\n".join(lines) + "\n")
    return data_dir
