"""TFRecord support without TensorFlow — native C++ codec + pure-Python fallback.

The reference's ``write_format = "tfrecord"`` path leans on TF's C++ runtime
(``tensorflow2/data.py:70-131``: ``tf.io.TFRecordWriter`` with GZIP,
``tf.train.Example`` protos, ``FixedLenFeature`` parsing, and a
``{prefix}_data_size.json`` row-count sidecar).  This module re-implements
that contract standalone:

  * ``tf.train.Example`` protobuf wire format (Features map of
    bytes_list/float_list/int64_list) encoded/decoded directly — no protobuf
    runtime needed for these three fixed shapes.
  * TFRecord framing (u64 length + masked crc32c + payload + crc) via the
    C++ library (``tdfo_tpu/native``) when available, pure Python otherwise;
    GZIP optional exactly like the reference.
  * row-count sidecar parity (``tensorflow2/data.py:83-84`` →
    ``get_data_size``, ``tensorflow2/utils.py:41-48``).
"""

from __future__ import annotations

import gzip
import json
import struct
from pathlib import Path
from typing import Iterator, Mapping, Sequence

import numpy as np

from tdfo_tpu.native import load_native

__all__ = [
    "encode_example",
    "decode_example",
    "write_tfrecord_file",
    "read_tfrecord_records",
    "write_tfrecord_shards",
    "read_tfrecord_columns",
    "write_size_sidecar",
    "read_size_sidecar",
]


# ------------------------------------------------------------ protobuf wire


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def encode_example(row: Mapping[str, object]) -> bytes:
    """One ``tf.train.Example`` from a dict of scalars/sequences.

    int -> int64_list, float -> float_list, bytes/str -> bytes_list
    (the schema at ``tensorflow2/data.py:108-131``)."""
    entries = b""
    for key, value in row.items():
        kind = None  # "bytes" | "float" | "int"; None = infer from values
        if isinstance(value, np.ndarray):
            if np.issubdtype(value.dtype, np.floating):
                kind = "float"
            elif np.issubdtype(value.dtype, np.integer):
                kind = "int"
        if isinstance(value, (bytes, str)):
            values = [value.encode() if isinstance(value, str) else value]
        elif isinstance(value, (int, np.integer, float, np.floating)):
            values = [value]
        else:
            values = list(value)
        if kind == "bytes" or (kind is None and values and isinstance(values[0], (bytes, str))):
            payload = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v) for v in values
            )
            feature = _ld(1, payload)  # Feature.bytes_list
        elif kind == "float" or (kind is None and values and isinstance(values[0], (float, np.floating))):
            packed = struct.pack(f"<{len(values)}f", *values)
            feature = _ld(2, _varint(1 << 3 | 2) + _varint(len(packed)) + packed)
        else:
            packed = b"".join(_varint(int(v) & (2**64 - 1)) for v in values)
            feature = _ld(3, _varint(1 << 3 | 2) + _varint(len(packed)) + packed)
        entry = _ld(1, key.encode()) + _ld(2, feature)  # map entry
        entries += _ld(1, entry)  # Features.feature
    return _ld(1, entries)  # Example.features


def _decode_list(buf: memoryview) -> list:
    """BytesList/FloatList/Int64List inner payload -> python list."""
    pos = 0
    out: list = []
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        wt = tag & 7
        if wt == 2:  # bytes value OR packed numeric run
            ln, pos = _read_varint(buf, pos)
            out.append(bytes(buf[pos : pos + ln]))
            pos += ln
        elif wt == 0:  # unpacked varint
            v, pos = _read_varint(buf, pos)
            out.append(v)
        elif wt == 5:  # unpacked float
            out.append(struct.unpack("<f", buf[pos : pos + 4])[0])
            pos += 4
        else:
            raise ValueError(f"unexpected wire type {wt} in list")
    return out


def decode_example(payload: bytes) -> dict[str, np.ndarray]:
    """Example bytes -> dict of numpy arrays (int64 / float32 / object)."""
    buf = memoryview(payload)
    pos = 0
    out: dict[str, np.ndarray] = {}
    tag, pos = _read_varint(buf, pos)
    assert tag >> 3 == 1, "not an Example"
    flen, pos = _read_varint(buf, pos)
    features = buf[pos : pos + flen]
    fpos = 0
    while fpos < len(features):
        tag, fpos = _read_varint(features, fpos)
        elen, fpos = _read_varint(features, fpos)
        entry = features[fpos : fpos + elen]
        fpos += elen
        epos = 0
        key = None
        feature = None
        while epos < len(entry):
            tag, epos = _read_varint(entry, epos)
            ln, epos = _read_varint(entry, epos)
            if tag >> 3 == 1:
                key = bytes(entry[epos : epos + ln]).decode()
            else:
                feature = entry[epos : epos + ln]
            epos += ln
        if key is None or feature is None:
            continue
        ftag, fp = _read_varint(feature, 0)
        kind = ftag >> 3  # 1 bytes, 2 float, 3 int64
        llen, fp = _read_varint(feature, fp)
        inner = feature[fp : fp + llen]
        if kind == 1:
            out[key] = np.array(_decode_list(inner), dtype=object)
        else:
            # inner is `repeated value` — either one packed blob or unpacked
            ipos = 0
            vals: list = []
            while ipos < len(inner):
                vtag, ipos = _read_varint(inner, ipos)
                if vtag & 7 == 2:  # packed
                    ln, ipos = _read_varint(inner, ipos)
                    blob = inner[ipos : ipos + ln]
                    ipos += ln
                    if kind == 2:
                        vals.extend(struct.unpack(f"<{len(blob) // 4}f", blob))
                    else:
                        bpos = 0
                        while bpos < len(blob):
                            v, bpos = _read_varint(blob, bpos)
                            vals.append(v - 2**64 if v >= 2**63 else v)
                elif vtag & 7 == 5:
                    vals.append(struct.unpack("<f", inner[ipos : ipos + 4])[0])
                    ipos += 4
                else:
                    v, ipos = _read_varint(inner, ipos)
                    vals.append(v - 2**64 if v >= 2**63 else v)
            out[key] = np.asarray(
                vals, dtype=np.float32 if kind == 2 else np.int64
            )
    return out


# -------------------------------------------------------------- frame codec


_CRC_TABLE: list[int] | None = None


def _crc32c_py(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (poly ^ (c >> 1)) if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    lib = load_native()
    if lib is not None and data:
        import ctypes

        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return lib.tdfo_masked_crc32c(buf, len(data))
    crc = _crc32c_py(data)
    return (((crc >> 15) | ((crc << 17) & 0xFFFFFFFF)) + 0xA282EAD8) & 0xFFFFFFFF


def write_tfrecord_file(path: str | Path, records: Sequence[bytes],
                        compression: str | None = "GZIP") -> None:
    """Framed records to a file; GZIP matches the reference's writer options
    (``tensorflow2/data.py:114-116``).

    Production path: ONE native batch call per shard (framing + crc32c + gzip
    all in C++, ``tdfo_tfrecord_write_batch``); pure-Python fallback when the
    toolchain is absent."""
    lib = load_native()
    if lib is not None:
        import ctypes

        buf = b"".join(records)
        offsets = np.zeros(len(records) + 1, np.uint64)
        np.cumsum([len(r) for r in records], out=offsets[1:])
        mode = b"wb" if compression == "GZIP" else b"wbT"  # T = transparent
        handle = lib.tdfo_file_open(str(path).encode(), mode)
        if handle:
            try:
                # zero-copy view into the joined bytes (the C side reads
                # const uint8*) — from_buffer_copy would double the shard's
                # transient memory
                cbuf = ctypes.cast(
                    ctypes.c_char_p(buf or b"\0"),
                    ctypes.POINTER(ctypes.c_uint8),
                )
                rc = lib.tdfo_tfrecord_write_batch(
                    handle, cbuf,
                    offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                    len(records),
                )
            finally:
                lib.tdfo_file_close(handle)
            if rc != 0:
                raise IOError(f"native tfrecord write failed at record {rc - 1}")
            return
    opener = gzip.open if compression == "GZIP" else open
    with opener(path, "wb") as f:
        for payload in records:
            hdr = struct.pack("<Q", len(payload))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))


def read_tfrecord_records(path: str | Path,
                          compression: str | None = "GZIP") -> Iterator[bytes]:
    """Yield verified record payloads.

    Production path: native frame reader (gzread auto-detects gzip vs plain;
    length/data crc verification in C++); pure-Python fallback otherwise."""
    lib = load_native()
    if lib is not None:
        import ctypes

        handle = lib.tdfo_file_open(str(path).encode(), b"rb")
        if handle:
            try:
                n = ctypes.c_uint64()
                buf = (ctypes.c_uint8 * 4096)()  # grown as records demand
                while True:
                    rc = lib.tdfo_tfrecord_next_len(handle, ctypes.byref(n))
                    if rc == 1:
                        return
                    if rc == -1:  # short header read: cut-off file, not bitrot
                        raise IOError(f"truncated tfrecord header in {path}")
                    if rc != 0:
                        raise IOError(f"tfrecord length crc mismatch ({rc})")
                    if n.value > len(buf):
                        buf = (ctypes.c_uint8 * n.value)()
                    rc = lib.tdfo_tfrecord_read_payload(handle, buf, n.value)
                    if rc != 0:
                        raise IOError(f"tfrecord data crc mismatch ({rc})")
                    # single copy out of the reused buffer
                    yield ctypes.string_at(buf, n.value)
            finally:
                lib.tdfo_file_close(handle)
    opener = gzip.open if compression == "GZIP" else open
    with opener(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            if len(hdr) != 12:
                raise IOError("truncated tfrecord header")
            (n,) = struct.unpack("<Q", hdr[:8])
            (crc,) = struct.unpack("<I", hdr[8:])
            if _masked_crc(hdr[:8]) != crc:
                raise IOError("tfrecord length crc mismatch")
            payload = f.read(n)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(payload) != dcrc:
                raise IOError("tfrecord data crc mismatch")
            yield payload


# ---------------------------------------------------------- columnar layer


def write_tfrecord_shards(
    columns: Mapping[str, np.ndarray],
    write_dir: str | Path,
    prefix: str,
    *,
    file_num: int = 8,
    compression: str | None = "GZIP",
) -> list[Path]:
    """Dict-of-arrays -> Example-per-row tfrecord shards + row-count sidecar
    (``tensorflow2/data.py:70-105`` parity)."""
    write_dir = Path(write_dir)
    write_dir.mkdir(parents=True, exist_ok=True)
    n = len(next(iter(columns.values())))
    from tdfo_tpu.data.shards import shard_ranges

    paths = []
    shard_sizes: dict[str, int] = {}
    for i, start, end in shard_ranges(n, file_num):
        records = [
            encode_example({k: v[r] for k, v in columns.items()})
            for r in range(start, end)
        ]
        p = write_dir / f"{prefix}_part_{i}.tfrecord"
        write_tfrecord_file(p, records, compression)
        shard_sizes[p.name] = end - start
        paths.append(p)
    write_size_sidecar(write_dir, prefix, n, shard_sizes)
    return paths


def read_tfrecord_columns(
    files: Sequence[str | Path], compression: str | None = "GZIP"
) -> dict[str, np.ndarray]:
    """All rows of the shards as stacked columns (map-style read)."""
    rows = []
    for f in files:
        for payload in read_tfrecord_records(f, compression):
            rows.append(decode_example(payload))
    return stack_example_rows(rows) if rows else {}


def write_size_sidecar(write_dir: str | Path, prefix: str, n_rows: int,
                       shard_sizes: Mapping[str, int] | None = None) -> None:
    payload: dict = {"data_size": int(n_rows)}
    if shard_sizes:
        payload["shard_sizes"] = {k: int(v) for k, v in shard_sizes.items()}
    with open(Path(write_dir) / f"{prefix}_data_size.json", "w") as f:
        json.dump(payload, f)


def read_size_sidecar(write_dir: str | Path, prefix: str) -> int | None:
    p = Path(write_dir) / f"{prefix}_data_size.json"
    if not p.exists():
        return None
    with open(p) as f:
        # sidecars grown by write_shard_sizes_entry after fallback scans
        # carry shard_sizes only — no fabricated total
        size = json.load(f).get("data_size")
    return None if size is None else int(size)


def read_shard_sizes(write_dir: str | Path, prefix: str) -> dict[str, int] | None:
    """Per-shard row counts recorded by :func:`write_tfrecord_shards`."""
    p = Path(write_dir) / f"{prefix}_data_size.json"
    if not p.exists():
        return None
    with open(p) as f:
        sizes = json.load(f).get("shard_sizes")
    return {k: int(v) for k, v in sizes.items()} if sizes else None


def write_shard_sizes_entry(write_dir: str | Path, prefix: str,
                            shard_name: str, n_rows: int) -> None:
    """Record one shard's row count into the sidecar (creating it if
    absent) — the loader calls this after a fallback full scan of a shard
    whose sidecar is missing, so the O(dataset) rescan happens at most once
    per shard ever, not once per epoch-budget computation per host.  Best
    effort: a read-only data dir keeps the in-memory count only."""
    import os

    p = Path(write_dir) / f"{prefix}_data_size.json"
    try:
        doc = {}
        if p.exists():
            with open(p) as f:
                doc = json.load(f)
        # only the per-shard map is maintained here — "data_size" (the
        # dataset total) stays untouched: a partially-scanned directory
        # must not masquerade as a complete count
        sizes = doc.setdefault("shard_sizes", {})
        sizes[shard_name] = int(n_rows)
        # per-process tmp name + atomic replace: concurrent hosts hitting
        # the fallback scan together must never interleave into one file
        tmp = p.with_suffix(f".json.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        tmp.replace(p)
    except (OSError, ValueError):
        # best effort: unwritable dirs or a concurrently-garbled sidecar
        # keep the in-memory count only
        pass


def stack_example_rows(
    rows: Sequence[Mapping[str, np.ndarray]],
    columns: Sequence[str] | None = None,
) -> dict[str, np.ndarray]:
    """Decoded Example rows -> dict of columns: length-1 features concatenate
    to scalars, fixed-width features stack to [B, T]."""
    out: dict[str, np.ndarray] = {}
    for k in rows[0]:
        if columns is not None and k not in columns:
            continue
        vals = [r[k] for r in rows]
        out[k] = np.concatenate(vals) if all(len(v) == 1 for v in vals) else np.stack(vals)
    return out
