"""Goodreads CTR ETL — TwoTower features, parquet shards, size_map contract.

Capability parity with ``jax-flax/preprocessing.py`` (and its twin
``tensorflow2/preprocessing.py``), re-implemented on pandas/pyarrow (this
image carries no polars) with vectorised groupby/merge instead of
row-level apply:

  * interactions: keep users with 10..250 interactions, label = rating>=4,
    per-user sorted item lists (``jax-flax/preprocessing.py:40-71``).
  * book features: 5 categoricals (empty -> "unknown", sorted-unique vocab ->
    contiguous ids; ``:131-144``), 2 continuous (empty/outlier -> median,
    min-max normalise; ``:110-128``), publication year -> decade bucket
    (``:74-107`` — note the reference's inclusive ``is_between`` chains put
    exact decade boundaries (e.g. 1910) in the EARLIER decade; preserved).
  * split: per user, first ceil(0.8*n) sorted items -> train, rest -> eval
    (``:212-237``).
  * output: 8 parquet shards per split, train rows shuffled with seed 42
    (``:240-270``), plus ``size_map.json`` (``:273-275``) — the
    preprocessing -> training contract.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pandas as pd

from tdfo_tpu.data.shards import shard_ranges, write_df_part

__all__ = ["run_ctr_preprocessing", "FINAL_COLUMNS"]

SPLIT_RATIO = 0.8
FILE_NUM = 8
MAX_CONTINUOUS = 2000.0  # reference outlier bound for avg_rating / num_pages

FINAL_COLUMNS = [
    "user_id", "item_id", "language", "is_ebook", "format", "publisher",
    "pub_decade", "avg_rating", "num_pages", "is_read", "is_reviewed", "label",
]

CATEGORY_COLS = ["language", "is_ebook", "format", "publisher", "pub_decade"]
CONTINUOUS_COLS = ["avg_rating", "num_pages"]


def read_interactions(data_dir: Path) -> pd.DataFrame:
    """Users with 10..250 interactions; label = rating>=4; items sorted per user."""
    df = pd.read_csv(
        data_dir / "goodreads_interactions.csv",
        dtype={"user_id": np.int32, "book_id": np.int32, "is_read": np.int8,
               "rating": np.int8, "is_reviewed": np.int8},
    )
    counts = df.groupby("user_id")["book_id"].transform("size")
    df = df[(counts >= 10) & (counts <= 250)]
    df = df.assign(label=(df["rating"] >= 4).astype(np.int8)).drop(columns=["rating"])
    return df.sort_values(["user_id", "book_id"], kind="stable").reset_index(drop=True)


def year_to_decade(years: pd.Series) -> pd.Series:
    """Publication year string -> decade label.

    Inclusive-boundary semantics preserved from the reference's chained
    ``is_between``: a year landing exactly on a boundary (1910, 1920, ...)
    belongs to the earlier decade; range covered is [1900, 2030]."""
    y = pd.to_numeric(years, errors="coerce")
    decade_start = np.where(y <= 1900, -1, ((y - 1) // 10 * 10))
    decade_start = np.where(y == 1900, 1900, decade_start)
    valid = (y >= 1900) & (y <= 2030) & ~np.isnan(y)
    labels = np.where(valid, np.char.add(
        np.nan_to_num(decade_start, nan=0).astype(np.int64).astype(str), "s"
    ), "unknown")
    return pd.Series(labels, index=years.index, dtype=object)


def build_vocab(col: pd.Series) -> dict[str, int]:
    """Empty -> "unknown"; sorted unique values -> contiguous ids from 0."""
    vals = col.astype(object).fillna("").replace("", "unknown")
    uniq = sorted(set(map(str, vals)))
    return {v: i for i, v in enumerate(uniq)}


def encode_categorical(col: pd.Series, vocab: dict[str, int]) -> np.ndarray:
    vals = col.astype(object).fillna("").replace("", "unknown").astype(str)
    return vals.map(vocab).to_numpy(dtype=np.int32)


def normalize_continuous(col: pd.Series) -> np.ndarray:
    """Empty -> median, > MAX_CONTINUOUS -> median, then min-max."""
    x = pd.to_numeric(col.astype(object).replace("", np.nan), errors="coerce")
    valid = x[(~x.isna()) & (x <= MAX_CONTINUOUS)]
    lo, hi = float(valid.min()), float(valid.max())
    med = round(float(valid.median()), 4)
    x = x.fillna(med)
    x = x.where(x <= MAX_CONTINUOUS, med)
    return ((x - lo) / (hi - lo)).to_numpy(dtype=np.float32)


def get_book_features(data_dir: Path) -> tuple[pd.DataFrame, dict[str, int]]:
    """Book feature table keyed by contiguous book_id, plus the size_map."""
    size_map: dict[str, int] = {}
    user_map = pd.read_csv(data_dir / "user_id_map.csv")
    size_map["user"] = int(len(user_map))
    book_map = pd.read_csv(data_dir / "book_id_map.csv")
    book_map.columns = ["book_id", "book_original_id"]
    book_map["book_original_id"] = book_map["book_original_id"].astype(str)
    size_map["item"] = int(len(book_map))

    # STREAM the ndjson in bounded chunks, keeping only the feature columns
    # (the reference streams this file too: polars collect(streaming=True),
    # jax-flax/preprocessing.py:53 — a full read of the 2 GB books dump
    # would spike peak RSS by the whole raw payload)
    keep = ["book_id", "language_code", "is_ebook", "average_rating",
            "format", "publisher", "num_pages", "publication_year"]
    chunks = []
    with pd.read_json(data_dir / "goodreads_books.json", lines=True,
                      dtype=False, chunksize=100_000) as reader:
        for chunk in reader:
            chunks.append(chunk[[c for c in keep if c in chunk.columns]])
    books = pd.concat(chunks, ignore_index=True)
    books = books.rename(columns={
        "book_id": "book_original_id", "language_code": "language",
        "average_rating": "avg_rating", "publication_year": "pub_year",
    })
    books["book_original_id"] = books["book_original_id"].astype(str)
    books["pub_decade"] = year_to_decade(books["pub_year"])

    out = pd.DataFrame({"book_original_id": books["book_original_id"]})
    for col in CATEGORY_COLS:
        vocab = build_vocab(books[col])
        out[col] = encode_categorical(books[col], vocab)
        size_map[col] = len(vocab)
    for col in CONTINUOUS_COLS:
        out[col] = normalize_continuous(books[col])

    feats = book_map.merge(out, on="book_original_id", how="left").drop(
        columns=["book_original_id"]
    )
    assert not feats.isna().any().any(), "book feature join left nulls"
    return feats, size_map


def split_interactions(df: pd.DataFrame, is_train: bool) -> pd.DataFrame:
    """Per user: first ceil(0.8*n) sorted items train, the rest eval."""
    rank = df.groupby("user_id").cumcount()
    n = df.groupby("user_id")["book_id"].transform("size")
    cut = np.ceil(n * SPLIT_RATIO).astype(np.int64)
    keep = rank < cut if is_train else rank >= cut
    return df.loc[keep, ["user_id", "book_id"]]


def _join_split_part(part: pd.DataFrame, split_key: pd.MultiIndex,
                     book_features: pd.DataFrame) -> pd.DataFrame:
    """Restrict interaction rows to the split's (user, item) pairs and join
    book features into the final training schema (shared by both formats)."""
    mask = pd.MultiIndex.from_frame(part[["user_id", "book_id"]]).isin(split_key)
    part = part[mask]
    return part.merge(book_features, on="book_id", how="left").rename(
        columns={"book_id": "item_id"}
    )[FINAL_COLUMNS]


def write_parquet_shards(
    data_dir: Path,
    split_pairs: pd.DataFrame,
    interactions: pd.DataFrame,
    book_features: pd.DataFrame,
    prefix: str,
    *,
    file_num: int = FILE_NUM,
    seed: int = 42,
) -> list[Path]:
    """FILE_NUM shards: slice the interaction table, restrict to the split's
    (user, item) pairs, join book features; train rows shuffled."""
    write_dir = data_dir / "parquet"
    write_dir.mkdir(exist_ok=True)
    key = pd.MultiIndex.from_frame(split_pairs)
    paths = []
    for i, start, end in shard_ranges(len(interactions), file_num):
        part = _join_split_part(interactions.iloc[start:end], key, book_features)
        paths.append(write_df_part(part, write_dir, prefix, i,
                                   shuffle=prefix == "train", seed=seed))
    return paths


def run_ctr_preprocessing(data_dir: str | Path, *, file_num: int = FILE_NUM,
                          seed: int = 42,
                          write_format: str = "parquet",
                          hot_vocab: int = 0,
                          hot_fraction: float = 0.9) -> dict[str, int]:
    """Full ETL: raw goodreads files -> parquet or tfrecord shards +
    size_map.json (``write_format`` dispatch parity,
    ``tensorflow2/data.py:70-105``).

    ``hot_vocab > 0`` also emits the hot/cold remap artifact
    (``tdfo_tpu/data/hot_ids.py``) for the two power-law tables — user and
    item — from TRAIN-split interaction frequencies.  Unlike the Criteo
    ETL, these vocabs are sorted-unique (NOT frequency-ranked), so the hot
    sets are genuine scattered subsets exercising the searchsorted remap
    path.  The small book-categorical tables are left unsplit (each is
    either fully hot or too small to matter)."""
    data_dir = Path(data_dir)
    book_features, size_map = get_book_features(data_dir)
    with open(data_dir / "size_map.json", "w") as f:
        json.dump(size_map, f, indent=4)

    interactions = read_interactions(data_dir)
    # ids index Embed tables sized by the id maps; an out-of-range id would
    # silently gather NaN (jnp.take fill mode) at train time — fail here.
    if interactions["user_id"].max() >= size_map["user"] or interactions["user_id"].min() < 0:
        raise ValueError("interaction user_id outside [0, n_users) of user_id_map")
    if interactions["book_id"].max() >= size_map["item"] or interactions["book_id"].min() < 0:
        raise ValueError("interaction book_id outside [0, n_items) of book_id_map")
    # per-table id lookup counts from TRAIN-split interaction frequencies;
    # the small book-categorical tables get theirs by pushing per-item
    # traffic through each book's encoded feature value
    train_pairs = split_interactions(interactions, True)
    stats_counts: dict[str, np.ndarray] = {}
    for col, vocab_key in (("user_id", "user"), ("item_id", "item")):
        src = "user_id" if col == "user_id" else "book_id"
        id_counts = np.zeros(size_map[vocab_key], np.int64)
        vc = train_pairs[src].value_counts()
        id_counts[vc.index.to_numpy()] = vc.to_numpy()
        stats_counts[col] = id_counts
    feat_by_book = book_features.set_index("book_id")
    item_counts = stats_counts["item_id"]
    touched = np.nonzero(item_counts)[0]
    for col in CATEGORY_COLS:
        vals = feat_by_book[col].reindex(touched).to_numpy(np.int64)
        stats_counts[col] = np.bincount(
            vals, weights=item_counts[touched].astype(np.float64),
            minlength=size_map[col]).astype(np.int64)

    # always emit the planner's traffic-stats artifact (plan/stats.py):
    # the auto-sharding planner prices per-table placements from it
    from tdfo_tpu.plan.stats import write_table_stats

    write_table_stats(data_dir, stats_counts)

    if hot_vocab > 0:
        from tdfo_tpu.data.hot_ids import hot_ids_from_counts, write_hot_ids

        per_table, coverage = {}, {}
        for col in ("user_id", "item_id"):
            id_counts = stats_counts[col]
            per_table[col] = hot_ids_from_counts(
                id_counts, hot_vocab=hot_vocab, hot_fraction=hot_fraction)
            total = max(int(id_counts.sum()), 1)
            coverage[col] = float(id_counts[per_table[col]].sum() / total)
        write_hot_ids(data_dir, per_table, hot_vocab=hot_vocab,
                      hot_fraction=hot_fraction, coverage=coverage)

    for prefix, is_train in (("train", True), ("eval", False)):
        pairs = split_interactions(interactions, is_train)
        if write_format == "parquet":
            write_parquet_shards(
                data_dir, pairs, interactions, book_features, prefix,
                file_num=file_num, seed=seed,
            )
        elif write_format == "tfrecord":
            from tdfo_tpu.data.tfrecord import write_tfrecord_shards

            part = _join_split_part(
                interactions, pd.MultiIndex.from_frame(pairs), book_features
            )
            if prefix == "train":
                part = part.sample(frac=1.0, random_state=seed)
            write_tfrecord_shards(
                {c: part[c].to_numpy() for c in part.columns},
                data_dir / "tfrecord", prefix, file_num=file_num,
            )
        else:
            raise ValueError(f"unknown write_format {write_format!r}")
    return size_map
