"""Jagged (ragged) tensors — the framework's KeyedJaggedTensor equivalent.

TPU-native re-design of torchrec's ``KeyedJaggedTensor``
(``torchrec/train.py:33-41`` builds one per batch;
``torchrec/models.py:163-178,208-212`` consumes it).  Differences forced by
XLA:

  * **Static shapes.** XLA traces once; `values` therefore has a fixed
    capacity ``N = sum(lengths)`` padded up to a static bound.  A boolean
    validity is derivable from ``lengths``; trailing slots hold ``pad_id``.
  * **Offsets are derived, not stored** — ``offsets = cumsum(lengths)`` is
    free under XLA fusion, so the canonical representation is
    ``(values[N], lengths[B])``.
  * ``jagged_to_dense`` / ``dense_to_jagged`` (fbgemm kernel parity,
    ``torchrec/models.py:168-172``) are expressed as single fused gathers
    with static ``max_len`` so they tile onto the VPU and fuse into
    neighbouring ops — deliberately NOT Pallas kernels: XLA already lowers
    a one-gather formulation well, and row gathers are fast on v5e.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "JaggedTensor",
    "KeyedJagged",
    "jagged_to_dense",
    "jagged_to_dense_per_host",
    "dense_to_jagged",
    "lengths_to_offsets",
    "pack_rows",
]


def lengths_to_offsets(lengths: jax.Array) -> jax.Array:
    """[B] lengths -> [B+1] exclusive offsets."""
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths, dtype=jnp.int32)]
    )


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class JaggedTensor:
    """One ragged feature: ``B`` rows flattened into ``values`` with per-row
    ``lengths``.  ``values.shape[0]`` is the static capacity; entries at or
    beyond ``offsets[i] + lengths[i]`` are padding."""

    values: jax.Array  # [N] int32 (ids) or [N, D] float
    lengths: jax.Array  # [B] int32

    @property
    def offsets(self) -> jax.Array:
        """Exclusive offsets, shape [B+1]."""
        return lengths_to_offsets(self.lengths)

    @property
    def batch_size(self) -> int:
        return self.lengths.shape[0]

    def to_dense(self, max_len: int, pad_value=0) -> jax.Array:
        return jagged_to_dense(self.values, self.lengths, max_len, pad_value)

    @classmethod
    def from_dense(cls, dense: jax.Array, lengths: jax.Array) -> "JaggedTensor":
        """Inverse of :meth:`to_dense` with capacity ``B * max_len``."""
        values = dense_to_jagged(dense, lengths)
        return cls(values=values, lengths=lengths)

    @classmethod
    def from_lists(cls, rows: list[np.ndarray | list], capacity: int | None = None,
                   dtype=np.int32) -> "JaggedTensor":
        """Host-side constructor (KJT.from_lengths_sync parity,
        ``torchrec/train.py:33-41``)."""
        lengths = np.asarray([len(r) for r in rows], dtype=np.int32)
        flat = np.concatenate([np.asarray(r, dtype=dtype) for r in rows]) if rows else np.zeros((0,), dtype)
        n = int(lengths.sum())
        capacity = capacity or n
        if capacity < n:
            raise ValueError(f"capacity {capacity} < total length {n}")
        values = np.zeros((capacity,), dtype=dtype)
        values[:n] = flat
        return cls(values=jnp.asarray(values), lengths=jnp.asarray(lengths))


# A keyed collection of jagged features (KJT parity) is a plain dict — idiomatic
# pytree; no bespoke container needed under jax transforms.
KeyedJagged = Mapping[str, JaggedTensor]


def jagged_to_dense(values: jax.Array, lengths: jax.Array, max_len: int, pad_value=0) -> jax.Array:
    """``[N] -> [B, max_len]`` (or ``[N, D] -> [B, max_len, D]``).

    fbgemm ``jagged_2d_to_dense`` parity (``torchrec/models.py:168-172``),
    expressed as one vectorised gather with a validity mask — fuses into
    neighbouring ops under XLA instead of launching a custom CUDA kernel.
    Rows longer than ``max_len`` are truncated (keeping the head, matching
    fbgemm).
    """
    offsets = lengths_to_offsets(lengths)
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]  # [1, T]
    gather_idx = offsets[:-1, None] + pos  # [B, T]
    valid = pos < lengths[:, None]  # [B, T]
    gather_idx = jnp.where(valid, gather_idx, 0)
    dense = jnp.take(values, gather_idx, axis=0)  # [B, T, ...]
    mask = valid if dense.ndim == 2 else valid[..., None]
    return jnp.where(mask, dense, jnp.asarray(pad_value, dense.dtype))


def pack_rows(rows, capacity: int, dtype=np.int32) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: a sequence of variable-length rows -> (values[capacity],
    lengths[B]) numpy arrays, zero-padded tail.  The loader's ragged object
    columns feed straight in; the device side reads them back with
    :func:`jagged_to_dense` inside the jitted step."""
    lengths = np.fromiter((len(r) for r in rows), np.int32, len(rows))
    n = int(lengths.sum())
    if capacity < n:
        raise ValueError(f"capacity {capacity} < total jagged length {n}")
    values = np.zeros((capacity,), dtype)
    if n:
        values[:n] = np.concatenate([np.asarray(r, dtype) for r in rows])
    return values, lengths


def jagged_to_dense_per_host(values: jax.Array, lengths: jax.Array,
                             max_len: int, pad_value=0,
                             n_hosts: int = 1) -> jax.Array:
    """:func:`jagged_to_dense` for values packed PER HOST.

    On a multi-host mesh each process packs only its local rows into its own
    ``capacity/n_hosts`` slice of the global values array (the slices line up
    with the batch-axis sharding), so offsets restart at every host boundary
    instead of running globally.  ``n_hosts=1`` is exactly
    :func:`jagged_to_dense`.
    """
    if n_hosts <= 1:
        return jagged_to_dense(values, lengths, max_len, pad_value)
    b = lengths.shape[0]
    if b % n_hosts or values.shape[0] % n_hosts:
        raise ValueError(
            f"jagged_to_dense_per_host: batch ({b}) and values capacity "
            f"({values.shape[0]}) must both divide by n_hosts ({n_hosts}); "
            "uneven splits would mis-segment host boundaries"
        )
    rows_per_host = b // n_hosts
    cap_per_host = values.shape[0] // n_hosts
    off = jnp.cumsum(lengths, dtype=jnp.int32) - lengths  # global exclusive
    host = jnp.arange(b, dtype=jnp.int32) // rows_per_host
    host_start = jnp.take(off, host * rows_per_host)  # offset at host's row 0
    local_off = off - host_start
    base = host * cap_per_host + local_off  # [B] start of each row's values
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    gather_idx = base[:, None] + pos
    valid = pos < lengths[:, None]
    dense = jnp.take(values, jnp.where(valid, gather_idx, 0), axis=0)
    mask = valid if dense.ndim == 2 else valid[..., None]
    return jnp.where(mask, dense, jnp.asarray(pad_value, dense.dtype))


def dense_to_jagged(dense: jax.Array, lengths: jax.Array) -> jax.Array:
    """``[B, T] -> [N=B*T]`` packed values (fbgemm ``dense_to_jagged`` parity).

    Static capacity B*T; valid entries are left-compacted via an argsort on
    validity (stable sort keeps row-major order), so ``values[:sum(lengths)]``
    is the packed jagged payload.
    """
    b, t = dense.shape[0], dense.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    valid = (pos < lengths[:, None]).reshape(-1)  # [B*T]
    flat = dense.reshape((b * t,) + dense.shape[2:])
    # stable sort: valid entries (key 0) first, in original order
    order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
    packed = jnp.take(flat, order, axis=0)
    # invariant: slots past sum(lengths) hold 0, not leftover dense padding
    tail_valid = jnp.take(valid, order)
    mask = tail_valid if packed.ndim == 1 else tail_valid[:, None]
    return jnp.where(mask, packed, 0)
