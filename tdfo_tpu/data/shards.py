"""Shared parquet shard-writing primitives for both ETLs.

One policy for shard slicing, train-shuffle, and file naming
(``{prefix}_part_{i}.parquet``, 1-indexed — the contract the loaders and the
reference's readers share: ``jax-flax/preprocessing.py:240-270``,
``torchrec/preprocessing.py:318-334``).
"""

from __future__ import annotations

import math
from pathlib import Path

import pandas as pd

__all__ = ["shard_ranges", "write_df_part"]


def shard_ranges(n_rows: int, file_num: int):
    """Yield (part_index_1based, start, end) row ranges."""
    file_unit = math.ceil(max(n_rows, 1) / file_num)
    for i, offset in enumerate(range(0, n_rows, file_unit), start=1):
        yield i, offset, min(offset + file_unit, n_rows)


def write_df_part(
    part: pd.DataFrame,
    write_dir: Path,
    prefix: str,
    index: int,
    *,
    shuffle: bool,
    seed: int,
) -> Path:
    """Write one shard; train shards are row-shuffled with the fixed seed."""
    if shuffle:
        part = part.sample(frac=1.0, random_state=seed)
    path = write_dir / f"{prefix}_part_{index}.parquet"
    part.to_parquet(path, index=False)
    return path
