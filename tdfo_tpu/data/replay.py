"""Crash-safe request-log replay: the serve -> retrain stream adapter.

The serving frontend records every scored request as JSONL (torchrec's
streaming-retrain input and Monolith §3.3's online training joiner keep the
same artifact: a log of served traffic that doubles as the incremental
training stream).  This module owns BOTH ends of that file:

  * ``RequestLog`` — the writer.  Appends are segment-rotated at a byte
    threshold; a finished segment is sealed by an atomically-published
    sidecar carrying its byte count and sha256, so a reader can verify a
    sealed segment end-to-end before trusting a single record.  Reopening
    after a crash truncates a torn tail line and resumes the ``seq``
    numbering from the last durable record — the writer never emits two
    records with the same seq and never leaves a half-record in front of a
    new append.

  * ``ReplayConsumer`` — the reader.  Tails the segment chain from a
    byte-offset cursor (persisted as a checkpoint sidecar by the online
    supervisor, the same idiom as PR 1's stream cursors) and forms
    deterministic fixed-size training batches.  Exactly-once delivery is the
    contract: the cursor only commits when a FULL batch assembles
    (all-or-nothing, so a kill mid-assembly re-reads the same rows), ``seq``
    dedup drops writer-retry duplicates, sealed segments are digest-verified
    once, a torn tail in the active segment stops the tail (more data may
    yet arrive) instead of erroring, and complete-but-garbage lines are
    quarantined up to ``max_bad_records`` then fatal — mirroring the shard
    loader's ``max_bad_shards``.

Counter / cursor bookkeeping lives INSIDE the cursor dict so a resumed
process recounts nothing: ``records`` (trained), ``bad`` (quarantined),
``dup`` (deduped), ``skipped`` (non-training or backpressure-dropped) all
travel with the byte position.  ``counters()`` surfaces them — plus the
measured records-behind ``replay/lag`` — through the PR-7 telemetry path.

A serving FLEET writes one log per replica (``<root>/replica-<k>``);
``MergedReplayConsumer`` folds those into one exactly-once stream keyed by
``(replica_id, seq)`` — each replica's writer owns its own seq line, so the
existing per-log dedup applies per replica and the merger round-robins
whole records across replicas deterministically.  Its cursor nests one
plain cursor per replica plus the round-robin position, and commits
all-or-nothing like the single-log one.  ``make_replay_consumer`` picks
the right reader from the directory layout.

Retention: ``gc_consumed_segments`` deletes sealed segments the committed
cursor has fully passed (keeping the newest ``keep``); the guarded
``gc_segments`` refuses to touch any segment the cursor still points into.
After a GC the log only replays from a committed cursor — replay-from-zero
is gone by design.

Reading JSONL line-by-line outside this module is rejected by
``tests/test_quality.py``: ad-hoc tailers would bypass the truncation and
digest checks that make replay exactly-once.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Any, Iterator

import numpy as np

from tdfo_tpu.obs import trace as _trace
from tdfo_tpu.utils import faults as _faults

__all__ = [
    "REPLAY_SCHEMA_VERSION",
    "MergedReplayConsumer",
    "ReplayError",
    "ReplayLagError",
    "RequestLog",
    "ReplayConsumer",
    "make_replay_consumer",
    "replica_log_dir",
]

REPLAY_SCHEMA_VERSION = 1


class ReplayError(RuntimeError):
    """Unrecoverable log damage: digest mismatch, unsealed non-final
    segment, or the bad-record quarantine budget exhausted."""


class ReplayLagError(ReplayError):
    """The consumer fell further behind than ``max_lag_records`` under the
    fail-hard backpressure policy."""


def _seg_name(i: int) -> str:
    return f"requests-{i:06d}.jsonl"


def _seal_name(i: int) -> str:
    return f"requests-{i:06d}.seal.json"


def _list_segments(root: Path) -> list[int]:
    out = []
    for p in root.glob("requests-*.jsonl"):
        stem = p.name[len("requests-"):-len(".jsonl")]
        if stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def replica_log_dir(root: str | Path, replica_id: int) -> Path:
    """Per-replica request-log directory under a fleet log root — the
    naming contract the fleet writer and the merged reader share."""
    return Path(root) / f"replica-{replica_id}"


def _list_replicas(root: Path) -> list[int]:
    out = []
    for p in root.glob("replica-*"):
        stem = p.name[len("replica-"):]
        if p.is_dir() and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


# --------------------------------------------------------------------- writer


class RequestLog:
    """Append-only, segment-rotated JSONL writer with sealed digests.

    ``segment_bytes = 0`` disables rotation (single growing segment —
    fine for tests, wrong for a long-running frontend).  Rotation order is
    the crash-safety invariant: the seal sidecar is atomically published
    (temp + fsync + rename, via the swap store's sanctioned helper) BEFORE
    the next segment is created, so a reader that finds an unsealed segment
    with a successor knows the chain is damaged rather than racing.
    """

    def __init__(self, root: str | Path, *, segment_bytes: int = 0):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        segs = _list_segments(self.root)
        self._seg = segs[-1] if segs else 0
        self._seq = 0
        if segs and (self.root / _seal_name(self._seg)).exists():
            # crashed between sealing and opening the successor: resume seq
            # from the seal and start the next segment fresh
            seal = json.loads((self.root / _seal_name(self._seg)).read_text())
            self._seq = int(seal.get("last_seq") or 0)
            self._seg += 1
        elif segs:
            self._seq = self._recover_active(self.root / _seg_name(self._seg))
            if self._seg:
                # a fresh (or torn-empty) active segment carries no seqs —
                # continuity lives in the predecessor's seal
                prev = self.root / _seal_name(self._seg - 1)
                if prev.exists():
                    seal = json.loads(prev.read_text())
                    self._seq = max(self._seq,
                                    int(seal.get("last_seq") or 0))
        self._path = self.root / _seg_name(self._seg)
        self._first_seq = None  # first seq in the ACTIVE segment
        self._records = 0  # lines in the active segment
        self._f = open(self._path, "ab")
        if self._path.stat().st_size:
            first, n = self._scan_segment(self._path)[:2]
            self._first_seq, self._records = first, n

    def _recover_active(self, path: Path) -> int:
        """Truncate a torn tail line (no trailing newline) and return the
        highest seq among the surviving complete records."""
        data = path.read_bytes()
        cut = data.rfind(b"\n") + 1  # 0 when no complete line survives
        if cut != len(data):
            with open(path, "r+b") as f:
                f.truncate(cut)
        last = 0
        for line in data[:cut].split(b"\n"):
            if not line:
                continue
            try:
                rec = json.loads(line)
                last = max(last, int(rec.get("seq") or 0))
            except (ValueError, TypeError):
                continue  # corrupt line: reader quarantines it; seq unknown
        return last

    def _scan_segment(self, path: Path) -> tuple[int | None, int, int]:
        """(first_seq, line_count, last_seq) of a segment's complete lines."""
        first, last, n = None, 0, 0
        for line in path.read_bytes().split(b"\n"):
            if not line:
                continue
            n += 1
            try:
                seq = int(json.loads(line).get("seq") or 0)
            except (ValueError, TypeError):
                continue
            first = seq if first is None else first
            last = max(last, seq)
        return first, n, last

    # ------------------------------------------------------------------ api

    def append(self, record: dict[str, Any]) -> int:
        """Append one record (stamped with ``seq`` + ``schema_version``),
        flush it to the OS, and rotate if the segment crossed the byte
        threshold.  Returns the assigned seq."""
        self._seq += 1
        seq = self._seq
        rec = dict(record)
        rec["seq"] = seq
        rec["schema_version"] = REPLAY_SCHEMA_VERSION
        line = (json.dumps(rec) + "\n").encode()
        inj = _faults.active()
        if inj is not None and inj.corrupt_record_due():
            # complete-but-garbage line: '{' -> '#' can never parse as JSON,
            # driving the reader's quarantine on a REAL sealed bad line
            line = b"#" + line[1:]
        self._f.write(line)
        if inj is not None and inj.dup_record_due():
            self._f.write(line)  # same seq twice: the at-least-once artifact
            self._records += 1
        self._f.flush()
        if self._first_seq is None:
            self._first_seq = seq
        self._records += 1
        if inj is not None:
            size = self._f.tell()
            if inj.truncate_log_due(size):
                # torn tail mid-record, as a crashed writer leaves it; a
                # reopened RequestLog truncates it, the reader stops before it
                self._f.truncate(inj.spec.truncate_log_at_byte)
                self._f.seek(inj.spec.truncate_log_at_byte)
        if self.segment_bytes and self._f.tell() >= self.segment_bytes:
            self._rotate()
        return seq

    def _rotate(self) -> None:
        """Seal the active segment (fsync data, publish the digest sidecar)
        THEN open the successor — a reader can always tell 'rotation in
        flight' from 'chain damaged'."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self.seal_segment(self._seg)
        self._seg += 1
        self._path = self.root / _seg_name(self._seg)
        self._f = open(self._path, "ab")
        self._first_seq = None
        self._records = 0

    def seal_segment(self, seg: int) -> None:
        """Publish the digest sidecar for a finished segment."""
        from tdfo_tpu.serve.swap import atomic_write_json

        path = self.root / _seg_name(seg)
        data = path.read_bytes()
        first, n, last = self._scan_segment(path)
        atomic_write_json(self.root / _seal_name(seg), {
            "segment": seg,
            "schema_version": REPLAY_SCHEMA_VERSION,
            "bytes": len(data),
            "records": n,
            "first_seq": first,
            "last_seq": last,
            "sha256": hashlib.sha256(data).hexdigest(),
        })

    def seal_active(self) -> None:
        """Force-seal the active segment (end-of-stream marker for tests and
        drained frontends) and open a fresh successor on the next append."""
        if self._f.closed:
            return
        if self._path.stat().st_size == 0:
            return  # nothing to seal; an empty sealed segment is noise
        self._rotate()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    @property
    def active_segment(self) -> int:
        return self._seg

    @property
    def last_seq(self) -> int:
        return self._seq


# --------------------------------------------------------------------- reader


def _fresh_cursor() -> dict[str, int]:
    return {"segment": 0, "offset": 0, "row": 0,
            "records": 0, "bad": 0, "dup": 0, "skipped": 0, "last_seq": 0}

_CURSOR_KEYS = frozenset(_fresh_cursor())


class ReplayConsumer:
    """Exactly-once batch former over a ``RequestLog`` directory.

    ``schema`` is the trainer's ``_eval_schema`` dict (``{column: (dtype,
    shape)}``); only records whose feature payload validates against it
    train.  ``cursor`` resumes from a previously committed position (the
    checkpoint sidecar); omit it to start at segment 0, byte 0.
    """

    def __init__(self, root: str | Path, *, schema: dict[str, tuple],
                 batch_size: int, max_bad_records: int = 0,
                 max_lag_records: int = 0, lag_policy: str = "fail",
                 cursor: dict[str, int] | None = None):
        if lag_policy not in ("fail", "skip"):
            raise ValueError(f"lag_policy must be 'fail' or 'skip', "
                             f"got {lag_policy!r}")
        for col, (_, shape) in schema.items():
            shape = tuple(shape)
            # scalar-per-row (CTR) or fixed-width vector-per-row (seq eval
            # windows / candidate panels) — anything ragged or higher-rank
            # cannot form deterministic fixed-size batches
            if len(shape) > 1 or (shape and int(shape[0]) <= 0):
                raise ValueError(
                    f"replay schema column {col!r} must be scalar or a "
                    f"fixed-width 1-D vector per row, got shape {shape} — "
                    "ragged payloads cannot batch deterministically")
        self.root = Path(root)
        self.schema = dict(schema)
        self.batch_size = int(batch_size)
        self.max_bad_records = int(max_bad_records)
        self.max_lag_records = int(max_lag_records)
        self.lag_policy = lag_policy
        cur = _fresh_cursor()
        if cursor is not None:
            unknown = set(cursor) - _CURSOR_KEYS
            if unknown:
                raise ValueError(f"unknown replay cursor keys: {sorted(unknown)}")
            cur.update({k: int(v) for k, v in cursor.items()})
        self._cursor = cur
        self._verified: set[int] = set()
        self._peeking = False  # suppress trace spans for uncommitted reads

    # ------------------------------------------------------------- segments

    def _seal(self, seg: int) -> dict | None:
        p = self.root / _seal_name(seg)
        if not p.exists():
            return None
        return json.loads(p.read_text())

    def _verify(self, seg: int, seal: dict) -> None:
        if seg in self._verified:
            return
        data = (self.root / _seg_name(seg)).read_bytes()
        if len(data) != seal["bytes"]:
            raise ReplayError(
                f"sealed segment {_seg_name(seg)} is {len(data)} bytes, seal "
                f"says {seal['bytes']} — truncated after sealing")
        digest = hashlib.sha256(data).hexdigest()
        if digest != seal["sha256"]:
            raise ReplayError(
                f"sealed segment {_seg_name(seg)} digest mismatch "
                f"({digest[:12]} != {seal['sha256'][:12]}) — refusing to "
                f"replay silently corrupted traffic")
        self._verified.add(seg)

    def _segment_bytes(self, seg: int) -> bytes | None:
        """Readable bytes of a segment: the verified whole file when sealed,
        everything up to the last complete line when active, ``None`` when
        the segment does not exist yet."""
        path = self.root / _seg_name(seg)
        if not path.exists():
            return None
        seal = self._seal(seg)
        if seal is not None:
            self._verify(seg, seal)
            data = path.read_bytes()
            if data and not data.endswith(b"\n"):
                raise ReplayError(
                    f"sealed segment {_seg_name(seg)} ends mid-record — the "
                    f"writer seals only complete lines; refusing torn data")
            return data
        if (self.root / _seg_name(seg + 1)).exists():
            raise ReplayError(
                f"segment {_seg_name(seg)} has a successor but no seal — "
                f"the rotation order guarantees seals land first; this "
                f"chain is damaged")
        data = path.read_bytes()
        cut = data.rfind(b"\n") + 1  # torn tail: wait, don't error
        return data[:cut]

    def _lines(self, cur: dict[str, int]) -> Iterator[tuple[bytes, int, int]]:
        """Yield ``(line, segment, next_offset)`` for every complete line at
        or after the cursor, crossing sealed segment boundaries."""
        seg, offset = cur["segment"], cur["offset"]
        while True:
            data = self._segment_bytes(seg)
            if data is None:
                return
            while offset < len(data):
                end = data.index(b"\n", offset) + 1
                yield data[offset:end - 1], seg, end
                offset = end
            if self._seal(seg) is None:
                return  # active segment exhausted: no more durable data yet
            seg, offset = seg + 1, 0

    # -------------------------------------------------------------- records

    def _classify(self, line: bytes, cur: dict[str, int]):
        """Parse + validate one complete line against the cursor's dedup
        state.  Returns ``("train", record, columns)`` /
        ``("skip"|"dup"|"bad", reason, None)`` and updates the dedup seq."""
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except (ValueError, TypeError) as e:
            return "bad", f"unparseable line: {e}", None
        seq = rec.get("seq")
        if not isinstance(seq, int) or seq <= 0:
            return "bad", "missing/invalid seq", None
        if seq <= cur["last_seq"]:
            return "dup", f"seq {seq} already consumed", None
        cur["last_seq"] = seq
        if rec.get("schema_version") != REPLAY_SCHEMA_VERSION:
            return "bad", f"schema_version {rec.get('schema_version')!r}", None
        if (rec.get("event") != "serve_request" or rec.get("outcome") != "ok"
                or "features" not in rec):
            return "skip", "not a trainable serve_request", None
        feats = rec["features"]
        if not isinstance(feats, dict):
            return "bad", "features is not an object", None
        rows = rec.get("rows")
        if not isinstance(rows, int) or rows <= 0:
            return "bad", "missing/invalid rows", None
        cols = {}
        for col, (dtype, shape) in self.schema.items():
            vals = feats.get(col)
            if not isinstance(vals, list) or len(vals) != rows:
                return "bad", f"feature {col!r} missing or wrong length", None
            try:
                arr = np.asarray(vals, dtype=dtype)
            except (ValueError, TypeError, OverflowError):
                return "bad", f"feature {col!r} not castable to {dtype}", None
            # enforce the per-row shape exactly (seq panels: [rows, width]);
            # a drifted width would desync multihost lockstep downstream
            if arr.shape != (rows, *tuple(shape)):
                return ("bad", f"feature {col!r} has shape {arr.shape}, "
                        f"schema says {(rows, *tuple(shape))}", None)
            cols[col] = arr
        return "train", rec, cols

    # ------------------------------------------------------------------ api

    def cursor(self) -> dict[str, int]:
        """The committed cursor (a copy — safe to persist as-is)."""
        return dict(self._cursor)

    def lag(self) -> int:
        """Complete records durable in the log but not yet consumed — the
        records-behind backpressure metric (``replay/lag``)."""
        n = 0
        seg, offset = self._cursor["segment"], self._cursor["offset"]
        while True:
            data = self._segment_bytes(seg)
            if data is None:
                return n
            n += data.count(b"\n", offset)
            if self._seal(seg) is None:
                return n
            seg, offset = seg + 1, 0

    def counters(self) -> dict[str, float]:
        """Replay counters for the telemetry JSONL (PR-7 naming)."""
        c = self._cursor
        return {
            "replay/records": float(c["records"]),
            "replay/bad": float(c["bad"]),
            "replay/dup": float(c["dup"]),
            "replay/skipped": float(c["skipped"]),
            "replay/lag": float(self.lag()),
        }

    def check_backpressure(self) -> int:
        """Enforce the bounded-lag policy.  Returns the measured lag.
        ``fail``: raise ``ReplayLagError`` beyond ``max_lag_records``.
        ``skip``: drop whole records (counted, dedup-consistent) until at
        most ``max_lag_records`` remain — skip-to-fresh for a consumer that
        prefers recency over completeness."""
        lag = self.lag()
        if not self.max_lag_records or lag <= self.max_lag_records:
            return lag
        if self.lag_policy == "fail":
            raise ReplayLagError(
                f"replay is {lag} records behind (max_lag_records="
                f"{self.max_lag_records}); the frontend outpaces training — "
                f"fail-hard policy refuses to silently train on stale data")
        cur = dict(self._cursor)
        to_drop = lag - self.max_lag_records
        for line, seg, next_offset in self._lines(cur):
            if to_drop <= 0:
                break
            try:
                rec = json.loads(line)
                seq = rec.get("seq")
                if isinstance(seq, int) and seq > cur["last_seq"]:
                    cur["last_seq"] = seq
            except (ValueError, TypeError):
                pass  # unparseable skipped line: nothing to dedup against
            cur["segment"], cur["offset"], cur["row"] = seg, next_offset, 0
            cur["skipped"] += 1
            to_drop -= 1
        self._cursor = cur
        return self.lag()

    def _take(self, cur: dict[str, int], taken: dict[str, list],
              consumed: list[tuple[int, int, int]], need: int, *,
              max_records: int | None = None) -> int:
        """Advance the WORKING cursor ``cur`` over the log, appending up to
        ``need`` rows of trainable columns into ``taken`` and their
        ``(seq, row_start, row_end)`` spans into ``consumed``.  Stops after
        ``max_records`` whole train records (the merged consumer's record-
        level round-robin grain), when ``need`` is filled, or when durable
        data runs out.  Returns the rows taken; commits nothing."""
        got, records = 0, 0
        for line, seg, next_offset in self._lines(cur):
            prev_seq = cur["last_seq"]  # restored on a mid-record boundary
            kind, info, cols = self._classify(line, cur)
            if kind == "bad":
                cur["bad"] += 1
                if cur["bad"] > self.max_bad_records:
                    raise ReplayError(
                        f"bad request-log record #{cur['bad']} exceeds "
                        f"max_bad_records={self.max_bad_records} "
                        f"(segment {seg}): {info}")
                cur["segment"], cur["offset"], cur["row"] = seg, next_offset, 0
                continue
            if kind in ("dup", "skip"):
                cur["dup" if kind == "dup" else "skipped"] += 1
                cur["segment"], cur["offset"], cur["row"] = seg, next_offset, 0
                continue
            rec, start = info, cur["row"]
            rows = rec["rows"]
            if start >= rows:  # cursor damage: row offset beyond the record
                raise ReplayError(
                    f"cursor row {start} >= record rows {rows} at seq "
                    f"{rec['seq']} — cursor does not match this log")
            stop = min(rows, start + need - got)
            for col, arr in cols.items():
                taken[col].append(arr[start:stop])
            consumed.append((rec["seq"], start, stop))
            got += stop - start
            if stop == rows:
                cur["records"] += 1
                cur["segment"], cur["offset"], cur["row"] = seg, next_offset, 0
                records += 1
            else:
                # mid-record batch boundary: stay ON this line, resume at row
                # `stop`; un-bump the dedup seq so the re-read is not a dup
                cur["row"] = stop
                cur["last_seq"] = prev_seq
            if got >= need:
                break
            if max_records is not None and records >= max_records:
                break
        return got

    def next_batch(self):
        """Assemble one deterministic batch of exactly ``batch_size`` rows.

        Returns ``(batch, consumed)`` — ``batch`` maps schema columns to
        ``[batch_size]`` arrays; ``consumed`` lists ``(seq, row_start,
        row_end)`` spans for record-id accounting — or ``None`` when fewer
        than ``batch_size`` rows are durably available (partial progress is
        discarded; the cursor only ever commits whole batches)."""
        cur = dict(self._cursor)
        taken: dict[str, list] = {col: [] for col in self.schema}
        consumed: list[tuple[int, int, int]] = []
        got = self._take(cur, taken, consumed, self.batch_size)
        if got < self.batch_size:
            return None  # not enough durable rows: all-or-nothing, no commit
        batch = {col: np.concatenate(parts) for col, parts in taken.items()}
        self._cursor = cur
        # span AFTER the in-memory commit: what the trace claims consumed
        # is exactly what the cursor advanced over ((seq, lo, hi) spans —
        # obs/aggregate.py normalises them to (replica=0, seq) join keys).
        # Peeks (shadow eval) never emit — they commit nothing.
        if not self._peeking:
            _trace.emit("replay", "replay_batch", rows=self.batch_size,
                        consumed=[list(c) for c in consumed],
                        records=cur["records"])
        inj = _faults.active()
        if inj is not None:
            inj.maybe_kill_replay(cur["records"])
        return batch, consumed

    def peek_batches(self, n: int) -> list[dict[str, np.ndarray]]:
        """Read up to ``n`` batches PAST the committed position without
        moving the cursor — the gated supervisor's shadow-eval slice:
        traffic the cycle's candidate has NOT trained on (it trains in a
        later cycle — progressive validation), so gate scores are always
        held-out.  Returns fewer than ``n`` batches when the log drains."""
        saved = dict(self._cursor)
        out = []
        self._peeking = True
        try:
            for _ in range(int(n)):
                got = self.next_batch()
                if got is None:
                    break
                out.append(got[0])
        finally:
            self._peeking = False
            self._cursor = saved
        return out

    # -------------------------------------------------------------- retention

    def gc_segments(self, upto: int) -> list[int]:
        """Delete sealed segments ``0..upto`` (data + seal sidecar).
        REFUSES — ``ValueError``, nothing deleted — when the committed
        cursor still points into any candidate segment, or when a
        candidate below the cursor is unsealed (chain damage a GC must not
        paper over).  Returns the deleted segment indices."""
        upto = int(upto)
        if upto < 0:
            return []
        if upto >= self._cursor["segment"]:
            raise ValueError(
                f"refusing to GC segment {upto}: the committed replay "
                f"cursor still points into segment "
                f"{self._cursor['segment']} — only segments the cursor has "
                "fully passed may be deleted")
        doomed = [i for i in _list_segments(self.root) if i <= upto]
        for i in doomed:
            if self._seal(i) is None:
                raise ValueError(
                    f"refusing to GC segment {i}: no seal sidecar below the "
                    "committed cursor — the rotation order guarantees seals "
                    "land first, so this chain is damaged, not consumable")
        removed = []
        for i in doomed:
            (self.root / _seg_name(i)).unlink()
            (self.root / _seal_name(i)).unlink()
            self._verified.discard(i)
            removed.append(i)
        return removed

    def gc_consumed_segments(self, keep: int = 0) -> list[int]:
        """Retention sweep ([online] keep_consumed_segments): delete fully-
        consumed sealed segments, keeping the newest ``keep`` of them
        behind the committed cursor.  Returns the deleted indices."""
        upto = self._cursor["segment"] - 1 - max(0, int(keep))
        if upto < 0:
            return []
        return self.gc_segments(upto)


class MergedReplayConsumer:
    """Exactly-once batch former over a FLEET of per-replica request logs.

    A multi-replica serving fleet (``serve/fleet.py``) writes one
    ``RequestLog`` per replica under ``<root>/replica-<k>``; this consumer
    folds them into a single deterministic stream.  Identity is
    ``(replica_id, seq)`` — each sub-log keeps its own dedup ``last_seq``,
    so a seq collision ACROSS replicas is two distinct records, while a
    crash-redo WITHIN one replica's log still dedups.  Interleave order is
    record-level round-robin over replica ids ascending, starting from the
    persisted ``rr`` index; a replica with no durable record simply yields
    its turn.  The merged cursor ``{"rr": int, "replicas": {str(id):
    sub_cursor}}`` commits all-or-nothing alongside the cycle checkpoint,
    same single-durability-point discipline as the flat consumer.
    """

    def __init__(self, root: str | Path, *, schema: dict[str, tuple],
                 batch_size: int, max_bad_records: int = 0,
                 max_lag_records: int = 0, lag_policy: str = "fail",
                 cursor: dict | None = None):
        self.root = Path(root)
        self.batch_size = int(batch_size)
        ids = _list_replicas(self.root)
        if not ids:
            raise ValueError(
                f"no replica-<k> request-log directories under {self.root} — "
                f"a merged replay consumer needs a fleet log layout")
        subs: dict | None = None
        self._rr = 0
        if cursor is not None:
            unknown = set(cursor) - {"rr", "replicas"}
            if unknown or "replicas" not in cursor:
                raise ValueError(
                    f"cursor is not a merged replay cursor (keys "
                    f"{sorted(cursor)}) — a fleet log cannot resume from a "
                    f"single-log cursor")
            self._rr = int(cursor.get("rr", 0))
            subs = cursor["replicas"]
            ghost = set(subs) - {str(i) for i in ids}
            if ghost:
                raise ValueError(
                    f"merged replay cursor names replicas {sorted(ghost)} "
                    f"with no log directory under {self.root} — cursor does "
                    f"not match this fleet")
        self._ids = ids
        self._subs = {
            i: ReplayConsumer(
                replica_log_dir(self.root, i), schema=schema,
                batch_size=batch_size, max_bad_records=max_bad_records,
                max_lag_records=max_lag_records, lag_policy=lag_policy,
                cursor=None if subs is None else subs.get(str(i)))
            for i in ids
        }
        self.schema = dict(schema)
        self._peeking = False  # suppress trace spans for uncommitted reads

    def next_batch(self):
        """One deterministic ``batch_size``-row batch round-robined across
        replica logs, or ``None`` when the fleet has too few durable rows.
        ``consumed`` spans are 4-tuples ``(replica_id, seq, row_start,
        row_end)``.  All sub-cursors commit together or not at all."""
        curs = {i: dict(s._cursor) for i, s in self._subs.items()}
        taken: dict[str, list] = {col: [] for col in self.schema}
        consumed: list[tuple[int, int, int, int]] = []
        need = self.batch_size
        got_total = 0
        rr, dry = self._rr, 0
        ids = self._ids
        while got_total < need and dry < len(ids):
            rid = ids[rr % len(ids)]
            sub = self._subs[rid]
            spans: list[tuple[int, int, int]] = []
            got = sub._take(curs[rid], taken, spans, need - got_total,
                            max_records=1)
            consumed.extend((rid, s, a, b) for s, a, b in spans)
            got_total += got
            if got == 0:
                dry += 1
                rr += 1
            else:
                dry = 0
                # a mid-record split keeps the turn so the record finishes
                # contiguously next batch; a whole record passes the turn
                if curs[rid]["row"] == 0:
                    rr += 1
        if got_total < need:
            return None  # all-or-nothing: no sub-cursor moved
        batch = {col: np.concatenate(parts) for col, parts in taken.items()}
        for i, s in self._subs.items():
            s._cursor = curs[i]
        self._rr = rr % len(ids)
        # (replica, seq, lo, hi) spans — the merged half of the causal
        # chain: these ids are the ones served-request spans carry.
        # Peeks (shadow eval) never emit — they commit nothing.
        if not self._peeking:
            _trace.emit("replay", "replay_batch", rows=self.batch_size,
                        consumed=[list(c) for c in consumed])
        inj = _faults.active()
        if inj is not None:
            inj.maybe_kill_replay(
                sum(s._cursor["records"] for s in self._subs.values()))
        return batch, consumed

    def peek_batches(self, n: int) -> list[dict[str, np.ndarray]]:
        """Shadow-eval slice (see ``ReplayConsumer.peek_batches``): up to
        ``n`` batches past the committed position, nothing committed."""
        saved = {i: dict(s._cursor) for i, s in self._subs.items()}
        saved_rr = self._rr
        out = []
        self._peeking = True
        try:
            for _ in range(int(n)):
                got = self.next_batch()
                if got is None:
                    break
                out.append(got[0])
        finally:
            self._peeking = False
            for i, s in self._subs.items():
                s._cursor = saved[i]
            self._rr = saved_rr
        return out

    def cursor(self) -> dict:
        """The committed merged cursor (deep copy — persist as-is)."""
        return {"rr": self._rr,
                "replicas": {str(i): s.cursor()
                             for i, s in self._subs.items()}}

    def lag(self) -> int:
        return sum(s.lag() for s in self._subs.values())

    def counters(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for s in self._subs.values():
            for k, v in s.counters().items():
                out[k] = out.get(k, 0.0) + v
        return out

    def check_backpressure(self) -> int:
        return sum(s.check_backpressure() for s in self._subs.values())

    def gc_consumed_segments(self, keep: int = 0) -> list[tuple[int, int]]:
        """Retention sweep over every replica log.  Returns deleted
        segments as ``(replica_id, segment)`` pairs."""
        out = []
        for i, s in self._subs.items():
            out.extend((i, seg) for seg in s.gc_consumed_segments(keep))
        return out


def make_replay_consumer(root: str | Path, **kw):
    """The one construction point callers should use: a
    ``MergedReplayConsumer`` when ``root`` holds a fleet layout
    (``replica-<k>`` subdirectories), a flat ``ReplayConsumer`` otherwise.
    Keyword arguments pass through unchanged."""
    root = Path(root)
    if _list_replicas(root):
        return MergedReplayConsumer(root, **kw)
    return ReplayConsumer(root, **kw)
