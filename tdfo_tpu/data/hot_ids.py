"""Hot-id remap artifacts for frequency-partitioned (hot/cold) embeddings.

Recsys lookup traffic is power-law: a tiny head of ids absorbs most of the
lookup mass (the observation behind fbgemm's ``MANAGED_CACHING`` placement
and the FAE "hot embeddings fit in fast memory" design).  The preprocessing
passes already count value frequencies, so they can emit, per table, the
smallest frequency-ranked id prefix covering ``hot_fraction`` of the lookup
mass (capped at ``hot_vocab`` ids) as a ``hot_ids.json`` artifact next to
``size_map.json``.  At build time ``ShardedEmbeddingCollection`` splits
every listed table into a small contiguous HOT head (replicated, updated
scatter-free via one-hot MXU contractions) and the residual COLD table
(row-sharded, updated via the existing dedupe + row-scatter path) — see
``parallel/embedding.py``.

The artifact is a MODEL-STATE compatibility surface: a checkpoint written
under one hot set pairs every hot row with a specific id, so resuming under
a different artifact would silently scramble the head.  ``hot_ids_digest``
fingerprints the artifact for the checkpoint ``stamps`` sidecar
(``train/checkpoint.py``), which refuses such resumes loudly.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Mapping

import numpy as np

__all__ = [
    "FORMAT_VERSION",
    "hot_ids_from_counts",
    "write_hot_ids",
    "load_hot_ids",
    "hot_ids_digest",
]

# Artifact schema version; bump on incompatible layout changes so a loader
# never silently misreads an old file.
FORMAT_VERSION = 1

_FILENAME = "hot_ids.json"


def hot_ids_from_counts(
    counts: np.ndarray, *, hot_vocab: int, hot_fraction: float = 0.9
) -> np.ndarray:
    """The hot-id set of one table from its per-id lookup counts
    (``counts[i]`` = lookups of id ``i``): the SMALLEST count-ranked prefix
    whose mass reaches ``hot_fraction`` of the total, capped at
    ``hot_vocab`` ids.  Ties break toward lower ids (stable argsort on
    negated counts), so ETLs that already assign ids by descending
    frequency (the Criteo recipe) produce contiguous ``[0, K)`` prefixes —
    which the collection remaps with a compare instead of a searchsorted.
    Returns the hot ids SORTED ascending (int32).  A table whose whole
    vocab fits under the cap is fully hot (every id in the set) regardless
    of mass — its cold side would be empty anyway.
    """
    counts = np.asarray(counts)
    v = counts.shape[0]
    if hot_vocab <= 0:
        raise ValueError(f"hot_vocab must be positive, got {hot_vocab}")
    if v <= hot_vocab:
        return np.arange(v, dtype=np.int32)
    order = np.argsort(-counts, kind="stable")
    total = float(counts.sum())
    if total <= 0:
        k = hot_vocab  # no mass observed: take the cap (arbitrary but valid)
    else:
        mass = np.cumsum(counts[order]) / total
        k = int(np.searchsorted(mass, hot_fraction) + 1)
        k = min(k, hot_vocab)
    return np.sort(order[:k]).astype(np.int32)


def write_hot_ids(
    data_dir: str | Path,
    per_table: Mapping[str, np.ndarray],
    *,
    hot_vocab: int,
    hot_fraction: float,
    coverage: Mapping[str, float] | None = None,
) -> Path:
    """Persist the artifact next to the parquet shards / size_map.json.
    ``per_table`` keys are the categorical COLUMN names (the feature names
    the trainer's embedding specs use); values are sorted id arrays from
    :func:`hot_ids_from_counts`.  ``coverage`` optionally records each
    table's achieved lookup-mass fraction (diagnostics only)."""
    data_dir = Path(data_dir)
    payload = {
        "format_version": FORMAT_VERSION,
        "hot_vocab": int(hot_vocab),
        "hot_fraction": float(hot_fraction),
        "tables": {
            name: np.asarray(ids, dtype=np.int64).tolist()
            for name, ids in per_table.items()
        },
    }
    if coverage is not None:
        payload["coverage"] = {k: float(c) for k, c in coverage.items()}
    path = data_dir / _FILENAME
    path.write_text(json.dumps(payload))
    return path


def load_hot_ids(data_dir: str | Path) -> dict[str, np.ndarray] | None:
    """Read the artifact back as ``{column: sorted int32 ids}``; ``None``
    when ``data_dir`` carries no artifact (hot/cold then cannot build —
    the trainer raises with re-run-preprocessing guidance)."""
    path = Path(data_dir) / _FILENAME
    if not path.exists():
        return None
    payload = json.loads(path.read_text())
    version = payload.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError(
            f"{path} has hot-id artifact format_version {version!r}, this "
            f"build reads {FORMAT_VERSION}.  Re-run preprocessing to "
            "regenerate the artifact."
        )
    out = {}
    for name, ids in payload["tables"].items():
        arr = np.asarray(ids, dtype=np.int32)
        if arr.ndim != 1 or (arr.size and (np.any(np.diff(arr) <= 0)
                                           or arr[0] < 0)):
            raise ValueError(
                f"{path}: table {name!r} hot ids must be sorted, unique and "
                "non-negative — the file is corrupt; re-run preprocessing."
            )
        out[name] = arr
    return out


def hot_ids_digest(per_table: Mapping[str, np.ndarray]) -> dict[str, str]:
    """Per-table fingerprint of the hot sets for the checkpoint ``stamps``
    sidecar: sha256 over the sorted int64 id bytes, truncated to 16 hex
    chars (collision-safe at artifact scale, short enough to read in an
    error message)."""
    return {
        name: hashlib.sha256(
            np.asarray(ids, dtype=np.int64).tobytes()
        ).hexdigest()[:16]
        for name, ids in sorted(per_table.items())
    }
