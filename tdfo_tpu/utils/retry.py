"""Retryable I/O: exponential backoff + jitter, bounded attempts, JSONL log.

The reference stacks assume I/O never fails mid-run (orbax writes, parquet
opens, TFRecord scans all raise straight through and kill the job).  On
preemptible TPU fleets the common failures are transient — GCS 5xx, NFS
staleness, a checkpoint write racing a preemption — and a bounded retry with
backoff is the difference between "training survived" and "8 hours lost".

Every failure (retried or terminal) is appended to an in-memory ring and,
when :func:`set_failure_log` configured a path, to a JSONL file — the same
observability convention as the trainer's ``metrics.jsonl``.

The deterministic fault-injection harness (``tdfo_tpu/utils/faults.py``)
hooks in here: when a ``fail_io_nth`` fault is armed, the Nth call protected
by :func:`retry_call` raises an injected ``OSError`` on its first attempt,
proving the retry path end-to-end in tests without real storage faults.
"""

from __future__ import annotations

import collections
import functools
import json
import random
import time
from pathlib import Path
from typing import Any, Callable, Iterable

__all__ = ["backoff_delay", "retry_call", "retryable", "set_failure_log",
           "recent_failures"]

# last N failure records, observable by tests and post-mortems even when no
# log file is configured
_RECENT: collections.deque = collections.deque(maxlen=256)
_LOG_PATH: Path | None = None
_ROTATE_BYTES: int = 0


def set_failure_log(path: str | Path | None, *, rotate_bytes: int = 0) -> None:
    """Route failure records to a JSONL file (``None`` disables).  The
    trainer points this at ``<log_dir>/retries.jsonl`` on process 0.
    ``rotate_bytes`` > 0 retires the file to ``retries.jsonl.1`` once it
    reaches that size (``[telemetry] log_rotate_bytes``) so a long-running
    online loop cannot fill the disk with retry diagnostics."""
    global _LOG_PATH, _ROTATE_BYTES
    _LOG_PATH = Path(path) if path is not None else None
    _ROTATE_BYTES = int(rotate_bytes)


def recent_failures() -> list[dict[str, Any]]:
    """The in-memory ring of recent failure records (newest last)."""
    return list(_RECENT)


def _record(rec: dict[str, Any]) -> None:
    _RECENT.append(rec)
    if _LOG_PATH is not None:
        try:
            _LOG_PATH.parent.mkdir(parents=True, exist_ok=True)
            with open(_LOG_PATH, "a") as f:
                f.write(json.dumps(rec) + "\n")
            from tdfo_tpu.utils.logrotate import maybe_rotate_path

            maybe_rotate_path(_LOG_PATH, _ROTATE_BYTES)
        except OSError:
            pass  # the failure log must never turn a retry into a crash


def backoff_delay(
    attempt: int,
    *,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
) -> float:
    """Jittered exponential backoff: ``base_delay * 2**attempt`` capped at
    ``max_delay``, then spread by up to ``jitter`` fraction (full-jitter-lite;
    the cap applies BEFORE jitter, so the worst case is
    ``max_delay * (1 + jitter)``).  ``rng`` is injectable so tests pin the
    draw; ``attempt`` is 0-based.  The single backoff law for the repo —
    checkpoint I/O and serving bundle loads both go through here via
    :func:`retry_call`."""
    if attempt < 0:
        raise ValueError(f"attempt must be >= 0, got {attempt}")
    delay = min(base_delay * (2 ** attempt), max_delay)
    return delay * (1.0 + jitter * (rng or random.Random()).random())


def retry_call(
    fn: Callable,
    *args: Any,
    description: str,
    attempts: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    retry_on: tuple[type[BaseException], ...] | Iterable[type[BaseException]] = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    **kwargs: Any,
):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, back off
    exponentially (``base_delay * 2**attempt``, capped at ``max_delay``, plus
    up to ``jitter`` fraction of random spread) and try again, at most
    ``attempts`` times total.  The final failure re-raises.

    Every failed attempt appends a JSONL record ``{time, description,
    attempt, attempts, error, delay}`` (see :func:`set_failure_log`).

    ``sleep``/``rng`` are injectable for deterministic tests.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    retry_on = tuple(retry_on)
    rng = rng or random.Random()

    from tdfo_tpu.utils import faults

    for attempt in range(attempts):
        try:
            inj = faults.active()
            if inj is not None:
                inj.io_op(description)  # may raise an injected OSError
            return fn(*args, **kwargs)
        except retry_on as e:
            final = attempt == attempts - 1
            delay = 0.0
            if not final:
                delay = backoff_delay(attempt, base_delay=base_delay,
                                      max_delay=max_delay, jitter=jitter,
                                      rng=rng)
            _record({
                "time": time.time(),
                "description": description,
                "attempt": attempt + 1,
                "attempts": attempts,
                "error": f"{type(e).__name__}: {e}",
                "delay": round(delay, 4),
                "final": final,
            })
            if final:
                raise
            sleep(delay)


def retryable(**retry_kwargs: Any) -> Callable:
    """Decorator form of :func:`retry_call`.  ``description`` defaults to the
    wrapped function's qualified name."""

    def deco(fn: Callable) -> Callable:
        kw = dict(retry_kwargs)
        kw.setdefault("description", getattr(fn, "__qualname__", repr(fn)))

        @functools.wraps(fn)
        def wrapped(*args: Any, **kwargs: Any):
            return retry_call(fn, *args, **kw, **kwargs)

        return wrapped

    return deco
