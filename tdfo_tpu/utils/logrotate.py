"""Size-based rotation for the run's append-only JSONL sinks.

A long-running online loop (serve -> retrain -> swap, ``train/online.py``)
writes ``metrics.jsonl`` and ``retries.jsonl`` forever; without a cap they
fill the disk.  Rotation here is deliberately minimal and crash-safe: the
current file is CLOSED (every record complete — the writers flush per line)
and atomically renamed to ``<name>.1``, replacing the previous overflow, and
a fresh file continues under the original name.  A crash at any byte leaves
either the old complete file or the renamed complete file — never a torn
one.  One generation of history is the contract (these are diagnostics
sinks, not durable state; durable state lives in checkpoints and bundles).

:func:`rotate_path` is a sanctioned rename site in
``tests/test_quality.py``'s bare-rename rule: the rename operates on a
closed, complete file, so the fsync-file + fsync-dir discipline of
``serve/swap.py``'s helpers (which protect half-WRITTEN payloads) adds
nothing here.

The request log is NOT rotated here — ``data/replay.py``'s ``RequestLog``
owns its segment chain, which must seal digests rather than discard.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import IO

__all__ = ["rotate_path", "maybe_rotate_path", "maybe_rotate_file"]


def rotate_path(path: Path) -> None:
    """Atomically retire a closed, complete JSONL file to ``<name>.1``."""
    path = Path(path)
    os.replace(path, path.with_name(path.name + ".1"))


def maybe_rotate_path(path: str | Path, rotate_bytes: int) -> bool:
    """Rotate a closed-between-appends sink (the ``retries.jsonl`` shape)
    once it reaches ``rotate_bytes``.  Returns whether it rotated."""
    if not rotate_bytes:
        return False
    path = Path(path)
    try:
        if path.stat().st_size < rotate_bytes:
            return False
    except OSError:
        return False
    rotate_path(path)
    return True


def maybe_rotate_file(f: IO[str], path: str | Path, rotate_bytes: int) -> IO[str]:
    """Rotate an open append handle (the ``metrics.jsonl`` shape) once its
    write position reaches ``rotate_bytes``.  Returns the handle to keep
    writing to — the original, or a fresh one after rotation."""
    if not rotate_bytes or f.tell() < rotate_bytes:
        return f
    f.close()
    rotate_path(Path(path))
    return open(path, "a")
