"""TensorBoard-compatible scalar event writer — TF-free.

Reference parity: the PS recipe ships a ``tf.keras.callbacks.TensorBoard``
callback (``tensorflow2/train_ps.py:154``); here the capability is
framework-wide — ``MetricLogger`` mirrors every scalar it logs into a
``tfevents`` file when the ``tensorboard`` config knob is on, so
``tensorboard --logdir <checkpoint_dir>`` shows train/eval curves for any
model family and regime.

The wire format is two small pieces this repo already implements for
TFRecord (``tdfo_tpu/data/tfrecord.py``): protobuf primitives (varints +
length-delimited fields) and the length/masked-crc32c record framing —
an Event proto is just::

    Event { double wall_time = 1; int64 step = 2;
            string file_version = 3;     # first record only
            Summary summary = 5; }
    Summary { repeated Value value = 1; }
    Value   { string tag = 1; float simple_value = 2;
              HistogramProto histo = 5; }
    HistogramProto { double min = 1; double max = 2; double num = 3;
                     double sum = 4; double sum_squares = 5;
                     repeated double bucket_limit = 6 [packed];
                     repeated double bucket = 7 [packed]; }

Cross-validated against TensorFlow's own ``summary_iterator`` in
``tests/test_tensorboard.py`` (TF happens to be in the test image; the
framework itself never imports it).
"""

from __future__ import annotations

import socket
import struct
import time
from pathlib import Path

import numpy as np

from tdfo_tpu.data.tfrecord import _ld as _bytes_field
from tdfo_tpu.data.tfrecord import _masked_crc, _varint

__all__ = ["TBScalarWriter"]


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _double_field(num: int, v: float) -> bytes:
    return _field(num, 1) + struct.pack("<d", v)


def _float_field(num: int, v: float) -> bytes:
    return _field(num, 5) + struct.pack("<f", v)


def _varint_field(num: int, v: int) -> bytes:
    return _field(num, 0) + _varint(v & (2**64 - 1))  # int64 two's complement


def _packed_doubles(num: int, vals) -> bytes:
    return _bytes_field(num, b"".join(struct.pack("<d", float(v))
                                      for v in vals))


def _histogram_proto(values: np.ndarray, bins: int) -> bytes:
    counts, edges = np.histogram(values, bins=bins)
    # bucket_limit[i] is bucket i's RIGHT edge (TB's HistogramProto
    # convention); min/max/num/sum/sum_squares feed the distribution chart
    return (_double_field(1, float(values.min()))
            + _double_field(2, float(values.max()))
            + _double_field(3, float(values.size))
            + _double_field(4, float(values.sum()))
            + _double_field(5, float((values * values).sum()))
            + _packed_doubles(6, edges[1:])
            + _packed_doubles(7, counts))


def _event(wall_time: float, *, step: int | None = None,
           file_version: str | None = None,
           scalars: dict[str, float] | None = None) -> bytes:
    out = _double_field(1, wall_time)
    if step is not None:
        out += _varint_field(2, step)
    if file_version is not None:
        out += _bytes_field(3, file_version.encode())
    if scalars:
        summary = b"".join(
            _bytes_field(1, _bytes_field(1, tag.encode())
                         + _float_field(2, float(v)))
            for tag, v in scalars.items()
        )
        out += _bytes_field(5, summary)
    return out


class TBScalarWriter:
    """Append scalar events to ``events.out.tfevents.<ts>.<host>``."""

    def __init__(self, log_dir: str | Path):
        log_dir = Path(log_dir)
        log_dir.mkdir(parents=True, exist_ok=True)
        name = f"events.out.tfevents.{time.time():.6f}.{socket.gethostname()}"
        self._f = open(log_dir / name, "ab")
        self._write(_event(time.time(), file_version="brain.Event:2"))

    def _write(self, payload: bytes) -> None:
        hdr = struct.pack("<Q", len(payload))
        self._f.write(hdr)
        self._f.write(struct.pack("<I", _masked_crc(hdr)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def scalars(self, step: int, values: dict[str, float],
                wall_time: float | None = None) -> None:
        if not values:
            return
        # negative steps (the bert4rec pre-training validation at epoch -1)
        # encode fine as two's-complement int64 and keep the untrained
        # baseline point distinct from epoch 0
        self._write(_event(wall_time if wall_time is not None else time.time(),
                           step=int(step), scalars=values))

    def histogram(self, step: int, tag: str, values,
                  wall_time: float | None = None, bins: int = 30) -> None:
        """One histogram summary (grad/param norm distributions from the
        telemetry counter registry).  Cross-validated against TF's
        ``summary_iterator`` like the scalar path."""
        v = np.asarray(values, np.float64).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            return
        value = (_bytes_field(1, tag.encode())
                 + _bytes_field(5, _histogram_proto(v, bins)))
        payload = (_double_field(1, wall_time if wall_time is not None
                                 else time.time())
                   + _varint_field(2, int(step))
                   + _bytes_field(5, _bytes_field(1, value)))
        self._write(payload)

    def close(self) -> None:
        self._f.close()
