"""Parameter summaries — ``model.tabulate`` equivalent.

The reference prints a layer table via ``flax`` (``_visualize_model_layers``,
``jax-flax/models.py:154-155``).  Here the same capability works for ANY
param pytree (flax params, sparse-regime dense params, embedding tables —
including fat-row storage, where the array carries optimizer moments and the
true parameter count comes from the table spec).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np

__all__ = ["param_summary", "tabulate_model"]


def _rows_from_tree(params: Any, prefix: str = "") -> list[tuple[str, tuple, str, int]]:
    rows = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = prefix + "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                                 for k in path)
        rows.append((name, tuple(leaf.shape), str(leaf.dtype), int(np.prod(leaf.shape) or 1)))
    return rows


def param_summary(
    params: Any,
    tables: Mapping[str, jax.Array] | None = None,
    coll=None,
    title: str = "model parameters",
) -> str:
    """Render a parameter table (name, shape, dtype, count) plus totals.

    ``tables``/``coll``: the sparse regime's embedding arrays and their
    ``ShardedEmbeddingCollection`` — fat-row arrays ([V, T, 128] holding
    table|mu|nu) are reported with their TRUE parameter count (vocab x dim
    from the spec), with the storage shape shown alongside.
    """
    rows = _rows_from_tree(params)
    if tables is not None:
        for name, arr in sorted(tables.items()):
            if coll is not None and arr.ndim == 3:  # fat-line storage
                d = coll.array_embedding_dim(name)
                r = coll.fat_layout_for(name).r
                count = arr.shape[0] * r * d
                rows.append((f"tables/{name} (fat {tuple(arr.shape)} incl. opt state)",
                             (arr.shape[0] * r, d), str(arr.dtype), count))
            else:
                rows.append((f"tables/{name}", tuple(arr.shape), str(arr.dtype),
                             int(np.prod(arr.shape) or 1)))
    w = max((len(r[0]) for r in rows), default=10) + 2
    lines = [title, "-" * len(title)]
    for name, shape, dtype, count in rows:
        lines.append(f"{name:<{w}} {str(shape):<20} {dtype:<10} {count:>14,}")
    total = sum(r[3] for r in rows)
    lines.append("-" * len(title))
    lines.append(f"{'total':<{w}} {'':<20} {'':<10} {total:>14,}")
    return "\n".join(lines)


def tabulate_model(model, *init_args, **init_kwargs) -> str:
    """flax ``Module.tabulate`` passthrough (jax-flax/models.py:154-155
    parity) for callers holding a flax module + dummy inputs."""
    return model.tabulate(jax.random.key(0), *init_args, **init_kwargs)
