"""Deterministic fault injection — the testable half of the fault-tolerance story.

"Training survives preemption by construction" is only a claim until a test
can crash a real process mid-epoch and assert the resumed run's metrics are
bit-identical to an uninterrupted one.  This module turns the ``[faults]``
config section into deterministic, step-keyed fault triggers the trainer and
the retry layer consult:

  * ``kill_at_step = N``  — hard-kill the process (``os._exit(17)``) when
    global data step N completes.  With a ``checkpoint_dir``, the kill fires
    AT MOST ONCE per directory (a ``faults_kill.marker`` sentinel records the
    firing), so "restart the same command" converges instead of crash-looping
    — the semantics of a one-off preemption.
  * ``nan_at_step = N``  — poison the step-N host batch (first float column
    -> NaN) so the real jitted step produces a non-finite loss and corrupt
    gradients, exercising the trainer's rollback guard on the true data path.
  * ``fail_io_nth = N``  — the Nth I/O operation protected by
    ``tdfo_tpu/utils/retry.py`` raises an injected ``OSError`` (once); the
    retry's next attempt proceeds, proving backoff+retry end-to-end.
  * ``stall_at_step = N`` + ``stall_seconds = S``  — the training loop
    sleeps S wall-clock seconds after global data step N completes (once),
    simulating a hung data source / wedged collective so the stall watchdog
    (``tdfo_tpu/obs/watchdog.py``) is testable end-to-end.  State evolution
    is untouched — the stall is pure host-side latency.

The serving-side triggers (consulted by ``tdfo_tpu/serve/swap.py`` and the
MicroBatcher) key on OPERATION counts rather than data steps:

  * ``corrupt_delta_nth = N``  — the Nth delta bundle the swap store reads
    has its payload bit-flipped in memory (once), so the digest-verification
    + quarantine + fall-back-to-last-good path runs against a REAL corrupt
    payload, not a mocked error.
  * ``slow_score_ms = M``  — every shipped scoring batch sleeps M ms on the
    host, a deterministic wedged-scorer stand-in driving the serving
    heartbeat/stall path.
  * ``kill_during_swap = N``  — hard-kill (``os._exit(17)``) in the middle
    of the Nth hot-swap apply, AFTER the composed bundle is staged but
    BEFORE it is published — the canonical half-applied state the restart
    recovery must survive.  One-shot per workdir via a
    ``faults_swap_kill.marker`` sentinel, like ``kill_at_step``.

The replay / online-loop triggers (consulted by ``tdfo_tpu/data/replay.py``
and ``tdfo_tpu/train/online.py``) exercise the request-log tail:

  * ``truncate_log_at_byte = N``  — once, after the request-log writer's
    append pushes the active segment to >= N bytes, the file is truncated
    back to exactly N — a torn tail mid-record, the canonical crashed-writer
    artifact the reader's last-good-offset recovery must survive.
  * ``dup_record_nth = N``  — the Nth appended request record is written
    twice (same ``seq``, once), so the reader's seq-dedup path runs against
    a REAL duplicate, the retried-append artifact of an at-least-once writer.
  * ``corrupt_record_nth = N``  — the Nth appended request record has its
    payload bytes flipped before the newline (once), driving the
    per-record quarantine (``max_bad_records``) on real garbage.
  * ``kill_during_replay = N``  — hard-kill when the replay consumer
    commits its Nth good record; one-shot per workdir via a
    ``faults_replay_kill.marker`` sentinel.
  * ``kill_between_stages = N``  — hard-kill at the Nth stage boundary the
    online supervisor crosses (replay -> train -> checkpoint -> export ->
    publish -> swap); one-shot per workdir via a
    ``faults_stage_kill.marker`` sentinel.  Together with
    ``kill_during_replay`` and ``kill_during_swap`` this covers every edge
    of the serve -> retrain -> delta-export -> swap cycle.

The canary-gatekeeper triggers (consulted by ``tdfo_tpu/train/online.py``
and ``tdfo_tpu/serve/fleet.py``) drive the fleet rollout state machine:

  * ``corrupt_candidate = N``  — the Nth candidate delta the gated
    supervisor exports has its ON-DISK payload bit-flipped (once per
    process), so the pre-publish shadow gate verifies real corruption and
    the re-export repair path runs — the exporter-side twin of
    ``corrupt_delta_nth``.
  * ``regress_auc_at_cycle = N``  — the candidate of gated cycle N serves
    garbage on the replicas that load it (the fleet replaces its logits
    with a feature heuristic: training/serving skew).  Keyed on the
    DURABLE cycle number from the verdict checkpoint, so a killed-and-
    restarted run re-injects the regression at exactly the same cycle.
  * ``kill_during_canary = N``  — hard-kill at the start of the Nth canary
    watch round, after the candidate reached the canary replicas but
    before any verdict is durable; one-shot per workdir via a
    ``faults_canary_kill.marker`` sentinel.  The restart must redo the
    whole cycle from the last verdict checkpoint and converge to the
    uninterrupted run's fleet state.
  * ``kill_replica_nth = K``  — replica K-1 (1-based K) drops dead at the
    first canary watch round it participates in.  An in-process soft kill
    (the replica stops syncing/serving; NO ``os._exit`` — the supervisor
    process survives), re-fired deterministically on every restart so
    killed and uninterrupted lineages see the same fleet membership.
  * ``kill_replica_signal = K``  — replica K-1 (1-based K) gets a REAL
    ``SIGKILL`` delivered to its child pid at the first canary watch round
    (process fleets only, ``[serving] fleet_mode = "process"``): the
    supervisor must detect the death, respawn the lineage with backoff,
    and the respawn must re-follow CURRENT/CANARY by (version, digest)
    with a seq-contiguous request log.  The in-process flag twin is
    ``kill_replica_nth`` — spoofed-mesh unit tests use the flag (cheap,
    membership stays degraded), OS-boundary drills use the signal
    (``tests/test_fleet_process.py``); the soft-kill path is exercised by
    ``tests/test_fleet.py``.  Fires once per process, no marker — the
    respawn recovers membership, so a restarted supervisor re-firing the
    kill converges to the same fleet state.
  * ``slow_canary_at_cycle = N`` (+ ``slow_score_ms = M``)  — the candidate
    of gated cycle N scores slowly ON THE REPLICAS THAT LOAD IT (the fleet
    wraps that digest's scorer in an M-ms host sleep): a latency
    regression the AUC gate cannot see, driving the
    ``[online] max_p99_regression_ms`` verdict term.  Pure compare on the
    DURABLE cycle number, like ``regress_auc_at_cycle``, so restarted
    redos re-inject identically.  The stable cohort is untouched.

All training triggers key on run-global DATA position (batches consumed),
which is monotone across rollbacks and resumes — ``state.step`` is not
(rollback rewinds it).  Zero disables a trigger; a process with no faults
configured pays a single ``is None`` check per site.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

__all__ = ["FaultSpec", "FaultInjector", "configure", "active", "KILL_EXIT_CODE"]

KILL_EXIT_CODE = 17  # distinguishes an injected kill from real crashes
_MARKER = "faults_kill.marker"
_SWAP_MARKER = "faults_swap_kill.marker"
_REPLAY_MARKER = "faults_replay_kill.marker"
_STAGE_MARKER = "faults_stage_kill.marker"
_CANARY_MARKER = "faults_canary_kill.marker"


@dataclass(frozen=True)
class FaultSpec:
    """The ``[faults]`` config section.  All steps are 1-based run-global
    data steps; serving triggers are 1-based operation counts; 0 disables."""

    kill_at_step: int = 0
    nan_at_step: int = 0
    fail_io_nth: int = 0
    stall_at_step: int = 0
    stall_seconds: float = 0.0
    corrupt_delta_nth: int = 0
    slow_score_ms: float = 0.0
    kill_during_swap: int = 0
    truncate_log_at_byte: int = 0
    dup_record_nth: int = 0
    corrupt_record_nth: int = 0
    kill_during_replay: int = 0
    kill_between_stages: int = 0
    corrupt_candidate: int = 0
    regress_auc_at_cycle: int = 0
    kill_during_canary: int = 0
    kill_replica_nth: int = 0
    kill_replica_signal: int = 0
    slow_canary_at_cycle: int = 0

    def __post_init__(self) -> None:
        for name in ("kill_at_step", "nan_at_step", "fail_io_nth",
                     "stall_at_step", "stall_seconds", "corrupt_delta_nth",
                     "slow_score_ms", "kill_during_swap",
                     "truncate_log_at_byte", "dup_record_nth",
                     "corrupt_record_nth", "kill_during_replay",
                     "kill_between_stages", "corrupt_candidate",
                     "regress_auc_at_cycle", "kill_during_canary",
                     "kill_replica_nth", "kill_replica_signal",
                     "slow_canary_at_cycle"):
            if getattr(self, name) < 0:
                raise ValueError(f"faults.{name} must be >= 0 (0 = disabled)")

    def any(self) -> bool:
        return bool(self.kill_at_step or self.nan_at_step
                    or self.fail_io_nth or self.stall_at_step
                    or self.corrupt_delta_nth or self.slow_score_ms
                    or self.kill_during_swap or self.truncate_log_at_byte
                    or self.dup_record_nth or self.corrupt_record_nth
                    or self.kill_during_replay or self.kill_between_stages
                    or self.corrupt_candidate or self.regress_auc_at_cycle
                    or self.kill_during_canary or self.kill_replica_nth
                    or self.kill_replica_signal
                    or self.slow_canary_at_cycle)


class FaultInjector:
    """Stateful trigger evaluation for one training process."""

    def __init__(self, spec: FaultSpec, workdir: str | Path | None = None):
        self.spec = spec
        self.workdir = Path(workdir) if workdir else None
        self._io_count = 0
        self._io_fired = False
        self._stall_fired = False
        self._delta_count = 0
        self._delta_fired = False
        self._swap_count = 0
        self._truncate_fired = False
        self._dup_count = 0
        self._dup_fired = False
        self._rec_corrupt_count = 0
        self._rec_corrupt_fired = False
        self._stage_count = 0
        self._candidate_count = 0
        self._candidate_fired = False
        self._canary_count = 0
        self._replica_kill_fired = False
        self._replica_sigkill_fired = False

    # ------------------------------------------------------------- kill

    def kill_due(self, global_step: int) -> bool:
        """True when the injected preemption should fire at this step.
        Consults (and honours) the one-shot marker; does NOT exit."""
        if not self.spec.kill_at_step or global_step < self.spec.kill_at_step:
            return False
        if self.workdir is not None and (self.workdir / _MARKER).exists():
            return False  # already preempted once in this checkpoint lineage
        return True

    def maybe_kill(self, global_step: int) -> None:
        """Hard-exit (``os._exit``, no cleanup — a real preemption gives no
        cleanup either) when the kill trigger is due."""
        if not self.kill_due(global_step):
            return
        if self.workdir is not None:
            self.workdir.mkdir(parents=True, exist_ok=True)
            (self.workdir / _MARKER).write_text(
                f"killed at global step {global_step} at {time.time()}\n"
            )
        print(f"[faults] injected kill at global step {global_step}",
              flush=True)
        os._exit(KILL_EXIT_CODE)

    # -------------------------------------------------------------- nan

    def nan_due(self, global_step: int) -> bool:
        return bool(self.spec.nan_at_step) and global_step == self.spec.nan_at_step

    def poison_batch(self, batch: dict[str, np.ndarray],
                     global_step: int) -> dict[str, np.ndarray]:
        """Overwrite the first float-typed column with NaN (host-side, before
        device transfer) so the REAL step computes a non-finite loss — the
        corrupted-shard / overflow failure mode, injected deterministically."""
        if not self.nan_due(global_step):
            return batch
        for k, v in batch.items():
            if np.issubdtype(np.asarray(v).dtype, np.floating):
                out = dict(batch)
                out[k] = np.full_like(v, np.nan)
                print(f"[faults] injected NaN into column {k!r} at global "
                      f"step {global_step}", flush=True)
                return out
        raise ValueError(
            "faults.nan_at_step needs a float-typed batch column to poison; "
            "this workload ships integer-only batches"
        )

    # ------------------------------------------------------------- stall

    def maybe_stall(self, global_step: int) -> None:
        """Sleep ``stall_seconds`` once when the stall trigger is due — a
        deterministic stand-in for a hung shard read or wedged collective.
        Purely host-side: device state and the data cursor are untouched."""
        if (not self.spec.stall_at_step or self._stall_fired
                or global_step < self.spec.stall_at_step):
            return
        self._stall_fired = True
        print(f"[faults] injected {self.spec.stall_seconds:.1f}s stall at "
              f"global step {global_step}", flush=True)
        time.sleep(self.spec.stall_seconds)

    # ----------------------------------------------------------- serving

    def corrupt_delta_due(self) -> bool:
        """Called by the swap store once per delta payload it reads.  True
        exactly once, on the configured Nth read — the caller then bit-flips
        the in-memory payload so digest verification sees REAL corruption."""
        if not self.spec.corrupt_delta_nth or self._delta_fired:
            return False
        self._delta_count += 1
        if self._delta_count == self.spec.corrupt_delta_nth:
            self._delta_fired = True
            print(f"[faults] corrupting delta read #{self._delta_count}",
                  flush=True)
            return True
        return False

    def maybe_slow_score(self) -> None:
        """Sleep ``slow_score_ms`` on every shipped scoring batch — a
        deterministic wedged-scorer stand-in for the serving heartbeat.
        When ``slow_canary_at_cycle`` is ALSO set the knob is claimed by
        the digest-keyed canary slowdown (:meth:`slow_score_sleep` via the
        fleet's slow-scorer wrap) and this fleet-wide path stays fast —
        the latency regression must be differential or the p99 verdict
        term has nothing to compare."""
        if self.spec.slow_score_ms and not self.spec.slow_canary_at_cycle:
            time.sleep(self.spec.slow_score_ms / 1000.0)

    def slow_score_sleep(self) -> None:
        """Unconditional ``slow_score_ms`` sleep — called only from the
        fleet's digest-keyed slow-scorer wrap (``slow_canary_at_cycle``),
        which already decided THIS scorer is the slow one."""
        if self.spec.slow_score_ms:
            time.sleep(self.spec.slow_score_ms / 1000.0)

    def swap_kill_due(self) -> bool:
        """True when the mid-swap kill should fire on THIS apply (counts
        applies; honours the one-shot marker); does NOT exit."""
        if not self.spec.kill_during_swap:
            return False
        if self.workdir is not None and (self.workdir / _SWAP_MARKER).exists():
            return False
        self._swap_count += 1
        return self._swap_count == self.spec.kill_during_swap

    def maybe_kill_swap(self) -> None:
        """Hard-exit mid-apply (staged, not yet published) when due — the
        restart must recover to the last fully-verified version."""
        if not self.swap_kill_due():
            return
        if self.workdir is not None:
            self.workdir.mkdir(parents=True, exist_ok=True)
            (self.workdir / _SWAP_MARKER).write_text(
                f"killed during swap apply #{self._swap_count} at {time.time()}\n"
            )
        print(f"[faults] injected kill during swap apply #{self._swap_count}",
              flush=True)
        os._exit(KILL_EXIT_CODE)

    # ------------------------------------------------------------ replay

    def truncate_log_due(self, segment_bytes: int) -> bool:
        """Called by the request-log writer after each flushed append with
        the active segment's current size.  True exactly once, the first
        time the size reaches ``truncate_log_at_byte`` — the caller then
        truncates the file back to that byte, tearing the tail record."""
        if (not self.spec.truncate_log_at_byte or self._truncate_fired
                or segment_bytes < self.spec.truncate_log_at_byte):
            return False
        self._truncate_fired = True
        print(f"[faults] truncating request log at byte "
              f"{self.spec.truncate_log_at_byte}", flush=True)
        return True

    def dup_record_due(self) -> bool:
        """Called by the request-log writer once per appended record.  True
        exactly once, on the configured Nth append — the caller then writes
        the same line (same seq) a second time."""
        if not self.spec.dup_record_nth or self._dup_fired:
            return False
        self._dup_count += 1
        if self._dup_count == self.spec.dup_record_nth:
            self._dup_fired = True
            print(f"[faults] duplicating request record #{self._dup_count}",
                  flush=True)
            return True
        return False

    def corrupt_record_due(self) -> bool:
        """Called by the request-log writer once per appended record.  True
        exactly once, on the configured Nth append — the caller then flips
        payload bytes so the reader sees real garbage on a sealed line."""
        if not self.spec.corrupt_record_nth or self._rec_corrupt_fired:
            return False
        self._rec_corrupt_count += 1
        if self._rec_corrupt_count == self.spec.corrupt_record_nth:
            self._rec_corrupt_fired = True
            print(f"[faults] corrupting request record "
                  f"#{self._rec_corrupt_count}", flush=True)
            return True
        return False

    def replay_kill_due(self, n_committed: int) -> bool:
        """True when the replay-commit kill should fire (``n_committed``
        good records committed so far); honours the one-shot marker."""
        if (not self.spec.kill_during_replay
                or n_committed < self.spec.kill_during_replay):
            return False
        if self.workdir is not None and (self.workdir / _REPLAY_MARKER).exists():
            return False
        return True

    def maybe_kill_replay(self, n_committed: int) -> None:
        """Hard-exit when the replay consumer commits its Nth good record —
        the restart must resume from the persisted cursor with no dup/loss."""
        if not self.replay_kill_due(n_committed):
            return
        if self.workdir is not None:
            self.workdir.mkdir(parents=True, exist_ok=True)
            (self.workdir / _REPLAY_MARKER).write_text(
                f"killed at replay record {n_committed} at {time.time()}\n"
            )
        print(f"[faults] injected kill at replay record {n_committed}",
              flush=True)
        os._exit(KILL_EXIT_CODE)

    def maybe_kill_stage(self, stage: str) -> None:
        """Hard-exit at the Nth online-supervisor stage boundary crossed
        (one-shot per workdir) — the named stage has NOT run yet, so the
        restart must redo it idempotently from the persisted cursors."""
        if not self.spec.kill_between_stages:
            return
        if self.workdir is not None and (self.workdir / _STAGE_MARKER).exists():
            return
        self._stage_count += 1
        if self._stage_count != self.spec.kill_between_stages:
            return
        if self.workdir is not None:
            self.workdir.mkdir(parents=True, exist_ok=True)
            (self.workdir / _STAGE_MARKER).write_text(
                f"killed before stage {stage!r} (boundary "
                f"#{self._stage_count}) at {time.time()}\n"
            )
        print(f"[faults] injected kill before stage {stage!r} (boundary "
              f"#{self._stage_count})", flush=True)
        os._exit(KILL_EXIT_CODE)

    # ------------------------------------------------------------- canary

    def corrupt_candidate_due(self) -> bool:
        """Called by the gated supervisor once per exported candidate delta.
        True exactly once, on the configured Nth export — the caller then
        bit-flips the ON-DISK payload so the shadow gate's digest check and
        the re-export repair path run against real corruption."""
        if not self.spec.corrupt_candidate or self._candidate_fired:
            return False
        self._candidate_count += 1
        if self._candidate_count == self.spec.corrupt_candidate:
            self._candidate_fired = True
            print(f"[faults] corrupting candidate export "
                  f"#{self._candidate_count}", flush=True)
            return True
        return False

    def auc_regress_due(self, cycle: int) -> bool:
        """True when the candidate of gated cycle ``cycle`` should serve
        garbage (training/serving skew).  Pure compare on the DURABLE cycle
        number — no process state, so a restarted redo of the same cycle
        re-injects the identical regression."""
        return bool(self.spec.regress_auc_at_cycle
                    and cycle == self.spec.regress_auc_at_cycle)

    def slow_canary_due(self, cycle: int) -> bool:
        """True when the candidate of gated cycle ``cycle`` should score
        slowly on the replicas that load it (``slow_score_ms`` per shipped
        batch) — the latency twin of ``auc_regress_due``: same pure compare
        on the durable cycle number, same restart determinism."""
        return bool(self.spec.slow_canary_at_cycle
                    and self.spec.slow_score_ms
                    and cycle == self.spec.slow_canary_at_cycle)

    def canary_kill_due(self, rnd: int) -> bool:
        """True when the mid-canary kill should fire on THIS watch round
        (counts rounds crossed; honours the one-shot marker); does NOT
        exit."""
        if not self.spec.kill_during_canary:
            return False
        if self.workdir is not None and (self.workdir / _CANARY_MARKER).exists():
            return False
        self._canary_count += 1
        return self._canary_count == self.spec.kill_during_canary

    def maybe_kill_canary(self, rnd: int) -> None:
        """Hard-exit at the start of a canary watch round — the candidate
        reached the canary replicas but no verdict is durable, so the
        restart must redo the cycle and converge to the uninterrupted
        run's verdict."""
        if not self.canary_kill_due(rnd):
            return
        if self.workdir is not None:
            self.workdir.mkdir(parents=True, exist_ok=True)
            (self.workdir / _CANARY_MARKER).write_text(
                f"killed during canary watch round {rnd} (boundary "
                f"#{self._canary_count}) at {time.time()}\n"
            )
        print(f"[faults] injected kill during canary watch round {rnd}",
              flush=True)
        os._exit(KILL_EXIT_CODE)

    def replica_kill_due(self) -> bool:
        """Called by the fleet at the start of each canary watch round.
        True exactly once per process — the fleet then marks replica
        ``kill_replica_nth - 1`` dead (soft kill, no exit).  No marker:
        the kill re-fires on restart so every lineage sees the same
        membership."""
        if not self.spec.kill_replica_nth or self._replica_kill_fired:
            return False
        self._replica_kill_fired = True
        print(f"[faults] soft-killing replica "
              f"{self.spec.kill_replica_nth - 1} at canary watch", flush=True)
        return True

    def replica_sigkill_due(self) -> bool:
        """Called by the PROCESS fleet at the start of each canary watch
        round.  True exactly once per process — the fleet then delivers a
        real ``SIGKILL`` to child ``kill_replica_signal - 1``'s pid and the
        supervisor's respawn path takes over.  No marker, like
        :meth:`replica_kill_due`: the respawn recovers membership, so a
        restarted supervisor re-firing the kill converges anyway."""
        if not self.spec.kill_replica_signal or self._replica_sigkill_fired:
            return False
        self._replica_sigkill_fired = True
        print(f"[faults] SIGKILLing replica process "
              f"{self.spec.kill_replica_signal - 1} at canary watch",
              flush=True)
        return True

    # --------------------------------------------------------------- io

    def io_op(self, description: str) -> None:
        """Called by ``retry_call`` before each protected attempt.  Raises an
        injected ``OSError`` exactly once, on the configured Nth operation."""
        if not self.spec.fail_io_nth or self._io_fired:
            return
        self._io_count += 1
        if self._io_count == self.spec.fail_io_nth:
            self._io_fired = True
            raise OSError(
                f"[faults] injected I/O failure on op #{self._io_count} "
                f"({description})"
            )


_ACTIVE: FaultInjector | None = None


def configure(spec: FaultSpec | None,
              workdir: str | Path | None = None) -> FaultInjector | None:
    """Install the process-global injector (``None`` / empty spec clears it).
    The Trainer calls this at construction, so each run re-arms from its own
    config and stale injectors never leak across tests."""
    global _ACTIVE
    _ACTIVE = (
        FaultInjector(spec, workdir) if spec is not None and spec.any() else None
    )
    return _ACTIVE


def active() -> FaultInjector | None:
    return _ACTIVE
